#!/usr/bin/env bash
# Run the task fleet on a Cloud TPU VM (single- or multi-host slice).
#
# Usage:
#   TPU_NAME=my-v5p-16 ZONE=us-east5-a launchers/tpu_vm_fleet.sh [config] [repeats]
#
# Every worker runs the same command; reval_tpu.parallel.distributed picks
# up the TPU runtime metadata and joins the jax.distributed mesh, so this
# one invocation covers the multi-host case (e.g. CodeLlama-70B on v5p-16).
#
# Off-TPU rigs (plain SSH clusters, CPU test fleets) have no runtime
# metadata: export REVAL_TPU_COORDINATOR=host0:port,
# REVAL_TPU_NUM_PROCESSES=N and a per-worker REVAL_TPU_PROCESS_ID
# instead — ensure_initialized() reads them before falling back to
# JAX's own cluster detection (tests/test_multihost.py drives this rig).
set -euo pipefail

: "${TPU_NAME:?set TPU_NAME to the TPU VM name}"
: "${ZONE:?set ZONE to the TPU VM zone}"
CONFIG="${1:-.eval_config}"
REPEATS="${2:-5}"
REPO_DIR="${REPO_DIR:-\$HOME/reval_tpu}"
# "global": one model sharded over every host's chips (70B-class);
# "replicate": a full engine per host with the prompt list sharded
MULTIHOST="${MULTIHOST:-global}"

gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "cd ${REPO_DIR} && python -m reval_tpu fleet -i ${CONFIG} --repeats ${REPEATS} --multihost ${MULTIHOST}"
