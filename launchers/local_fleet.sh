#!/usr/bin/env bash
# Full task fleet on the local TPU chip(s).
# Usage: launchers/local_fleet.sh [config_file] [repeats]
set -euo pipefail

CONFIG="${1:-.eval_config}"
REPEATS="${2:-5}"

cd "$(dirname "$0")/.."
exec python -m reval_tpu fleet -i "$CONFIG" --repeats "$REPEATS"
