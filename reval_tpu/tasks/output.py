"""Output task: complete ``assert f(x) == ??`` (reference
evaluation.py:908-1012).  One prompt per input pair (no per-line probes);
the verdict is whether the completed assertion executes cleanly in the
item's namespace, after the anti-cheat penalty screen.

Divergence from the reference (documented): for MBPP/MathQA the reference
filled the prompt's invocation slot with the call expression instead of the
``?? `` assert (evaluation.py:187-194 + 973-974), producing prompts without
a question; here the output prediction always goes in the prompt.  Pass
``reference_compat=True`` (config key) to restore the reference's prompts
byte-for-byte on those splits — required when comparing output-task
accuracies against reference-produced numbers.
"""

from __future__ import annotations

from ..prompting import build_prompt
from .answers import output_penalty, pad_output_answer, parse_output_answer
from .base import ProbeJob, TaskRunner

__all__ = ["OutputTask"]

CLASSEVAL_PRELUDE = "\n# Test code starts here. Only write the completed test code in your answer.\n"


class OutputTask(TaskRunner):
    name = "output"

    def __init__(self, *args, reference_compat: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self.reference_compat = bool(reference_compat)
        self._total = 0
        self._pass = 0

    @property
    def metrics(self) -> dict:
        return {"acc": self._pass / self._total if self._total else 0.0}

    # -- planning ----------------------------------------------------------
    def plan_function_pair(self, *, idx, fam, pair, space, entry, code, codelines,
                           sandbox, invocation, task_idx, gen_entry, jobs):
        _input = pair["output_pred"]
        shown = _input
        if self.reference_compat and fam in ("mbpp", "mathqa"):
            # reference prompts on these splits carry the bare call
            # expression, not the ??-assert (question-free, but what the
            # reference's committed accuracies were measured on)
            shown = invocation
        prompt = build_prompt("output", self.prompt_type, code=code, invocation="\n" + shown)
        jobs.append(ProbeJob(gen_entry=gen_entry, prompt=prompt,
                             context={"space": space, "_input": _input, "kind": "function"}))

    def plan_class_pair(self, *, idx, pair, test_cls, code, codelines, _input,
                        setup, gen_entry, jobs):
        prompt = build_prompt("output", self.prompt_type, code=test_cls.__doc__,
                              invocation=setup + CLASSEVAL_PRELUDE + _input)
        jobs.append(ProbeJob(gen_entry=gen_entry, prompt=prompt,
                             context={"test_cls": test_cls, "_input": _input, "kind": "class"}))

    # -- scoring -----------------------------------------------------------
    def score_job(self, job: ProbeJob, response: str) -> dict:
        ans = parse_output_answer(response, self.prompt_type)
        ans = pad_output_answer(ans, job.context["_input"])
        status = False
        if not output_penalty(ans, job.context["_input"]):
            if job.context["kind"] == "function":
                status = self._exec_function_answer(job, ans)
            else:
                status = self._exec_class_answer(job, ans)
        self._total += 1
        if status:
            self._pass += 1
        return {"generated": response, "pass": status}

    @staticmethod
    def _exec_function_answer(job: ProbeJob, ans: str) -> bool:
        try:
            job.context["space"].exec_driver(ans)
            return True
        except Exception:
            return False

    @staticmethod
    def _exec_class_answer(job: ProbeJob, ans: str) -> bool:
        test_cls = job.context["test_cls"]
        space = getattr(test_cls, "__reval_space__", None)
        if space is None:
            return False
        try:
            space.attach_output_predictor(ans, test_cls)
            obj = test_cls()
            if hasattr(obj, "setUp"):
                obj.setUp()
            obj.dreval_output_pred()
            return True
        except Exception:
            return False
