"""The DREval task suite: coverage, path, state, output + consistency."""

from .base import ProbeJob, ProbeTask, TaskRunner
from .consistency import ConsistencyScorer
from .coverage import CoverageTask
from .output import OutputTask
from .path import PathTask
from .results import ResultsStore
from .state import StateTask

TASKS = {
    "coverage": CoverageTask,
    "path": PathTask,
    "state": StateTask,
    "output": OutputTask,
}

__all__ = [
    "TASKS",
    "ConsistencyScorer",
    "CoverageTask",
    "OutputTask",
    "PathTask",
    "ProbeJob",
    "ProbeTask",
    "ResultsStore",
    "StateTask",
    "TaskRunner",
]
