"""State task: "Value and type of V after line L?" (reference
evaluation.py:610-906).  Ground truth comes from the variable interpreter
over the trace; the answer parser and type-aware equality live in
``answers.py``."""

from __future__ import annotations

import json

from ..dynamics import Nil
from .answers import parse_state_answer, state_answers_equal
from .base import ProbeJob, ProbeTask

__all__ = ["StateTask"]


class StateTask(ProbeTask):
    name = "state"
    uses_var = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._correct = 0
        self._total = 0

    @property
    def metrics(self) -> dict:
        return {"acc": self._correct / self._total if self._total else 0.0,
                "correct": self._correct, "total": self._total}

    def ground_truth(self, states, lineno0: int, var: str):
        return states.interpret_var(lineno0, var)

    # -- trace-of-thoughts -------------------------------------------------
    def tot_matches(self, job: ProbeJob, ans) -> bool:
        parsed = parse_state_answer(ans, "direct")
        return parsed != "ERROR" and state_answers_equal(parsed, job.expected)

    def tot_record(self, job: ProbeJob, ans, gen: str, error: str | None) -> dict:
        eq = False if error else self.tot_matches(job, ans)
        self._total += 1
        if eq:
            self._correct += 1
        record = {"generated": gen, "eq": eq, "line": job.lineno, "var": job.var,
                  "ans": ans if not error else error,
                  "actual": job.expected if job.expected is not Nil else "Nil",
                  "error": error}
        for key, value in record.items():
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                record[key] = f"STRINGIFIED, {value}"
        return record

    def probe_record(self, job: ProbeJob, response: str) -> dict:
        ans = parse_state_answer(response, self.prompt_type)
        actual = job.expected
        self._total += 1
        if ans == "ERROR":
            eq = False
        else:
            eq = state_answers_equal(ans, actual)
        if eq:
            self._correct += 1
        record = {"generated": response, "eq": eq, "line": job.lineno, "var": job.var,
                  "prompt": job.prompt, "ans": ans if ans is not Nil else "Nil",
                  "actual": actual if actual is not Nil else "Nil"}
        # values may be arbitrary Python objects; stringify what JSON can't hold
        for key, value in record.items():
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                record[key] = f"STRINGIFIED, {value}"
        return record
