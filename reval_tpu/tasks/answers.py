"""Answer post-processing: model text → task-typed answers, and equality.

Pure functions, golden-tested in tests/test_answers.py.  Semantics match
the reference postprocessors branch-for-branch (evaluation.py:263-290
coverage, 434-453 path, 684-770 state, 940-968 output, 645-682 state
equality) — these rules directly determine reported accuracies, so they are
part of the benchmark definition, not incidental code.
"""

from __future__ import annotations

import re
from pydoc import locate

import numpy as np

from ..dynamics import Nil

__all__ = [
    "strip_answer_tags",
    "parse_coverage_answer",
    "parse_path_answer",
    "path_answer_to_lines",
    "parse_state_answer",
    "state_answers_equal",
    "parse_output_answer",
    "pad_output_answer",
    "output_penalty",
]

COT_CLOSE = "[/THOUGHT]"


def strip_answer_tags(resp: str) -> str:
    """Cut the text between ``[ANSWER]`` and ``[/ANSWER]`` (tolerating a
    truncated closing tag, which local models emit when stop sequences
    misfire)."""
    idx = resp.find("[ANSWER]")
    if idx != -1:
        resp = resp[idx + len("[ANSWER]"):].strip()
    idx = resp.find("[/ANSWER]")
    if idx != -1:
        resp = resp[:idx].strip()
    idx = resp.find("[/ANSWER")
    if idx != -1:
        resp = resp[:idx].strip()
    return resp


def _cot_incomplete(resp: str, prompt_type: str) -> bool:
    """CoT generations that never closed their [THOUGHT] ran out of budget;
    they are scored as failures with task-specific sentinels."""
    return prompt_type == "cot" and COT_CLOSE not in resp


# -- coverage -------------------------------------------------------------
def parse_coverage_answer(resp: str, prompt_type: str = "direct") -> bool:
    """YES/NO from the first 3 characters of the stripped answer; anything
    empty or ambiguous scores NO."""
    ans = resp.upper().strip()
    if _cot_incomplete(ans, prompt_type):
        return False
    ans = strip_answer_tags(ans)
    if ans == "":
        return False
    head = ans[:3]
    has_yes = "YES" in head
    has_no = "NO" in head
    if has_yes == has_no:  # both or neither → ambiguous
        return False
    return has_yes


# -- path -----------------------------------------------------------------
def parse_path_answer(resp: str, prompt_type: str = "direct") -> int | str:
    """First line of the stripped answer: ``-1`` (trace ends), an int -2
    sentinel for empty/incomplete, or the raw code-line string."""
    if _cot_incomplete(resp, prompt_type):
        return -2
    ans = strip_answer_tags(resp)
    ans = ans.split("\n")[0].strip()
    if ans == "":
        return -2
    if ans == "-1":
        return -1
    return ans


def path_answer_to_lines(ans: int | str, codelines: list[str]) -> list[int]:
    """Map a parsed path answer onto 1-indexed line numbers.

    A code-line string maps to *every* source line whose stripped text
    matches; no match → ``[-2]`` (never correct)."""
    if isinstance(ans, int):
        return [ans]
    matches = [i + 1 for i, line in enumerate(codelines) if ans == line.strip()]
    return matches if matches else [-2]


# -- state ----------------------------------------------------------------
_UNICODE_QUOTES = {"‘": "'", "’": "'", "“": '"', "”": '"'}


def _is_builtin_type(cls) -> bool:
    return cls is not None and isinstance(cls, type) and cls.__module__ == "builtins"


def parse_state_answer(resp: str, prompt_type: str = "direct"):
    """Parse ``value; type`` into a concrete ``(value, type)`` pair.

    Applies the benchmark's repair chain: unicode quotes, ``<class '…'>``
    unwrapping, generics stripping, common type-name aliases, str/datetime/
    ndarray special cases, then ``pydoc.locate`` with eval-vs-constructor
    fallback.  Returns ``Nil`` when the model says Nil, ``'ERROR'`` when
    unparseable.
    """
    if _cot_incomplete(resp, prompt_type):
        return "ERROR"
    for u, a in _UNICODE_QUOTES.items():
        resp = resp.replace(u, a)
    resp = strip_answer_tags(resp.strip())
    if resp.capitalize() == "Nil" or resp == "[Nil]":
        return Nil
    semicolon = resp.rfind(";")
    if semicolon == -1:
        return "ERROR"
    value_text = resp[:semicolon].strip()
    type_text = resp[semicolon + 1:].strip().lower()
    if value_text.capitalize() == "Nil":
        return Nil

    m = re.match(r"<class '(.*)'>", type_text)
    if m:
        type_text = m.group(1)
    m = re.match(r"(.*)\[.*\]", type_text)
    if m:
        type_text = m.group(1)
    if type_text == "string":
        type_text = "str"
    if type_text == "integer":
        type_text = "int"
    if "," in type_text or "tuple" in type_text:
        type_text = "tuple"

    if type_text == "str":
        try:
            return eval(value_text), str  # noqa: S307 — benchmark-defined parsing
        except Exception:
            return value_text, str
    if type_text in ("datetime.datetime", "datetime"):
        from dateutil.parser import parse as parse_dt

        try:
            return parse_dt(value_text), locate(type_text)
        except Exception:
            return "ERROR"
    if type_text in ("numpy.ndarray", "np.ndarray"):
        try:
            return np.array(eval(value_text)), locate(type_text)  # noqa: S307
        except Exception:
            return "ERROR"
    if value_text == "None" or type_text == "NoneType":
        return None, type(None)
    try:
        _type = locate(type_text)
        if _is_builtin_type(_type):
            _val = eval(value_text)  # noqa: S307
        else:
            try:
                _val = _type(eval(value_text))  # noqa: S307
            except Exception:
                _val = _type(value_text)
        return _val, _type
    except Exception:
        return "ERROR"


def state_answers_equal(ans, actual) -> bool:
    """Type-aware equality between a parsed (value, type) answer and the
    list of ground-truth candidate values (float ε=1e-6; np.allclose for
    arrays; membership otherwise)."""
    if ans is Nil and actual is Nil:
        return True
    if ans is Nil or actual is Nil:
        return False
    ans_val, ans_type = ans
    if all(ans_type != type(a) for a in actual):
        return False
    if type(ans_val) != ans_type:
        return False
    if ans_type == float:
        for a in actual:
            try:
                if abs(ans_val - a) < 1e-6:
                    return True
            except Exception:
                continue
        return False
    try:
        return ans_val in actual
    except ValueError:
        # numpy arrays make `in` ambiguous; compare elementwise
        for a in actual:
            try:
                if isinstance(ans_val, np.ndarray) and isinstance(a, np.ndarray):
                    if np.allclose(ans_val, a):
                        return True
                elif ans_val == a:
                    return True
            except Exception:
                continue
        return False


# -- output ---------------------------------------------------------------
def parse_output_answer(resp: str, prompt_type: str = "direct") -> str:
    if _cot_incomplete(resp, prompt_type):
        return "ERROR"
    return strip_answer_tags(resp)


def pad_output_answer(ans: str, given_input: str) -> str:
    """Ensure the answer has at least as many lines as the given test code,
    padding missing leading lines from the input (models often echo only
    the lines they changed)."""
    if ans == "ERROR":
        return "assert False"
    in_lines = given_input.strip().split("\n")
    res_lines = ans.strip().split("\n")
    if len(res_lines) >= len(in_lines):
        return ans
    diff = len(in_lines) - len(res_lines)
    return "\n".join(in_lines[:diff] + res_lines)


def output_penalty(code: str, given_input: str) -> bool:
    """Anti-cheat: trivial self-satisfying asserts or fewer asserts than the
    question asked for mark the answer failed outright."""
    trivial = (
        "assertTrue(True)" in code
        or "assertFalse(False)" in code
        or "assert True" in code
        or "assert False" in code
    )
    if trivial:
        return True
    given = given_input.count("assert")
    assert given > 0, "output task input must contain an assert"
    return code.count("assert") < given
