"""The task engine: plan → batched inference → score.

The reference interleaves prompting with scoring, one model call per probe
(evaluation.py:105-107) — which serialises the accelerator.  This engine
splits a run into three phases:

1. **plan**: walk the benchmark rows for the chosen dataset family, run the
   ground-truth sandboxes, precompute expected answers, and emit one
   :class:`ProbeJob` per model call (prompt + scoring context);
2. **infer**: hand *all* prompts to the backend's ``infer_many`` — the TPU
   engine batches/schedules them freely;
3. **score**: post-process responses in plan order, accumulate metrics, and
   assemble records byte-compatible with the reference results schema.

Family branching (HumanEval/MBPP/MathQA functions vs ClassEval classes)
mirrors evaluation.py:135-218 with the §2.10 bugs fixed: kwargs plumb
through every task, no double-appended path records, MathQA list-typed
inputs handled, and split selection is explicit.
"""

from __future__ import annotations

import json
import os
import traceback
from dataclasses import dataclass, field
from typing import Any

from ..datasets import DREvalDataset, Families, family_of
from ..datasets.dreval import ClassEvalHooks
from ..dynamics import CodeSpace, Sandbox
from ..prompting import build_prompt
from ..resilience import INFER_FAILED
from .results import ResultsStore

__all__ = ["TaskRunner", "ProbeTask", "ProbeJob"]

VALID_PROMPT_TYPES = ("direct", "cot", "tot")


@dataclass
class ProbeJob:
    """One model call: its prompt plus everything scoring needs."""

    gen_entry: dict         # the {'input_idx', 'results'} entry this feeds
    prompt: str
    expected: Any = None    # precomputed ground truth (task-specific shape)
    lineno: int | None = None   # 1-indexed probe line
    var: str | None = None
    context: dict = field(default_factory=dict)


class TaskRunner:
    """Base engine; concrete tasks fill in planning/scoring hooks."""

    name: str = ""
    supports_tot = False      # probe tasks (coverage/path/state) set True

    @staticmethod
    def _build_tot_parser(kwargs: dict, dataset: str):
        """Construct the trace-dump parser from ``tot_*`` kwargs or a
        ``.tot_config`` JSON (reference evaluation.py:54-59; key names
        kept: ``base_dir``, ``inference_output_dir``)."""
        from ..tot import TraceOfThoughtsParser

        cfg = {}
        cfg_path = kwargs.get("tot_config", ".tot_config")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
        base_dir = kwargs.get("tot_base_dir") or cfg.get("base_dir")
        run_name = (kwargs.get("tot_run_name") or cfg.get("inference_output_dir")
                    or cfg.get("run_name"))
        assert base_dir and run_name, (
            "trace-of-thoughts mode needs tot_base_dir/tot_run_name kwargs "
            "or a .tot_config with base_dir + inference_output_dir")
        return TraceOfThoughtsParser(base_dir, dataset, run_name)

    def __init__(self, model=None, prompt_type: str = "direct", dataset: str = None,
                 split: str | None = None, mock: bool = False, custom_mock: bool = False,
                 results_dir: str = "model_generations", valid_test_cases_path: str | None = None,
                 sandbox_timeout: float = 120.0, progress: bool = True,
                 max_items: int | None = None, **kwargs):
        assert prompt_type in VALID_PROMPT_TYPES, f"prompt_type must be one of {VALID_PROMPT_TYPES}"
        self.backend = model
        self.prompt_type = prompt_type
        self.mock = bool(mock or custom_mock)
        if self.mock and self.backend is None:
            from ..inference.mock import MockBackend

            self.backend = MockBackend(prompt_type=prompt_type)
        self.kwargs = kwargs
        assert dataset is not None, "dataset is required (humaneval|classeval|mbpp|mathqa)"
        self.dataset = dataset
        if not self.mock and self.backend is not None and prompt_type != "tot":
            assert self.backend.prompt_type == prompt_type, \
                "backend prompt type must match task prompt type"
        self.data = DREvalDataset.load(dataset, split)
        self.sandbox_timeout = sandbox_timeout
        # ground-truth sandbox outcomes, tallied during planning; non-ok
        # pairs degrade to skipped probes and are surfaced in the metrics
        # trailer so fleet summaries can tell ground-truth timeouts from
        # model errors
        self.sandbox_stats = {"ok": 0, "timed out": 0, "exception": 0}
        self.progress = progress
        self.max_items = max_items  # smoke runs: only the first N benchmark rows
        self._no_skip: set[tuple] | None = None
        if valid_test_cases_path:
            with open(valid_test_cases_path) as f:
                self._no_skip = {tuple(t) for t in json.load(f)}
        self.tot_parser = None
        if prompt_type == "tot":
            assert self.supports_tot, f"task {self.name!r} has no trace-of-thoughts mode"
            self.tot_parser = self._build_tot_parser(kwargs, dataset)
            model_info = f"{kwargs.get('model_id', 'tot_model')}_tot"
        elif self.mock:
            model_info = "mock_model_" + prompt_type
        else:
            model_info = self.backend.info
        self.store = ResultsStore(self.name, model_info, results_dir)
        self.metrics_trailer: dict = {}

    # ---- per-task hooks (implemented by subclasses) ----------------------
    def plan_function_pair(self, *, idx, fam, pair, space, entry, code, codelines,
                           sandbox, invocation, task_idx, gen_entry, jobs):
        raise NotImplementedError

    def plan_class_pair(self, *, idx, pair, test_cls, code, codelines, _input,
                        setup, gen_entry, jobs):
        raise NotImplementedError

    def score_job(self, job: ProbeJob, response: str) -> dict:
        """Post-process one response, update metrics, return the record."""
        raise NotImplementedError

    @property
    def metrics(self) -> dict:
        raise NotImplementedError

    # ---- skip-list support (tot-validated test cases) --------------------
    def _skipped(self, key: tuple) -> bool:
        return self._no_skip is not None and key not in self._no_skip

    # ---- sandbox accounting ----------------------------------------------
    def _tally_sandbox(self, status: str) -> bool:
        """Record one ground-truth sandbox outcome; True when it ran ok."""
        key = "exception" if status.startswith("exception") else status
        self.sandbox_stats[key] = self.sandbox_stats.get(key, 0) + 1
        return status == "ok"

    def _final_metrics(self) -> dict:
        """The task's metrics plus failure accounting, when any occurred.
        Clean runs keep the exact reference trailer shape."""
        metrics = dict(self.metrics)
        timed_out = self.sandbox_stats.get("timed out", 0)
        raised = self.sandbox_stats.get("exception", 0)
        if timed_out or raised:
            metrics["sandbox_errors"] = {"timed_out": timed_out,
                                         "exception": raised}
        return metrics

    # ---- planning --------------------------------------------------------
    @staticmethod
    def _family_task_idx(idx: int, fam: str) -> int | None:
        """The per-family task index used in skip-list tuples: MBPP's test
        split starts at upstream id 11 (evaluation.py:179); MathQA is
        0-based; HumanEval/ClassEval don't use skip lists."""
        if fam == "mbpp":
            return (idx - Families.MBPP_START) + Families.MBPP_TASK_ID_OFFSET
        if fam == "mathqa":
            return idx - Families.MATHQA_START
        return None

    def _resolve_args(self, space: CodeSpace, _input):
        """Benchmark inputs are arg-tuple reprs for HumanEval/MBPP but JSON
        lists for MathQA; both become positional args."""
        if isinstance(_input, (list, tuple)):
            return tuple(_input)
        return space.eval_invocation(_input)

    def _plan(self) -> tuple[list[dict], list[ProbeJob]]:
        records: list[dict] = []
        jobs: list[ProbeJob] = []
        rows = list(self.data.iter_tasks(self.dataset))
        if self.max_items is not None:
            rows = rows[: self.max_items]
        for n, row in enumerate(rows):
            idx = int(row["idx"])
            record = {"task_id": f"DREval/{idx}", "generation": []}
            records.append(record)
            fam = family_of(idx)
            if fam == "classeval":
                self._plan_class_item(idx, row["tasks"], record, jobs)
            else:
                self._plan_function_item(idx, fam, row["tasks"], record, jobs)
            if self.progress and (n + 1) % 25 == 0:
                print(f"[{self.name}] planned {n + 1}/{len(rows)} items, {len(jobs)} prompts")
        failed = (self.sandbox_stats["timed out"]
                  + self.sandbox_stats["exception"])
        if failed and self.sandbox_stats["ok"] == 0:
            # partial sandbox failures degrade (skipped pairs, counted in
            # the trailer) — but EVERY pair failing is a broken host/config
            # (e.g. sandbox_timeout far too low), and scoring an empty run
            # as "complete" would journal it as done under --resume
            raise RuntimeError(
                f"[{self.name}] ground truth failed for all {failed} pairs "
                f"({dict(self.sandbox_stats)}) — broken sandbox config/host, "
                f"refusing to score an empty run")
        return records, jobs

    def _plan_function_item(self, idx: int, fam: str, pairs: list, record: dict, jobs: list):
        code = self.data.code(idx)
        entry = self.data.entry_point(idx)
        codelines = code.split("\n")
        space = CodeSpace()
        space.load_function(entry, code)
        sandbox = Sandbox(space.ns[entry], timeout=self.sandbox_timeout)
        inputs = self.data.inputs(idx)
        invocations = self.data.invocations(idx) if fam in ("mbpp", "mathqa") else None
        task_idx = self._family_task_idx(idx, fam)
        for pair in pairs:
            gen_entry = {"input_idx": pair["input_idx"], "results": []}
            record["generation"].append(gen_entry)
            _input = pair["output_pred"] if self.name == "output" else inputs[pair["input_idx"]]
            if invocations is not None:
                invocation = invocations[pair["input_idx"]].strip()
            elif isinstance(_input, str) and self.name != "output":
                # "(args,)" repr → "entry(args)" call syntax
                invocation = f"{entry}{_input[:-2]})"
            else:
                invocation = f"{entry}(…)"
            self.plan_function_pair(
                idx=idx, fam=fam, pair=pair, space=space, entry=entry, code=code,
                codelines=codelines, sandbox=sandbox, invocation=invocation,
                task_idx=task_idx, gen_entry=gen_entry, jobs=jobs,
            )

    def _plan_class_item(self, idx: int, pairs: list, record: dict, jobs: list):
        code = self.data.code(idx)
        cls_name = self.data.entry_point(idx)
        test_code = self.data.test_code(idx)
        space = CodeSpace()
        space.load_class(cls_name, code)
        test_classes = space.load_test_classes(
            cls_name, code, test_code,
            ClassEvalHooks.name_pattern, ClassEvalHooks.validation, ClassEvalHooks.postprocess,
        )
        codelines = code.split("\n")
        inputs = self.data.inputs(idx)
        for pair in pairs:
            gen_entry = {"input_idx": pair["input_idx"], "results": []}
            record["generation"].append(gen_entry)
            test_cls = test_classes[pair["input_idx"]]
            _input = pair["output_pred"] if self.name == "output" else inputs[pair["input_idx"]]
            setup = self._setup_comment(test_cls)
            self.plan_class_pair(
                idx=idx, pair=pair, test_cls=test_cls, code=code, codelines=codelines,
                _input=_input, setup=setup, gen_entry=gen_entry, jobs=jobs,
            )

    @staticmethod
    def _setup_comment(test_cls) -> str:
        """Render the class's own setUp body as a commented preamble for
        prompts (inherited unittest stubs contribute nothing)."""
        setup_src = getattr(test_cls, "__setup__", None)
        if not setup_src or "Hook method for setting up the test fixture" in setup_src:
            return ""
        body = setup_src.split("\n")[1:]
        return "\n# setup code executed previously\n# " + "\n# ".join(body)

    @staticmethod
    def run_class_sandbox(test_cls, timeout: float):
        """Instantiate, setUp, and trace the pair's dreval_test.  Returns
        ``(states, status)``; callers decide whether a non-ok status is
        fatal (taskgen) or a degraded skip (planning)."""
        try:
            obj = test_cls()
            if hasattr(obj, "setUp"):
                obj.setUp()
        except Exception as exc:  # fixture failure: no trace possible
            return None, f"exception: {exc}"
        sandbox = Sandbox(obj.dreval_test, timeout=timeout)
        _, states = sandbox.run()
        return states, sandbox.status

    # ---- trace-of-thoughts hooks (probe tasks implement) -----------------
    def tot_matches(self, job: "ProbeJob", ans) -> bool:
        """Does a parsed answer agree with the probe's ground truth?"""
        raise NotImplementedError

    def tot_record(self, job: "ProbeJob", ans, gen: str, error: str | None) -> dict:
        """Score one phase-2 answer and build its result record."""
        raise NotImplementedError

    # ---- trace-of-thoughts run (reference evaluation.py:303-351 et al) ---
    def run_tot(self) -> dict:
        records, jobs = self._plan()
        valid_cases: list[tuple] = []
        scored = 0
        for job in jobs:
            result = self._tot_probe(job, valid_cases)
            if result is not None:
                job.gen_entry["results"].append(result)
                scored += 1
        if self.progress:
            print(f"[{self.name}] tot: {len(valid_cases)} valid test cases, "
                  f"{scored} scored of {len(jobs)} probes")
        self.metrics_trailer = self._final_metrics()
        records.append(self.metrics_trailer)
        from datetime import datetime, timezone

        now = datetime.now(timezone.utc)  # one stamp pairs both artifacts
        path = self.store.write(records, self.dataset, now=now)
        valid_path = os.path.join(
            self.store.save_dir,
            f"{self.store.timestamp(now)}.valid_test_cases.{self.dataset}.json")
        with open(valid_path, "w") as f:
            json.dump([list(k) for k in valid_cases], f)
        if self.progress:
            print(f"[{self.name}] metrics: {self.metrics_trailer}")
            print(f"[{self.name}] wrote {path}\n[{self.name}] wrote {valid_path}")
        return self.metrics_trailer

    def _tot_probe(self, job: "ProbeJob", valid_cases: list[tuple]) -> dict | None:
        """Two-phase protocol per probe: (1) parse *with* ground-truth labels
        and keep the test case only if that reproduces the known answer;
        (2) re-parse the model channel for the scored answer, mapping
        failures to the reference error taxonomy."""
        from ..tot import EmptyAnswerError, ValidationError

        t_idx, i_idx = job.context["tot_key"]
        probe_kwargs = dict(lineno=job.lineno, var=job.var)
        try:
            self.tot_parser.validate_task(
                t_idx, i_idx, code=job.context["code"],
                invocation=job.context["invocation"])
            ans, _ = self.tot_parser.process_task(
                t_idx, i_idx, self.name, use_labels=True, **probe_kwargs)
            if not self.tot_matches(job, ans):
                return None
        except Exception:
            return None  # invalid test case: silently skipped (ref :317-327)
        valid_cases.append(
            self._probe_key(t_idx, i_idx, {"lineno": job.lineno, "var": job.var}))
        error = None
        try:
            ans, gen = self.tot_parser.process_task(
                t_idx, i_idx, self.name, use_labels=False, **probe_kwargs)
        except ValidationError as e:
            error, ans, gen = "VALIDATION_ERROR", None, str(e)
        except EmptyAnswerError as e:
            error, ans, gen = "EMPTY_ANSWER_ERROR", None, str(e)
        except Exception as e:
            error, ans, gen = "GENERAL_ERROR", None, "".join(
                traceback.format_exception(type(e), e, e.__traceback__))
        return self.tot_record(job, ans, gen, error)

    # ---- the run ---------------------------------------------------------
    def score_and_write(self, records: list[dict], jobs: list["ProbeJob"],
                        responses: list[str]) -> dict:
        """Score planned jobs against their responses and persist the log.
        Split out of :meth:`run` so the fleet runner can batch inference
        across several tasks before scoring each."""
        assert len(responses) == len(jobs), (
            f"[{self.name}] {len(responses)} responses for {len(jobs)} jobs")
        for job, resp in zip(jobs, responses):
            job.gen_entry["results"].append(self.score_job(job, resp))
        self.metrics_trailer = self._final_metrics()
        failed = sum(1 for r in responses if r == INFER_FAILED)
        if failed:
            # slots lost to the resilience sentinel (scored as wrong above):
            # distinct from sandbox_errors, these are *model-side* losses
            self.metrics_trailer["infer_failures"] = failed
        records.append(self.metrics_trailer)
        path = self.store.write(records, self.dataset)
        if self.progress:
            print(f"[{self.name}] metrics: {self.metrics_trailer}")
            print(f"[{self.name}] wrote {path}")
        return self.metrics_trailer

    def run(self) -> dict:
        if self.prompt_type == "tot":
            return self.run_tot()
        records, jobs = self._plan()
        prompts = [j.prompt for j in jobs]
        if self.progress:
            print(f"[{self.name}] {len(prompts)} prompts → backend {'(mock)' if self.mock else ''}")
        responses = self.backend.infer_many(prompts) if jobs else []
        return self.score_and_write(records, jobs, responses)


class ProbeTask(TaskRunner):
    """Shared planning for per-line probe tasks (coverage, path, state)."""

    uses_var = False          # state sets True (probes carry a variable)
    numbered_code = False     # path sets True (prompt shows numbered lines)
    supports_tot = True       # answers extractable from a trace dump

    # -- hooks for concrete probe tasks -----------------------------------
    def ground_truth(self, states, lineno0: int, var: str | None):
        raise NotImplementedError

    def probe_record(self, job: ProbeJob, response: str):
        raise NotImplementedError

    def score_job(self, job: ProbeJob, response: str) -> dict:
        return self.probe_record(job, response)

    # -- planning ----------------------------------------------------------
    @staticmethod
    def _prompt_code(code: str, codelines: list[str], numbered: bool) -> str:
        if numbered:
            return "".join(f"{i + 1}\t{line}\n" for i, line in enumerate(codelines))
        return code

    def _probe_key(self, task_idx, input_idx, probe) -> tuple:
        if self.uses_var:
            return (task_idx, input_idx, probe.get("var"), probe["lineno"])
        return (task_idx, input_idx, probe["lineno"])

    def plan_function_pair(self, *, idx, fam, pair, space, entry, code, codelines,
                           sandbox, invocation, task_idx, gen_entry, jobs):
        args = self._resolve_args(space, self.data.inputs(idx)[pair["input_idx"]])
        _, states = sandbox.run(*args)
        if not self._tally_sandbox(sandbox.status):
            # ground truth unavailable: skip this pair's probes (its
            # gen_entry stays empty) and keep the run alive — the count
            # lands in the metrics trailer as sandbox_errors
            if self.progress:
                print(f"[{self.name}] sandbox {sandbox.status!r} running "
                      f"{entry} on DREval/{idx} — skipping "
                      f"{len(pair['task'])} probes")
            return
        for probe in pair["task"]:
            if self._skipped(self._probe_key(task_idx, pair["input_idx"], probe)):
                continue
            self._append_probe_job(jobs, gen_entry, states=states, probe=probe,
                                   code=code, codelines=codelines,
                                   invocation=invocation, invocation_abbr=invocation,
                                   numbered=self.numbered_code,
                                   tot_key=(task_idx if task_idx is not None else idx,
                                            pair["input_idx"]))

    def plan_class_pair(self, *, idx, pair, test_cls, code, codelines, _input,
                        setup, gen_entry, jobs):
        states, status = self.run_class_sandbox(test_cls, self.sandbox_timeout)
        if not self._tally_sandbox(status):
            if self.progress:
                print(f"[{self.name}] sandbox {status!r} tracing "
                      f"{test_cls.__name__} on DREval/{idx} — skipping "
                      f"{len(pair['task'])} probes")
            return
        invocation = setup + "\n" + str(_input).rstrip()
        for probe in pair["task"]:
            # NOTE: ClassEval path prompts show un-numbered code (reference
            # evaluation.py:574-582 numbers only the function families).
            self._append_probe_job(jobs, gen_entry, states=states, probe=probe,
                                   code=code, codelines=codelines,
                                   invocation=invocation,
                                   invocation_abbr="the above test code",
                                   numbered=False,
                                   tot_key=(idx, pair["input_idx"]))

    def _append_probe_job(self, jobs, gen_entry, *, states, probe, code,
                          codelines, invocation, invocation_abbr, numbered,
                          tot_key=None):
        lineno = probe["lineno"]
        var = probe.get("var") if self.uses_var else None
        expected = self.ground_truth(states, lineno - 1, var)
        context = {"codelines": codelines, "code": code,
                   "invocation": invocation, "tot_key": tot_key}
        if self.prompt_type == "tot":
            # no prompt is rendered: answers come from trace dumps
            jobs.append(ProbeJob(gen_entry=gen_entry, prompt="",
                                 expected=expected, lineno=lineno, var=var,
                                 context=context))
            return
        fields = dict(
            code=self._prompt_code(code, codelines, numbered),
            invocation=invocation,
            invocation_abbr=invocation_abbr,
            line=lineno,
            codeline=codelines[lineno - 1],
        )
        if self.uses_var:
            fields["var"] = var
        prompt = build_prompt(self.name, self.prompt_type, **fields)
        jobs.append(ProbeJob(gen_entry=gen_entry, prompt=prompt,
                             expected=expected, lineno=lineno, var=var,
                             context=context))
