"""Cross-task consistency score (reference evaluation.py:1014-1063).

Aligns the latest coverage/state/path/output logs for one model and scores
each aligned test case on the weighted ladder: all four correct → 1,
coverage+state+path → 0.5, coverage+state → 0.25, coverage only → 0.125;
reported as ``100 * score / total``.

Output logs hold one record per input pair, so each verdict is expanded by
that pair's probe count (taken from the coverage log) to align with the
per-probe tasks.

Degraded pairs: planning re-runs each pair's ground-truth sandbox per task,
so a pair sitting near the sandbox timeout can skip (empty ``results``) in
one task's log but not another's.  State/path flatten against the coverage
log's per-pair probe counts — a count mismatch scores that pair wrong at
coverage's count instead of desynchronising the ladder and crashing a
finished fleet run at its final step.
"""

from __future__ import annotations

from .results import ResultsStore

__all__ = ["ConsistencyScorer"]

LADDER = ("coverage", "state", "path", "output")


class ConsistencyScorer:
    def __init__(self, model_info: str, dataset: str,
                 results_dir: str = "model_generations", progress: bool = True):
        self.model_info = model_info
        self.dataset = dataset
        self.progress = progress
        self.logs = {}
        for task in LADDER:
            store = ResultsStore(task, model_info, results_dir)
            path = store.latest(dataset)
            if progress:
                print(f"[consistency] load {path}")
            self.logs[task] = ResultsStore.read(path)

    @staticmethod
    def _flatten(rows: list[dict], rule) -> list[bool]:
        verdicts = []
        for row in rows[:-1]:  # last row is the metrics trailer
            for gen in row["generation"]:
                for atomic in gen["results"]:
                    verdict = rule(atomic)
                    assert isinstance(verdict, bool)
                    verdicts.append(verdict)
        return verdicts

    def _flatten_to_coverage(self, task: str, rule) -> list[bool]:
        """Flatten a per-probe task's log aligned to the coverage log's
        per-pair probe counts; a mismatched pair (its ground-truth sandbox
        degraded in one task but not the other) scores wrong at coverage's
        count rather than shifting every later verdict."""
        verdicts = []
        cov_rows = self.logs["coverage"]
        for i, row in enumerate(self.logs[task][:-1]):
            for j, gen in enumerate(row["generation"]):
                expected = len(cov_rows[i]["generation"][j]["results"])
                results = gen["results"]
                if len(results) == expected:
                    for atomic in results:
                        verdict = rule(atomic)
                        assert isinstance(verdict, bool)
                        verdicts.append(verdict)
                else:
                    if self.progress:
                        print(f"[consistency] {task} row {i} pair {j}: "
                              f"{len(results)} results vs coverage's "
                              f"{expected} — scoring pair as wrong")
                    verdicts.extend([False] * expected)
        return verdicts

    def run(self) -> float:
        coverage = self._flatten(self.logs["coverage"], lambda r: r["response"] == r["expected"])
        state = self._flatten_to_coverage("state", lambda r: bool(r["eq"]))
        path = self._flatten_to_coverage("path", lambda r: any(y in r["expected"] for y in r["response"]))
        output: list[bool] = []
        coverage_rows = self.logs["coverage"]
        for i, row in enumerate(self.logs["output"][:-1]):
            for j, gen in enumerate(row["generation"]):
                verdict = bool(gen["results"][0]["pass"]) if gen["results"] else False
                repeats = len(coverage_rows[i]["generation"][j]["results"])
                output.extend([verdict] * repeats)
        assert len(coverage) == len(state) == len(path) == len(output), (
            f"task logs misaligned: cov={len(coverage)} state={len(state)} "
            f"path={len(path)} out={len(output)}"
        )
        total = len(coverage)
        score = 0.0
        # Exclusive rungs (reference evaluation.py:1055-1062): partial credit
        # only when every rung *above* is correct and every rung below wrong.
        for c, s, p, o in zip(coverage, state, path, output):
            if c and s and p and o:
                score += 1
            elif c and s and p and not o:
                score += 0.5
            elif c and s and not p and not o:
                score += 0.25
            elif c and not s and not p and not o:
                score += 0.125
        final = 100.0 * score / total if total else 0.0
        if self.progress:
            print(f"Consistency score: {final}")
        return final
