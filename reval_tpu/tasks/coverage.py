"""Coverage task: "Is line L executed?" (reference evaluation.py:230-413)."""

from __future__ import annotations

from .answers import parse_coverage_answer
from .base import ProbeJob, ProbeTask

__all__ = ["CoverageTask"]


class CoverageTask(ProbeTask):
    name = "coverage"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.tp = self.tn = self.fp = self.fn = 0
        self._total = 0

    # -- metrics -----------------------------------------------------------
    def _acc(self):
        denom = self.tp + self.tn + self.fp + self.fn
        return (self.tp + self.tn) / denom if denom else 0.0

    def _prec(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def _rec(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def _f1(self):
        p, r = self._prec(), self._rec()
        return 2 * p * r / (p + r) if (p + r) else 0.0

    @property
    def metrics(self) -> dict:
        return {"total": self._total, "acc": self._acc(), "prec": self._prec(),
                "rec": self._rec(), "f1": self._f1()}

    # -- ground truth + scoring -------------------------------------------
    def ground_truth(self, states, lineno0: int, var):
        return states.get_coverage(lineno0)

    def _update(self, ans: bool, actual: bool) -> None:
        self._total += 1
        if ans and actual:
            self.tp += 1
        elif ans and not actual:
            self.fp += 1
        elif not ans and actual:
            self.fn += 1
        else:
            self.tn += 1

    def probe_record(self, job: ProbeJob, response: str) -> dict:
        ans = parse_coverage_answer(response, self.prompt_type)
        actual = job.expected
        self._update(ans, actual)
        return {"generated": response, "response": ans, "expected": actual}

    # -- trace-of-thoughts -------------------------------------------------
    def tot_matches(self, job: ProbeJob, ans) -> bool:
        return bool(ans) == bool(job.expected)

    def tot_record(self, job: ProbeJob, ans, gen: str, error: str | None) -> dict:
        ans = False if error else bool(ans)
        self._update(ans, job.expected)
        return {"generated": gen, "response": ans, "expected": job.expected,
                "line": job.lineno, "error": error}
