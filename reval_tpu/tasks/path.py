"""Path task: "Which line executes next after L?" (reference
evaluation.py:415-602).  The prompt shows the function-family code with
1-indexed line-number prefixes; the model may answer a line's text, which
maps to *all* matching source lines.  One record per probe (the reference's
double-append, evaluation.py:549-552, is not reproduced)."""

from __future__ import annotations

from .answers import parse_path_answer, path_answer_to_lines
from .base import ProbeJob, ProbeTask

__all__ = ["PathTask"]


class PathTask(ProbeTask):
    name = "path"
    numbered_code = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._correct = 0
        self._total = 0

    @property
    def metrics(self) -> dict:
        return {"acc": self._correct / self._total if self._total else 0.0,
                "correct": self._correct, "total": self._total}

    def ground_truth(self, states, lineno0: int, var):
        """Successor set, converted to 1-indexed; -1 (trace end / uncovered)
        passes through (reference evaluation.py:520-526)."""
        return [a if a == -1 else a + 1 for a in states.get_next_line(lineno0)]

    def probe_record(self, job: ProbeJob, response: str) -> dict:
        ans = parse_path_answer(response, self.prompt_type)
        ans_lines = path_answer_to_lines(ans, job.context["codelines"])
        actual = job.expected
        result = any(a in actual for a in ans_lines)
        self._total += 1
        if result:
            self._correct += 1
        return {"generated": response, "response": ans_lines, "expected": actual,
                "line": job.lineno, "prompt": job.prompt, "result": result}

    # -- trace-of-thoughts -------------------------------------------------
    def tot_matches(self, job: ProbeJob, ans) -> bool:
        return ans in job.expected

    def tot_record(self, job: ProbeJob, ans, gen: str, error: str | None) -> dict:
        # the parser answers a 1-indexed line (or -1); -2 marks errors, the
        # unmatched-answer sentinel of the text path
        ans = -2 if error else ans
        result = ans in job.expected
        self._total += 1
        if result:
            self._correct += 1
        return {"generated": gen, "response": [ans], "expected": job.expected,
                "line": job.lineno, "result": result, "error": error}
