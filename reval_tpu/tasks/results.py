"""Results store: timestamped JSONL logs per (task, model, prompt, temp).

Layout (byte-compatible with the reference consumer contract,
evaluation.py:122-133,220-221):

    <results_dir>/<task>@<model_info>/<YY-MM-DD-HH-MM>.<dataset>.jsonl

where each row is ``{"task_id": …, "generation": [{"input_idx": …,
"results": […]}]}`` and the final row is the metrics trailer.  Divergence
from the reference (SURVEY §2.10): ``/`` in model ids is sanitised to ``_``
so model names don't create nested directories; readers accept both.
"""

from __future__ import annotations

import glob
import json
import os
from datetime import datetime, timezone

__all__ = ["ResultsStore"]


class ResultsStore:
    def __init__(self, task_name: str, model_info: str, results_dir: str = "model_generations"):
        self.task_name = task_name
        self.model_info = model_info
        self.results_dir = results_dir

    @property
    def save_dir(self) -> str:
        return os.path.join(self.results_dir, f"{self.task_name}@{self.model_info}".replace("/", "_"))

    def _candidate_dirs(self) -> list[str]:
        raw = os.path.join(self.results_dir, f"{self.task_name}@{self.model_info}")
        return [self.save_dir, raw]

    @staticmethod
    def timestamp(now: datetime | None = None) -> str:
        return (now or datetime.now(timezone.utc)).strftime("%y-%m-%d-%H-%M")

    def write(self, records: list[dict], dataset: str, now: datetime | None = None) -> str:
        os.makedirs(self.save_dir, exist_ok=True)
        ts = self.timestamp(now)
        path = os.path.join(self.save_dir, f"{ts}.{dataset}.jsonl")
        # repeats within one minute (fleet runs) must not overwrite a log
        n = 1
        while os.path.exists(path):
            path = os.path.join(self.save_dir, f"{ts}-{n}.{dataset}.jsonl")
            n += 1
        with open(path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return path

    def latest(self, dataset: str | None = None) -> str:
        """Newest results file (both sanitised and raw-layout dirs searched)."""
        pattern = f"*.{dataset}.jsonl" if dataset else "*.jsonl"
        files: list[str] = []
        for d in self._candidate_dirs():
            files.extend(glob.glob(os.path.join(d, pattern)))
        if not files:
            raise FileNotFoundError(
                f"no results for task={self.task_name} model={self.model_info} under {self._candidate_dirs()}"
            )
        return max(files, key=os.path.getctime)

    @staticmethod
    def read(path: str) -> list[dict]:
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]
