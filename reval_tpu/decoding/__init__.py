"""Speculative + constrained decoding (ROADMAP item 2).

Three layers over the paged engine:

- :mod:`~reval_tpu.decoding.grammar` — REval answer shapes compiled to
  token-level constraint automata, applied as logit masks inside the
  decode step (a constrained row can never emit an out-of-grammar
  token);
- :mod:`~reval_tpu.decoding.draft` — self-drafting proposers
  (grammar-forced tokens + prompt-lookup n-gram spans over the row's
  own context);
- the engine's batched verify path
  (``inference/tpu/paged_engine.py::_verify_chunk``) — all K draft
  positions scored in ONE dispatch, with bit-identical greedy accept
  semantics: accepted tokens are provably the tokens plain greedy
  decode would have emitted (certified by the determinism observatory's
  ``spec-*`` parity cells every round).

Kill switch: ``REVAL_TPU_SPEC=0`` restores plain decode byte-for-byte.
"""

from .draft import NgramIndex, propose
from .grammar import (CLOSE_TAG, SHAPES, TASK_GRAMMARS, GrammarSet,
                      compile_shape, validate_grammar)

__all__ = [
    "CLOSE_TAG", "SHAPES", "TASK_GRAMMARS", "GrammarSet", "NgramIndex",
    "compile_shape", "propose", "validate_grammar",
]
