"""Self-drafting proposers for speculative decoding.

No draft model: draft tokens come from the request's OWN structure —

- **grammar forcing** — when the request's constraint automaton
  (decoding/grammar.py) is in a state with exactly ONE legal token,
  that token is a free draft: the verify step's masked argmax can only
  ever produce it, so it is accepted by construction.  Structured
  answers (``ES\\n[/ANSWER]`` after a ``Y``) draft themselves.
- **prompt lookup (n-gram matching)** — REval probes quote the program
  under test back at the model (the answer region echoes identifiers,
  line text, values seen in the prompt), so the classic
  prompt-lookup-decoding move applies: match the last ``n`` generated
  tokens against the request's own context and propose the historical
  continuation span.

Both proposers are exact-verify-safe: a wrong draft costs one rejected
verify position, never a wrong token (the batched verify step accepts
only drafts equal to its own masked greedy argmax —
``paged_engine._verify_chunk``).

Host-side and allocation-light by design: one :class:`NgramIndex` per
request, extended incrementally as tokens are accepted (never rebuilt),
and a propose loop of dict lookups — this runs inside the engine's
``# hot-path`` drive tick.
"""

from __future__ import annotations

__all__ = ["NgramIndex", "propose"]


class NgramIndex:
    """Prompt-lookup index over one request's token stream.

    Maps every gram of order ``2..n`` to the position FOLLOWING its most
    recent occurrence (latest wins — recency is the best predictor under
    repetitive probe text); a match tries the longest order first and
    falls back, which is what survives BPE merge jitter at the
    prompt/generation boundary.  ``extend`` registers the grams
    *preceding* each appended token, so the stream's current tail is
    never its own match.  Single-owner, like the request it belongs to.
    """

    __slots__ = ("n", "toks", "_maps")

    MIN_ORDER = 2

    def __init__(self, n: int, tokens=()):
        self.n = max(self.MIN_ORDER, int(n))
        self.toks: list[int] = []
        self._maps: dict[int, dict[tuple, int]] = {
            k: {} for k in range(self.MIN_ORDER, self.n + 1)}
        if tokens:
            self.extend(tokens)

    def extend(self, tokens) -> None:
        toks, maps = self.toks, self._maps
        for t in tokens:
            toks.append(int(t))
            i = len(toks) - 1
            for k, gram_map in maps.items():
                if i >= k:
                    gram_map[tuple(toks[i - k:i])] = i

    def match(self, tail) -> int | None:
        """Position whose history continues ``tail`` (the stream's last
        tokens incl. any pending drafts), longest order first; None when
        no order matches.  Slices BEFORE converting: this runs per
        eligible row per drive tick (the spec gate's promising probe),
        so it must not copy the whole stream."""
        tail = [int(t) for t in tail[-self.n:]]
        n_toks = len(self.toks)
        for k in range(min(self.n, len(tail)), self.MIN_ORDER - 1, -1):
            pos = self._maps[k].get(tuple(tail[-k:]))
            if pos is not None and pos < n_toks:
                return pos
        return None


def propose(index: NgramIndex | None, k: int, grammars=None,
            state: int = 0) -> tuple[list[int], int]:
    """Up to ``k`` draft tokens for one request.

    ``grammars``: the engine's :class:`~.grammar.GrammarSet` (None for
    an unconstrained row); ``state`` the row's current automaton state.
    Per position: a grammar-forced token wins (guaranteed accept), else
    the active n-gram span's next token — if it is grammar-legal —
    else try a fresh n-gram match, else stop.  Returns ``(drafts,
    n_forced)`` where ``n_forced`` counts the grammar-forced positions
    (the ``reval_grammar_forced_tokens_total`` observable); every draft
    is legal in sequence from ``state``.
    """
    drafts: list[int] = []
    n_forced = 0
    span_pos: int | None = None
    constrained = grammars is not None and state != 0
    while len(drafts) < k:
        tok = -1
        if constrained:
            forced = int(grammars.forced[state])
            if forced >= 0:
                tok = forced
                n_forced += 1
        if tok < 0 and index is not None:
            if span_pos is None or span_pos >= len(index.toks):
                # only the last n tokens matter to match(): never concat
                # the whole stream with the drafts (hot-path allocation)
                tail = ((index.toks[-index.n:] + drafts)
                        if drafts else index.toks)
                span_pos = index.match(tail)
            if span_pos is not None and span_pos < len(index.toks):
                cand = index.toks[span_pos]
                if not constrained or grammars.allowed(state, cand):
                    tok = cand
                    span_pos += 1
                else:
                    span_pos = None     # span went out-of-grammar: stop
        if tok < 0:
            break
        drafts.append(tok)
        if constrained:
            state = int(grammars.next[state, tok])
            constrained = state != 0
    return drafts, n_forced
