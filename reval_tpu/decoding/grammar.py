"""Answer-shape grammars compiled to token-level constraint automata.

REval's four probe tasks emit tiny, rigidly structured answers — a
YES/NO verdict, a line of code (or ``-1``), a ``value; type`` state
prediction, an assert completion — each wrapped in the benchmark's
``[ANSWER]``/``[/ANSWER]`` tags (prompting/templates).  This module
compiles each shape into a character-level automaton and *lifts* it to
the engine's real tokenizer: for every automaton state, which token ids
may be emitted next and which state each one leads to.  The paged
engine applies that as a logit mask inside the jitted decode step
(``paged_engine._decode_chunk`` / ``_verify_chunk``), so a constrained
row can never emit an out-of-grammar token — and the drafter
(decoding/draft.py) reads the same tables to propose grammar-forced
tokens for free when a state has exactly one legal continuation.

Layers:

- **Patterns** — a tiny combinator set (``lit``/``seq``/``alt``/
  ``star``/``plus``/``opt``/``cls``) compiled to a Thompson NFA.  The
  token lift executes the NFA with *frozensets of nodes* as states
  (lazy subset construction), so alternation/ambiguity (``Nil`` vs
  ``value; type``) needs no hand-built DFA.
- **Shapes** — the named grammars (:data:`SHAPES`): ``yesno``, ``int``,
  ``line``, ``state``, ``assert``, plus the user syntax
  ``lit:A|B|C`` (literal alternatives) and the ``cot-<shape>`` wrapper
  (free chain-of-thought text, then ``[/THOUGHT]`` … ``[ANSWER]``,
  then the shape body).  Every shape ends with the forced close
  ``[/ANSWER]`` — after it the automaton enters the FREE state.
- **TokenGrammar / GrammarSet** — the token-level tables.  A
  :class:`GrammarSet` owns ONE combined table per engine (state 0 is
  the shared FREE state: every token allowed, self-loop), with each
  compiled grammar's states at an offset.  The engine uploads the
  padded tables as jit operands; the host walks the same numpy tables
  to track per-request states and to draft.

Token-lift semantics (the contract the tests bite on):

- a token is **allowed** in a state iff its decoded characters all
  transition the automaton (reaching the accept node makes the rest of
  the token — and every later token — unconstrained: accept ⇒ FREE);
- tokens that decode to nothing (EOS, BOS, vocab padding, lone
  non-UTF-8 bytes) are allowed only in the FREE state — a constrained
  row cannot end or emit specials mid-answer;
- a state whose row would otherwise allow NOTHING (a tokenizer that
  cannot spell the next literal) degrades to EOS-only, so generation
  ends instead of emitting an arbitrary masked-logit argmax.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "SHAPES", "TASK_GRAMMARS", "CLOSE_TAG", "validate_grammar",
    "compile_shape", "token_strings", "GrammarSet",
]

CLOSE_TAG = "[/ANSWER]"

#: named answer shapes (see module docstring); ``lit:``/``cot-`` are
#: syntax, not names, and are validated in :func:`validate_grammar`
SHAPES = ("yesno", "int", "line", "state", "assert")

#: the per-task default grammars the fleet selects when grammar-
#: constrained decoding is enabled (direct templates; ``cot`` prompt
#: types use the ``cot-`` wrapped variant)
TASK_GRAMMARS = {"coverage": "yesno", "path": "line",
                 "state": "state", "output": "assert"}


# -- pattern combinators → Thompson NFA -----------------------------------
class _Node:
    __slots__ = ("eps", "trans")

    def __init__(self):
        self.eps: list[_Node] = []
        self.trans: list[tuple[str, "_Node"]] = []   # (matcher, target)


def _is_printable(c: str) -> bool:
    return c.isprintable() or c in " \t"


#: character classes usable in ``cls(name)`` — all exclude raw control
#: bytes so NUL/other unprintables never satisfy a constrained state
_CLASSES = {
    "digit": lambda c: c in "0123456789",
    "notnl": lambda c: c != "\n" and _is_printable(c),
    "ws": lambda c: c in " \t\n\r",
    "any": lambda c: c == "\n" or _is_printable(c),
}


def lit(s: str):
    return ("lit", s)


def seq(*ps):
    return ("seq", ps)


def alt(*ps):
    return ("alt", ps)


def star(p):
    return ("star", p)


def plus(p):
    return ("seq", (p, ("star", p)))


def opt(p):
    return ("alt", (p, ("lit", "")))


def cls(name: str):
    assert name in _CLASSES, name
    return ("cls", name)


def _build(p, start: _Node, accept: _Node) -> None:
    """Wire pattern ``p`` between ``start`` and ``accept`` (Thompson)."""
    kind, arg = p
    if kind == "lit":
        cur = start
        for ch in arg:
            nxt = _Node()
            cur.trans.append((ch, nxt))
            cur = nxt
        cur.eps.append(accept)
    elif kind == "cls":
        mid = _Node()
        start.trans.append(("\x00" + arg, mid))   # class marker
        mid.eps.append(accept)
    elif kind == "seq":
        cur = start
        for sub in arg:
            nxt = _Node()
            _build(sub, cur, nxt)
            cur = nxt
        cur.eps.append(accept)
    elif kind == "alt":
        for sub in arg:
            _build(sub, start, accept)
    elif kind == "star":
        hub = _Node()
        start.eps.append(hub)
        hub.eps.append(accept)
        _build(arg, hub, hub)
    else:   # pragma: no cover — combinator set is closed
        raise AssertionError(kind)


def _matches(matcher: str, c: str) -> bool:
    if matcher.startswith("\x00"):
        return _CLASSES[matcher[1:]](c)
    return matcher == c


class _CharNFA:
    """One compiled pattern, executed with frozensets as states."""

    def __init__(self, pattern):
        self.start = _Node()
        self.accept = _Node()
        _build(pattern, self.start, self.accept)
        self.start_set = self._closure({self.start})

    @staticmethod
    def _closure(nodes: set) -> frozenset:
        stack, seen = list(nodes), set(nodes)
        while stack:
            for nxt in stack.pop().eps:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return frozenset(seen)

    def advance(self, states: frozenset, c: str) -> frozenset:
        out: set = set()
        for node in states:
            for matcher, target in node.trans:
                if _matches(matcher, c):
                    out.add(target)
        return self._closure(out) if out else frozenset()

    def forced_chars(self, states: frozenset, limit: int = 48) -> str:
        """The deterministic character chain from ``states``: the
        longest run where exactly ONE concrete character can come next
        (a class transition or a literal fork ends it).  This is what
        the drafter proposes for free — under a multi-char tokenizer the
        chain spans several tokens, so forcing survives BPE."""
        out: list[str] = []
        cur = states
        while len(out) < limit and self.accept not in cur:
            chars: set[str] = set()
            for node in cur:
                for matcher, _ in node.trans:
                    if matcher.startswith("\x00"):
                        return "".join(out)     # class edge: not forced
                    chars.add(matcher)
            if len(chars) != 1:
                break
            c = chars.pop()
            out.append(c)
            cur = self.advance(cur, c)
        return "".join(out)


# -- named shapes ----------------------------------------------------------
def _pre():
    # at most ONE leading newline (the few-shot examples' spelling): an
    # unbounded whitespace loop would let a greedy model burn its whole
    # token budget on masked-in whitespace before the answer body
    return opt(lit("\n"))


def _close():
    # canonical close: newline + tag, exactly the spelling every
    # few-shot example shows.  Deliberately tighter than the parser
    # tolerates (strip_answer_tags accepts any whitespace) — ONE
    # canonical spelling keeps every post-body close state
    # single-successor, which is what lets the drafter propose the
    # whole close for free (decoding/draft.py grammar forcing)
    return lit("\n" + CLOSE_TAG)


def _body(name: str):
    if name == "yesno":
        return alt(lit("YES"), lit("NO"))
    if name == "int":
        return seq(opt(lit("-")), plus(cls("digit")))
    if name == "line":
        # one line of code, or the path task's -1 sentinel (an int IS a
        # printable line, so the int case needs no alternative here)
        return plus(cls("notnl"))
    if name == "state":
        # ``value; type`` — at least one semicolon on one line (the
        # parser rfinds the LAST one, so values may contain more) — or
        # the benchmark's Nil sentinel
        return alt(lit("Nil"),
                   seq(star(cls("notnl")), lit(";"), star(cls("notnl"))))
    if name == "assert":
        # assert completion: free line(s) that must contain an assert
        # before the close tag may ever be emitted
        return seq(star(cls("any")), lit("assert"), star(cls("any")))
    if name.startswith("lit:"):
        choices = [c for c in name[4:].split("|") if c]
        if not choices:
            raise ValueError(f"grammar {name!r}: lit: needs at least one "
                             f"non-empty alternative (lit:A|B)")
        return alt(*[lit(c) for c in choices])
    raise ValueError(
        f"unknown grammar {name!r} (shapes: {', '.join(SHAPES)}, "
        f"lit:A|B, cot-<shape>)")


def compile_shape(name: str) -> _CharNFA:
    """Compile one grammar name to its character automaton.  Raises
    ``ValueError`` for unknown names — the serving layer maps that to a
    400 at submit."""
    if name.startswith("cot-"):
        inner = _body(name[4:])
        pattern = seq(star(cls("any")), lit("[/THOUGHT]"), star(cls("ws")),
                      lit("[ANSWER]"), _pre(), inner, _close())
    else:
        pattern = seq(_pre(), _body(name), _close())
    return _CharNFA(pattern)


def validate_grammar(name: str) -> str:
    """Check a grammar name parses (no tokenizer needed); returns the
    name.  The one validation rule every entry point shares — engine
    submit, serving schema, the mock engine."""
    if not isinstance(name, str) or not name:
        raise ValueError("grammar must be a non-empty string")
    compile_shape(name)
    return name


# -- token lift ------------------------------------------------------------
def token_strings(tokenizer, vocab_size: int) -> list[str]:
    """Per-id decoded strings for ids [0, vocab_size).  Ids the
    tokenizer cannot decode (vocab padding) and ids that decode to
    nothing (EOS/BOS/specials) come back as "" — the lift treats those
    as FREE-state-only tokens."""
    out: list[str] = []
    for i in range(vocab_size):
        try:
            s = tokenizer.decode([i])
        except Exception:   # noqa: BLE001 — padding ids past the real
            # vocab are legitimately undecodable
            s = ""
        out.append(s if isinstance(s, str) else "")
    return out


class GrammarSet:
    """The per-engine combined token-constraint tables.

    State 0 is the FREE state (every token allowed, self-loop) — it is
    both "no grammar on this row" and "grammar satisfied".  Each
    compiled grammar occupies a contiguous state range; compiling a new
    grammar bumps ``version`` so the engine re-uploads device tables.

    Single-owner like the engine that holds it: the driver thread
    compiles and walks; no locks.
    """

    def __init__(self, tokenizer, vocab_size: int):
        self.tokenizer = tokenizer
        self.vocab_size = int(vocab_size)
        self.eos_id = int(tokenizer.eos_id)
        self.version = 0
        self._token_strs: list[str] | None = None   # built lazily, once
        self._starts: dict[str, int] = {}
        free_mask = np.ones((1, self.vocab_size), np.bool_)
        free_next = np.zeros((1, self.vocab_size), np.int32)
        self.mask = free_mask           # [S, V] token allowed in state
        self.next = free_next           # [S, V] successor state
        self.forced = np.full(1, -1, np.int32)  # exactly-one-legal token

    def names(self) -> list[str]:
        return sorted(self._starts)

    @property
    def n_states(self) -> int:
        return self.mask.shape[0]

    def _strings(self) -> list[str]:
        if self._token_strs is None:
            self._token_strs = token_strings(self.tokenizer, self.vocab_size)
        return self._token_strs

    def ensure(self, name: str) -> int:
        """Compile ``name`` into the combined tables (idempotent);
        returns its start state.  Raises ``ValueError`` on unknown
        names."""
        if name in self._starts:
            return self._starts[name]
        nfa = compile_shape(name)
        strs = self._strings()
        offset = self.n_states
        # lazy subset construction over the token alphabet: discover
        # reachable frozenset-states by walking every token string
        idx: dict[frozenset, int] = {nfa.start_set: offset}
        order: list[frozenset] = [nfa.start_set]
        rows_mask: list[np.ndarray] = []
        rows_next: list[np.ndarray] = []
        cursor = 0
        while cursor < len(order):
            states = order[cursor]
            cursor += 1
            mask_row = np.zeros(self.vocab_size, np.bool_)
            next_row = np.zeros(self.vocab_size, np.int32)
            for tok, s in enumerate(strs):
                if not s:
                    continue        # specials/padding: FREE-state only
                cur = states
                dest = None
                for ch in s:
                    cur = nfa.advance(cur, ch)
                    if not cur:
                        break
                    if nfa.accept in cur:
                        dest = 0    # answer complete: rest is FREE
                        break
                else:
                    if cur:
                        if cur not in idx:
                            idx[cur] = offset + len(order)
                            order.append(cur)
                        dest = idx[cur]
                if dest is None:
                    continue
                mask_row[tok] = True
                next_row[tok] = dest
            if not mask_row.any():
                # dead end (tokenizer cannot spell the continuation):
                # degrade to EOS-only so the row ends instead of
                # emitting an arbitrary all-masked argmax
                mask_row[self.eos_id] = True
                next_row[self.eos_id] = 0
            rows_mask.append(mask_row)
            rows_next.append(next_row)
        self.mask = np.concatenate([self.mask, np.stack(rows_mask)], axis=0)
        self.next = np.concatenate([self.next, np.stack(rows_next)], axis=0)
        # canonical draft token per state: the only legal token when the
        # mask leaves one (accepted by construction), else the LONGEST
        # allowed token spelling a prefix of the state's deterministic
        # character chain — multi-char tokenizers spell "\n[/ANSWER]" in
        # one or two tokens, and a draft that merely segments the forced
        # text differently than the model costs one rejected position,
        # never a wrong token
        forced = np.full(self.n_states, -1, np.int32)
        forced[: len(self.forced)] = self.forced
        for states, s in idx.items():
            allowed = np.flatnonzero(self.mask[s])
            if len(allowed) == 1:
                forced[s] = allowed[0]
                continue
            chain = nfa.forced_chars(states)
            if not chain:
                continue
            best, best_len = -1, 0
            for tok in allowed:
                t = strs[tok] if tok < len(strs) else ""
                if t and len(t) > best_len and chain.startswith(t):
                    best, best_len = int(tok), len(t)
            forced[s] = best
        self.forced = forced
        self._starts[name] = offset
        self.version += 1
        return offset

    def start_state(self, name: str) -> int:
        return self.ensure(name)

    def allowed(self, state: int, token: int) -> bool:
        return bool(self.mask[state, token])

    def walk(self, state: int, tokens) -> int:
        """Advance a state along emitted tokens (host-side mirror of the
        in-jit table walk).  An out-of-table token — impossible for a
        masked row, possible for a FREE row — keeps/returns FREE."""
        for t in tokens:
            t = int(t)
            if state == 0:
                continue
            if 0 <= t < self.vocab_size and self.mask[state, t]:
                state = int(self.next[state, t])
            else:
                state = 0
        return state
