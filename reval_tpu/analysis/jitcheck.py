"""Runtime recompile sanitizer (``REVAL_TPU_JITCHECK=1``) + the always-on
compile-variant tracker behind ``reval_jit_*``.

The static ``jit`` pass proves the DECLARED compile contracts (static
args, bucketed axes, warmup budgets); what it cannot see is dynamic:
whether the decode loop actually stays inside its budget once real
shapes flow.  A silent recompile storm is the classic paged-engine perf
cliff — every new (steps, span, batch) combination retraces, the tick
stalls for seconds, and throughput craters with nothing in the logs.
Two layers close the gap (mirroring ``lockcheck``):

- :class:`TrackedJit` — ALWAYS ON, a thin wrapper the engines put
  around their jit entry points.  Per call it derives a shape-key
  signature (leaf shapes/dtypes + hashable statics via one
  ``tree_flatten``, ~µs at chunk cadence — never per token) and counts
  distinct variants:

  * every NEW signature bumps ``reval_jit_compiles_total``;
  * a new signature PAST the entry's declared ``warmup`` budget bumps
    ``reval_jit_cache_misses_total`` and emits a ``jit.recompile`` log
    event — so a post-warmup recompile is visible in ``/metrics``,
    bench JSON, and (via the log ring every postmortem bundle carries)
    the flight recorder, in production too.

- :class:`JitSanitizer` — test-time (``REVAL_TPU_JITCHECK=1`` via
  conftest, or ``install()`` directly).  While installed, every
  post-warmup variant is also recorded as a violation, and
  :func:`drive_guard` arms a device→host transfer guard over the paged
  engine's drive tick, so an implicit sync the ``hostsync`` pass could
  not see lexically (reached through a helper) raises loudly inside
  the tick that performed it.  The guard is two-layered because the
  CPU test backend's device→host "transfers" are zero-copy and
  invisible to jax's own guard machinery:

  * ``jax.transfer_guard_device_to_host("disallow")`` — the real
    backend guard; bites on an actual TPU.  Device→host ONLY: the tick
    legitimately feeds fresh host tokens INTO jitted entries every
    chunk, so a full ``transfer_guard("disallow")`` would outlaw the
    engine's own design.
  * a process-wide patch of the concrete ``jax.Array``'s
    ``item``/``tolist``/``__array__`` — the lockcheck approach (patch
    the primitive, observe every caller); trips on any backend, but
    only lexically INSIDE a guarded tick (thread-local depth), so
    tests and cold paths fetch freely.  (On CPU, numpy reads jax
    arrays zero-copy through the buffer protocol without calling
    ``__array__`` — ``np.asarray`` leaks are a TPU-guard catch; the
    patch's CPU bite surface is ``.item()``/``.tolist()``.)

  The one deliberate fetch per chunk is marked at the call site with
  :func:`deliberate_fetch` — the runtime twin of the static pass's
  ``# host-sync: <why>`` annotation.  Violations accumulate (a
  sanitizer must not change program behavior) and the conftest wiring
  fails the pytest session if any exist; a tripped guard ALSO raises,
  because silently continuing past an unplanned sync would time the
  wrong thing.

A new variant's signature is counted, not hashed away: ``variants``
per entry ride :meth:`PagedTPUEngine.jit_counters` into the bench
``jit`` block, which is what PERF.md's per-path compile-count baseline
pins.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager, nullcontext

from ..obs.logging import log_event
from ..obs.metrics import JIT_CACHE_MISSES, JIT_COMPILES

__all__ = ["TrackedJit", "tracked_jit", "JitSanitizer", "install",
           "uninstall", "current", "scoped", "drive_guard",
           "deliberate_fetch"]


class JitSanitizer:
    """Violation ledger for post-warmup recompiles and in-tick syncs."""

    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock (writes)
        # (conftest reads the ledger once, after the session drained)
        self.violations: list[dict] = []

    def record(self, entry: str, variants: int, warmup: int,
               signature) -> None:
        with self._lock:
            self.violations.append({
                "kind": "post-warmup-recompile",
                "entry": entry,
                "detail": f"jit entry {entry!r} compiled variant "
                          f"#{variants} past its warmup budget of "
                          f"{warmup} — signature {str(signature)[:300]}"})

    def record_transfer(self, detail: str) -> None:
        with self._lock:
            self.violations.append({
                "kind": "implicit-device-host-transfer",
                "entry": "<drive-tick>",
                "detail": detail})


_current: JitSanitizer | None = None

#: per-thread (guard depth, deliberate-fetch depth) for the d2h patch
_tls = threading.local()

#: (cls, attr, original) triples the d2h patch replaced
_PATCHED: list = []


def _guard_depth() -> int:
    return getattr(_tls, "guard_depth", 0)


def _fetch_depth() -> int:
    return getattr(_tls, "fetch_depth", 0)


def _d2h_wrapper(orig, label: str):
    def wrapper(self, *args, **kwargs):
        if _guard_depth() > 0 and _fetch_depth() == 0:
            detail = (f"implicit device->host transfer via "
                      f"Array.{label}() inside a guarded drive tick — "
                      f"mark a deliberate fetch with "
                      f"jitcheck.deliberate_fetch() and a "
                      f"'# host-sync: <why>' annotation")
            san = _current
            if san is not None:
                san.record_transfer(detail)
            raise RuntimeError(f"jitcheck: {detail}")
        return orig(self, *args, **kwargs)

    wrapper.__name__ = getattr(orig, "__name__", label)
    return wrapper


def _patch_d2h() -> None:
    """Patch the concrete jax.Array's device→host entry points (CPU
    d2h is zero-copy, so jax's own transfer guard never fires on the
    test backend — the patch keeps the sanitizer's bite
    backend-independent)."""
    if _PATCHED:
        return
    try:
        from jax._src.array import ArrayImpl
    except Exception:        # pragma: no cover — jax internals moved
        return
    for name in ("item", "tolist", "__array__"):
        orig = getattr(ArrayImpl, name, None)
        if orig is None:     # pragma: no cover — jax internals moved
            continue
        setattr(ArrayImpl, name, _d2h_wrapper(orig, name))
        _PATCHED.append((ArrayImpl, name, orig))


def _unpatch_d2h() -> None:
    while _PATCHED:
        cls, name, orig = _PATCHED.pop()
        setattr(cls, name, orig)


def install() -> JitSanitizer:
    """Activate the sanitizer (idempotent per process): post-warmup
    variants become violations, :func:`drive_guard` arms the transfer
    guards, and the d2h call surface is patched."""
    global _current
    if _current is None:
        _current = JitSanitizer()
        _patch_d2h()
    return _current


def uninstall() -> None:
    global _current
    _current = None
    _unpatch_d2h()


def current() -> JitSanitizer | None:
    return _current


@contextmanager
def scoped(active: bool = True):
    """Temporarily swap the process-global sanitizer: a FRESH ledger
    when ``active`` (or none at all when not), restoring whatever was
    installed before on exit.  This is how test_jitcheck exercises the
    sanitizer without polluting a session-level install — under
    ``REVAL_TPU_JITCHECK=1`` the conftest ledger must neither receive a
    test's deliberately-seeded violations nor be uninstalled mid-session
    by a fixture teardown."""
    global _current
    prev = _current
    _current = JitSanitizer() if active else None
    if active:
        _patch_d2h()
    else:
        _unpatch_d2h()
    try:
        yield _current
    finally:
        _current = prev
        if prev is not None:
            _patch_d2h()
        else:
            _unpatch_d2h()


class _DriveGuard:
    """Device→host guard over one drive tick (see module docstring)."""

    def __enter__(self):
        _tls.guard_depth = _guard_depth() + 1
        import jax

        self._tg = jax.transfer_guard_device_to_host("disallow")
        self._tg.__enter__()
        return self

    def __exit__(self, *exc):
        out = self._tg.__exit__(*exc)
        _tls.guard_depth = _guard_depth() - 1
        return out


class _FetchAllow:
    """The ONE deliberate fetch inside a guarded tick."""

    def __enter__(self):
        _tls.fetch_depth = _fetch_depth() + 1
        import jax

        self._tg = jax.transfer_guard_device_to_host("allow")
        self._tg.__enter__()
        return self

    def __exit__(self, *exc):
        out = self._tg.__exit__(*exc)
        _tls.fetch_depth = _fetch_depth() - 1
        return out


def drive_guard():
    """Arm the device→host guards while the sanitizer is installed,
    else a free nullcontext — the paged engine wraps each drive tick in
    this, so the threaded test modules (session-driven drives included)
    run the whole loop under the guard with no per-module wiring."""
    if _current is None:
        return nullcontext()
    return _DriveGuard()


def deliberate_fetch():
    """Mark an INTENDED device→host fetch inside a guarded tick — the
    runtime twin of the static ``# host-sync: <why>`` annotation (both
    belong at the same call site).  Free nullcontext when the sanitizer
    is off."""
    if _current is None:
        return nullcontext()
    return _FetchAllow()


def _signature(args: tuple, kwargs: dict):
    """Hashable shape-key of one call: array leaves become
    (shape, dtype); other hashable leaves ride as values; the treedef
    captures structure (None vs array operands retrace by contract)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None and hasattr(leaf, "dtype"):
            sig.append((tuple(shape), str(leaf.dtype)))
            continue
        try:
            hash(leaf)
            sig.append(leaf)
        except TypeError:
            sig.append(str(type(leaf)))
    return treedef, tuple(sig)


class TrackedJit:
    """Compile-variant counter around one jitted callable (see module
    docstring).  Jit attributes (``lower``, ``clear_cache``, ...)
    delegate to the wrapped function."""

    __slots__ = ("_fn", "name", "warmup", "_sigs", "_misses", "_registry",
                 "_san", "_lock", "calls")

    def __init__(self, name: str, fn, registry=None,
                 warmup: int | None = None, sanitizer=None):
        self._fn = fn
        self.name = name
        self.warmup = warmup
        #: total dispatches through this entry (every call, not just new
        #: variants) — the bench ``ragged`` block's dispatches-per-tick
        #: denominator and the one-dispatch-per-tick test observable
        self.calls = 0
        # guarded-by: _lock (writes)
        # (the pre-lock membership read is a benign double-checked
        # fast path: a miss re-checks under the lock before adding)
        self._sigs: set = set()
        # guarded-by: _lock (writes)
        self._misses = 0
        # registry may be the MetricsRegistry itself or a zero-arg
        # callable returning it — engines hand a callable because their
        # stats (and with them the registry) are replaced wholesale by
        # bench A/B phases; a captured registry would go stale and the
        # reval_jit_* counters would silently stop moving
        self._registry = registry
        self._san = sanitizer
        self._lock = threading.Lock()

    @property
    def variants(self) -> int:
        return len(self._sigs)

    @property
    def misses(self) -> int:
        """Post-warmup recompiles this entry observed (reset-proof:
        survives an ``EngineStats`` swap, unlike the registry counter)."""
        return self._misses

    def __call__(self, *args, **kwargs):
        self.note_call(args, kwargs)
        return self._fn(*args, **kwargs)

    def note_call(self, args: tuple, kwargs: dict):
        """Run the variant accounting for one call WITHOUT executing the
        wrapped function, and return the call's signature key.  The AOT
        cache wrapper (inference/tpu/aot_cache.py) dispatches to its own
        deserialized executables — it must keep the ``reval_jit_*``
        counting identical without paying the underlying jit a second
        compile."""
        self.calls += 1     # single-owner drive threads; diagnostic only
        key = _signature(args, kwargs)
        if key not in self._sigs:
            is_new = miss = False
            with self._lock:
                if key not in self._sigs:
                    self._sigs.add(key)
                    is_new = True
                    n = len(self._sigs)
                    if self.warmup is not None and n > self.warmup:
                        self._misses += 1
                        miss = True
            if is_new:
                reg = self._registry
                if callable(reg):
                    reg = reg()
                if reg is not None:
                    reg.counter(JIT_COMPILES).add(1)
                if miss:
                    if reg is not None:
                        reg.counter(JIT_CACHE_MISSES).add(1)
                    log_event("jit.recompile", level="warning",
                              entry=self.name, variants=n,
                              warmup=self.warmup)
                    san = self._san if self._san is not None else _current
                    if san is not None:
                        san.record(self.name, n, self.warmup, key)
        return key

    def __getattr__(self, item):
        return getattr(self._fn, item)


def tracked_jit(name: str, fn, registry=None, warmup: int | None = None,
                sanitizer=None) -> TrackedJit:
    """Wrap one jit entry point.  ``name``/``warmup`` must mirror the
    site's ``# jit-entry:`` annotation — the static ``jit`` pass
    cross-checks the literals."""
    return TrackedJit(name, fn, registry=registry, warmup=warmup,
                      sanitizer=sanitizer)
