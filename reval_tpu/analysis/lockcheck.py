"""Runtime lock sanitizer (``REVAL_TPU_LOCKCHECK=1``) — test-time only.

The static ``locks`` pass proves LEXICAL discipline (guarded accesses
sit inside the right ``with`` block); what it cannot see is dynamic:
the ORDER locks are acquired in across call chains (an A→B path in one
thread plus a B→A path in another is a deadlock waiting for the right
schedule), and writes that reach a guarded field through an alias or
helper the annotations never covered.  This module closes that gap at
test time:

- :class:`SanitizedLock` — a drop-in ``threading.Lock`` stand-in that
  records, per thread, the stack of held sanitized locks.  Acquiring B
  while holding A records the edge A→B (keyed by each lock's creation
  site, with a unique serial per instance); if the REVERSED edge was
  ever recorded, a ``lock-order-inversion`` violation is logged with
  both sites.  Detection is by edge set, not by blocking — the planted
  inversion in the tests is caught on a single thread, no deadlock
  schedule required.
- :func:`install` — patches ``threading.Lock`` so every lock created
  AFTER it (sessions, registries, chaos injectors built inside tests)
  is sanitized, and audits the annotated serving/obs classes:
  ``__setattr__`` on a ``# guarded-by:`` field verifies the named lock
  is held by the writing thread (constructors exempt, matching the
  static pass).  Guard maps are derived from the SAME annotations the
  static pass reads — one contract, two enforcement layers.
- violations accumulate on the sanitizer (never raised mid-test: a
  sanitizer must not change program behavior); the conftest wiring
  fails the pytest session if any exist.

Overhead is a couple of dict/list operations per acquire — fine for the
fast tier, and the whole machinery only exists behind the env flag; no
production path ever constructs it (PERF.md notes the flag is test-only).
"""

from __future__ import annotations

import os
import sys
import threading

__all__ = ["SanitizedLock", "LockSanitizer", "install", "uninstall",
           "audit_class", "audit_module"]

#: the REAL factory, captured before any install() can patch it
_REAL_LOCK = threading.Lock


class SanitizedLock:
    """``threading.Lock`` wrapper recording acquisition order + owner.

    Implements the full lock protocol (``acquire``/``release``/context
    manager/``locked``); ``threading.Condition`` works with it through
    its documented fallbacks (no ``_release_save``/``_is_owned`` needed).
    """

    __slots__ = ("_lock", "name", "serial", "_owner", "_san")

    def __init__(self, sanitizer: "LockSanitizer", name: str, serial: int):
        self._lock = _REAL_LOCK()
        self.name = name
        self.serial = serial
        self._owner: int | None = None
        self._san = sanitizer

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._owner = threading.get_ident()
            self._san._on_acquire(self)
        return ok

    def release(self) -> None:
        self._san._on_release(self)
        self._owner = None
        self._lock.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib locks grow this in 3.9+ and concurrent.futures calls it
        # at import (os.register_at_fork) — a wrapper without it breaks
        # `import concurrent.futures` under the sanitizer
        self._lock._at_fork_reinit()
        self._owner = None

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name} #{self.serial}>"


def _holds(lock) -> bool:
    """Best-effort "does the current thread hold this lock?" across the
    lock types a guarded class may own (SanitizedLock, Condition/RLock).
    Unknown types answer True — the sanitizer must never false-positive
    on a lock it cannot introspect."""
    if isinstance(lock, SanitizedLock):
        return lock.held_by_me()
    is_owned = getattr(lock, "_is_owned", None)     # Condition / RLock
    if is_owned is not None:
        try:
            return bool(is_owned())
        except Exception:
            return True
    return True


class LockSanitizer:
    """Shared state for a set of sanitized locks: the per-thread held
    stack, the acquisition-order edge set, and the violation ledger."""

    def __init__(self):
        self._tls = threading.local()
        self._state_lock = _REAL_LOCK()
        self._serial = 0
        #: (a_serial, b_serial) -> (a_name, b_name): a held while b taken
        self._edges: dict[tuple[int, int], tuple[str, str]] = {}
        self._reported: set = set()
        self.violations: list[dict] = []

    # -- lock factory ------------------------------------------------------
    def wrap(self, name: str | None = None) -> SanitizedLock:
        if name is None:
            frame = sys._getframe(1)
            name = (f"{os.path.basename(frame.f_code.co_filename)}:"
                    f"{frame.f_lineno}")
        with self._state_lock:
            self._serial += 1
            serial = self._serial
        return SanitizedLock(self, name, serial)

    # -- order tracking ----------------------------------------------------
    def _held_stack(self) -> list[SanitizedLock]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _on_acquire(self, lock: SanitizedLock) -> None:
        stack = self._held_stack()
        if stack:
            with self._state_lock:
                for held in stack:
                    if held.serial == lock.serial:
                        continue
                    edge = (held.serial, lock.serial)
                    self._edges.setdefault(edge, (held.name, lock.name))
                    rev = (lock.serial, held.serial)
                    if rev in self._edges and frozenset(edge) not in self._reported:
                        self._reported.add(frozenset(edge))
                        self.violations.append({
                            "kind": "lock-order-inversion",
                            "a": held.name, "b": lock.name,
                            "detail": f"{held.name} -> {lock.name} here, "
                                      f"but {lock.name} -> {held.name} "
                                      f"was also observed"})
        stack.append(lock)

    def _on_release(self, lock: SanitizedLock) -> None:
        stack = self._held_stack()
        # out-of-order releases are legal for locks; remove by identity
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                break

    # -- guarded-attribute audit ------------------------------------------
    def record_off_lock_write(self, cls_name: str, attr: str,
                              lockname: str, caller: str) -> None:
        self.violations.append({
            "kind": "off-lock-write",
            "a": f"{cls_name}.{attr}", "b": lockname,
            "detail": f"{caller}() wrote {cls_name}.{attr} without "
                      f"holding {lockname}"})


def audit_class(cls, guarded: dict[str, str],
                sanitizer: LockSanitizer):
    """Wrap ``cls.__setattr__``: writing a guarded attribute without the
    named lock held logs an ``off-lock-write`` violation.  Constructor
    writes are exempt (the static pass's rule); the lock attribute not
    existing yet (mid-``__init__`` ordering) also exempts.  Returns an
    undo callable."""
    orig = cls.__setattr__

    def checked_setattr(self, attr, value):
        lockname = guarded.get(attr)
        if lockname is not None:
            lock = getattr(self, lockname, None)
            if lock is not None and not _holds(lock):
                caller = sys._getframe(1).f_code.co_name
                if caller not in ("__init__", "__post_init__"):
                    sanitizer.record_off_lock_write(
                        cls.__name__, attr, lockname, caller)
        orig(self, attr, value)

    cls.__setattr__ = checked_setattr

    def undo():
        cls.__setattr__ = orig

    return undo


def _module_guard_maps(module) -> dict[str, dict[str, str]]:
    """class name -> {field: lock} derived from the module's static
    ``# guarded-by:`` annotations (writes-only guards included — writes
    always need the lock; ``# unguarded`` fields are skipped)."""
    import inspect

    from .core import SourceFile

    try:
        path = inspect.getsourcefile(module)
        with open(path) as f:
            src = SourceFile(path, os.path.basename(path), f.read())
    except (OSError, TypeError, SyntaxError):
        return {}
    out: dict[str, dict[str, str]] = {}
    for name, spec in src.annotations().guards.items():
        owner = spec.owner
        if owner == "<module>" or "." in owner or owner[:1].islower():
            continue                    # module/function-local guards
        out.setdefault(owner, {})[name] = spec.lock
    return out


def audit_module(module, sanitizer: LockSanitizer) -> list:
    """Audit every annotated class of ``module``; returns undo callables."""
    undos = []
    for cls_name, guarded in _module_guard_maps(module).items():
        cls = getattr(module, cls_name, None)
        if cls is not None and isinstance(cls, type):
            undos.append(audit_class(cls, guarded, sanitizer))
    return undos


#: modules whose annotated classes the conftest wiring audits — the
#: threaded serving/obs surface (dp_paged would drag jax in; its shared
#: state is function-local and covered by the static pass)
AUDIT_MODULES = (
    "reval_tpu.serving.session",
    "reval_tpu.serving.server",
    "reval_tpu.serving.router",
    "reval_tpu.obs.metrics",
    "reval_tpu.obs.trace",
    "reval_tpu.resilience.chaos",
    # the KV-tier store's copier thread (jax-free by design, so the
    # import is as safe as the others)
    "reval_tpu.inference.tpu.kv_tiers",
)

_installed: dict | None = None


def install(audit: bool = True) -> LockSanitizer:
    """Patch ``threading.Lock`` with the sanitizing factory and (with
    ``audit=True``) wrap the annotated classes' ``__setattr__``.
    Idempotent per process; returns the active sanitizer."""
    global _installed
    if _installed is not None:
        return _installed["sanitizer"]
    sanitizer = LockSanitizer()

    def make_lock():
        frame = sys._getframe(1)
        name = (f"{os.path.basename(frame.f_code.co_filename)}:"
                f"{frame.f_lineno}")
        return sanitizer.wrap(name)

    threading.Lock = make_lock
    undos = []
    if audit:
        import importlib

        for mod_name in AUDIT_MODULES:
            undos.extend(audit_module(importlib.import_module(mod_name),
                                      sanitizer))
    _installed = {"sanitizer": sanitizer, "undos": undos}
    return sanitizer


def uninstall() -> None:
    global _installed
    if _installed is None:
        return
    threading.Lock = _REAL_LOCK
    for undo in _installed["undos"]:
        undo()
    _installed = None
