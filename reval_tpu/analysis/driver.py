"""reval-lint driver: run the passes, apply suppressions, report.

One entry point for every namespace/discipline check in the tree —
``python tools/reval_lint.py`` and ``python -m reval_tpu lint`` both
land here, and the fast test tier pins the repo clean
(``tests/test_lint.py``).

Suppression policy: a violation is silenced only by an inline
``# lint: allow(<pass>) — <reason>`` on the violating line (or the
comment block directly above it).  The reason is mandatory; every used
suppression is counted and printed, so the report always states how much
of the tree is exempted and why.  A suppression whose pass reports NO
violation at that site is a ZOMBIE (the code it excused is gone or
fixed) and is itself reported — reasoned waivers cannot outlive their
reason.

Exit codes (stable, documented for pre-commit hooks):

- ``0`` — clean (no unsuppressed violations);
- ``1`` — at least one violation;
- ``2`` — unrunnable: unknown pass name, ``--changed-only`` outside a
  git work tree, or other usage errors.

``--json`` emits one machine-readable object (per-pass violation counts
and wall time, every violation/suppression, the zombie list) instead of
the human report; ``--changed-only`` scopes the REPORTED violations to
files touched per ``git status`` (all passes still run — cross-file
checks need the whole tree — so this trades nothing but output noise;
registry-level findings anchored at unchanged files are filtered, which
is why the full run stays the tier-1 authority).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import time
from dataclasses import dataclass, field

from . import (detmatrix, enginezoo, envreg, errboundary, goldenstreams,
               hostsync, hotpath, jitreg, kernelbench, locks, meshreg,
               reshard, tilecontract)
from .core import Suppression, Violation, collect_sources
from .metrics_events import run_events, run_metrics

__all__ = ["PASSES", "LintReport", "run_lint", "main"]

#: name -> pass callable ``(sources, root) -> [Violation]`` in run order
PASSES = {
    "locks": locks.run,
    "hotpath": hotpath.run,
    "jit": jitreg.run,
    "hostsync": hostsync.run,
    "tilecontract": tilecontract.run,
    "mesh": meshreg.run,
    "reshard": reshard.run,
    "enginezoo": enginezoo.run,
    "errors": errboundary.run,
    "env": envreg.run,
    "metrics": run_metrics,
    "events": run_events,
    "detmatrix": detmatrix.run,
    "kernelbench": kernelbench.run,
    "goldenstreams": goldenstreams.run,
}


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


@dataclass
class LintReport:
    root: str
    violations: list[Violation] = field(default_factory=list)
    suppressions: list[Suppression] = field(default_factory=list)
    per_pass: dict[str, int] = field(default_factory=dict)
    #: per-pass wall time, seconds (``--json`` surfaces it so slow-pass
    #: regressions are visible before they threaten the <10 s bar)
    pass_seconds: dict[str, float] = field(default_factory=dict)
    files: int = 0
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.violations


def run_lint(root: str | None = None,
             passes: list[str] | None = None) -> LintReport:
    """Run ``passes`` (default: all) over ``root`` (default: this repo)."""
    root = os.path.abspath(root or _repo_root())
    names = list(passes) if passes else list(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise ValueError(f"unknown lint pass(es) {unknown}; "
                         f"available: {sorted(PASSES)}")
    t0 = time.perf_counter()
    problems: list[tuple[str, str]] = []
    sources = collect_sources(root, problems)
    report = LintReport(root=root, files=len(sources))
    for rel, msg in problems:
        # an unparseable file is an UNLINTED file — never report "ok"
        # over a tree a pass could not actually see
        report.violations.append(Violation("parse", rel, 0, msg))
    #: (path, allow-line) pairs that silenced (or failed to reason for)
    #: at least one finding — everything else with an allow is a zombie
    used_allows: set[tuple[str, int]] = set()
    for name in names:
        p0 = time.perf_counter()
        found = PASSES[name](sources, root)
        kept = 0
        for v in found:
            src = sources.get(v.path)
            allow = (src.allowance(name, v.line)
                     if src is not None and v.line else None)
            if allow is None:
                report.violations.append(v)
                kept += 1
                continue
            reason, allow_line = allow
            used_allows.add((v.path, allow_line))
            if not reason:
                # an allow with no stated reason is itself a violation:
                # the suppression ledger is only useful if it explains
                report.violations.append(Violation(
                    name, v.path, allow_line,
                    f"suppression without a reason for: {v.message}"))
                kept += 1
                continue
            report.suppressions.append(Suppression(
                name, v.path, v.line, reason, v.message))
        report.per_pass[name] = kept
        report.pass_seconds[name] = time.perf_counter() - p0
    _check_zombie_allows(sources, names, used_allows, report)
    report.elapsed_s = time.perf_counter() - t0
    return report


def _check_zombie_allows(sources, names_run: list[str],
                         used: set[tuple[str, int]],
                         report: LintReport) -> None:
    """Stale-suppression detection: an ``# lint: allow`` whose pass(es)
    all ran and reported nothing at that site excused code that no
    longer needs excusing — flag it so the waiver dies with the code.
    An allow naming an unknown pass can never be used and is flagged
    outright (the classic typo'd-pass-name silent no-op)."""
    ran = set(names_run)
    all_passes = set(PASSES)
    for rel, src in sorted(sources.items()):
        for line, (names, _reason) in sorted(src.allows.items()):
            unknown = names - all_passes - {"*"}
            for bad in sorted(unknown):
                report.violations.append(Violation(
                    "suppression", rel, line,
                    f"allow names unknown pass {bad!r} — it can never "
                    f"match a finding (available: {sorted(PASSES)})"))
            if (rel, line) in used:
                continue
            covered = names - unknown
            if "*" in names:
                eligible = ran == all_passes
            else:
                eligible = bool(covered) and covered <= ran
            if eligible:
                report.violations.append(Violation(
                    "suppression", rel, line,
                    f"zombie suppression: no "
                    f"{'/'.join(sorted(covered)) or 'lint'} violation at "
                    f"this site — the code it excused is gone; remove "
                    f"the stale allow"))


def scope_to_files(report: LintReport, files: set[str]) -> LintReport:
    """A copy of ``report`` with violations/suppressions restricted to
    ``files`` (repo-relative, posix-normalised) — the ``--changed-only``
    fast path.  Per-pass counts are recomputed; files/timing stay."""
    norm = {f.replace("\\", "/") for f in files}
    scoped = LintReport(root=report.root, files=report.files,
                        elapsed_s=report.elapsed_s,
                        pass_seconds=dict(report.pass_seconds))
    scoped.violations = [v for v in report.violations
                         if v.path.replace("\\", "/") in norm]
    scoped.suppressions = [s for s in report.suppressions
                           if s.path.replace("\\", "/") in norm]
    for name, _count in report.per_pass.items():
        scoped.per_pass[name] = sum(1 for v in scoped.violations
                                    if v.pass_name == name)
    return scoped


def changed_files(root: str) -> set[str]:
    """Files touched vs HEAD (staged + unstaged) plus untracked ones —
    the pre-commit scope.  Raises ``RuntimeError`` outside a git tree."""
    out: set[str] = set()
    for args in (["diff", "--name-only", "HEAD"],
                 ["ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(["git", "-C", root] + args,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)} failed "
                f"({proc.stderr.strip() or 'not a git work tree?'}) — "
                f"--changed-only needs a git checkout")
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return out


def render(report: LintReport) -> str:
    lines = [f"reval-lint: {len(report.per_pass)} pass(es) over "
             f"{report.files} files in {report.elapsed_s:.2f}s"]
    width = max(len(n) for n in report.per_pass)
    for name, count in report.per_pass.items():
        n_sup = sum(1 for s in report.suppressions if s.pass_name == name)
        status = "ok" if count == 0 else f"{count} violation(s)"
        sup = f", {n_sup} suppressed" if n_sup else ""
        lines.append(f"  {name:<{width}}  {status}{sup}")
    for v in report.violations:
        lines.append(f"  - {v}")
    if report.suppressions:
        lines.append(f"suppressions in force "
                     f"({len(report.suppressions)}):")
        for s in report.suppressions:
            lines.append(f"  * {s}")
    lines.append("reval-lint: "
                 + ("ok" if report.ok
                    else f"FAIL ({len(report.violations)} violation(s))"))
    return "\n".join(lines)


def render_json(report: LintReport) -> str:
    """One machine-readable object: per-pass counts + wall time, every
    violation/suppression — the pre-commit/CI consumption format."""
    doc = {
        "ok": report.ok,
        "files": report.files,
        "elapsed_s": round(report.elapsed_s, 4),
        "passes": {
            name: {
                "violations": report.per_pass.get(name, 0),
                "suppressed": sum(1 for s in report.suppressions
                                  if s.pass_name == name),
                "elapsed_s": round(report.pass_seconds.get(name, 0.0), 4),
            } for name in report.per_pass
        },
        "violations": [
            {"pass": v.pass_name, "path": v.path, "line": v.line,
             "message": v.message} for v in report.violations
        ],
        "suppressions": [
            {"pass": s.pass_name, "path": s.path, "line": s.line,
             "reason": s.reason, "message": s.message}
            for s in report.suppressions
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def write_engine_matrix(root: str | None = None) -> str:
    """(Re)generate the committed engine feature-parity matrix
    (``ENGINE_SURFACE.md``); returns the path written."""
    root = os.path.abspath(root or _repo_root())
    sources = collect_sources(root)
    problems: list[Violation] = []
    infos = enginezoo.collect(sources, problems)
    if problems or not infos:
        raise RuntimeError("cannot build the engine matrix: "
                           + "; ".join(v.message for v in problems))
    path = os.path.join(root, enginezoo.ARTIFACT)
    with open(path, "w") as f:
        f.write(enginezoo.render_matrix(infos))
    return path


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="reval_tpu lint",
        description="Codebase-native static analysis: lock discipline, "
                    "hot-path purity, jit-entry registry, host-sync "
                    "discipline, Pallas tile contracts, mesh/sharding "
                    "contracts, reshard reasoning, engine-surface "
                    "conformance, typed-error boundary, env registry, "
                    "metric/event namespaces, determinism-matrix schema, "
                    "kernel-CI leaderboard schema, golden-stream "
                    "registry schema. "
                    "Exit codes: 0 clean, 1 violations, 2 unrunnable.")
    parser.add_argument("passes", nargs="*", metavar="PASS",
                        help=f"passes to run (default: all of "
                             f"{', '.join(PASSES)})")
    parser.add_argument("--root", default=None,
                        help="tree to lint (default: this repo).  NOTE: "
                             "the spec-backed passes (env/metrics/events) "
                             "always lint against THIS repo's in-process "
                             "ENV/METRICS/EVENTS declarations — on a "
                             "foreign tree their spec-vs-tree findings "
                             "are expected noise; name the AST passes "
                             "(locks/hotpath/errors) explicitly there")
    parser.add_argument("--list", action="store_true",
                        help="list available passes and exit")
    parser.add_argument("--json", action="store_true",
                        help="emit one machine-readable JSON object "
                             "(per-pass violations + wall time) instead "
                             "of the human report")
    parser.add_argument("--changed-only", action="store_true",
                        help="report only violations in files touched "
                             "per git status (fast pre-commit scope; "
                             "all passes still run — the full report "
                             "remains the authority)")
    parser.add_argument("--write-engine-matrix", action="store_true",
                        help="(re)generate ENGINE_SURFACE.md from the "
                             "tree and exit (the enginezoo pass fails "
                             "when the committed artifact is stale)")
    args = parser.parse_args(argv)
    if args.list:
        for name in PASSES:
            print(name)
        return 0
    if args.write_engine_matrix:
        try:
            print(write_engine_matrix(args.root))
        except RuntimeError as exc:
            print(f"reval-lint: {exc}")
            return 2
        return 0
    try:
        report = run_lint(args.root, args.passes or None)
    except ValueError as exc:
        print(f"reval-lint: {exc}")
        return 2
    if args.changed_only:
        try:
            changed = changed_files(report.root)
        except RuntimeError as exc:
            print(f"reval-lint: {exc}")
            return 2
        report = scope_to_files(report, changed)
    print(render_json(report) if args.json else render(report))
    return 0 if report.ok else 1
