"""reval-lint driver: run the passes, apply suppressions, report.

One entry point for every namespace/discipline check in the tree —
``python tools/reval_lint.py`` and ``python -m reval_tpu lint`` both
land here, and the fast test tier pins the repo clean
(``tests/test_lint.py``).

Suppression policy: a violation is silenced only by an inline
``# lint: allow(<pass>) — <reason>`` on the violating line (or the
comment block directly above it).  The reason is mandatory; every used
suppression is counted and printed, so the report always states how much
of the tree is exempted and why.
"""

from __future__ import annotations

import argparse
import os
import time
from dataclasses import dataclass, field

from . import (detmatrix, envreg, errboundary, hostsync, hotpath, jitreg,
               locks, tilecontract)
from .core import Suppression, Violation, collect_sources
from .metrics_events import run_events, run_metrics

__all__ = ["PASSES", "LintReport", "run_lint", "main"]

#: name -> pass callable ``(sources, root) -> [Violation]`` in run order
PASSES = {
    "locks": locks.run,
    "hotpath": hotpath.run,
    "jit": jitreg.run,
    "hostsync": hostsync.run,
    "tilecontract": tilecontract.run,
    "errors": errboundary.run,
    "env": envreg.run,
    "metrics": run_metrics,
    "events": run_events,
    "detmatrix": detmatrix.run,
}


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


@dataclass
class LintReport:
    root: str
    violations: list[Violation] = field(default_factory=list)
    suppressions: list[Suppression] = field(default_factory=list)
    per_pass: dict[str, int] = field(default_factory=dict)
    files: int = 0
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.violations


def run_lint(root: str | None = None,
             passes: list[str] | None = None) -> LintReport:
    """Run ``passes`` (default: all) over ``root`` (default: this repo)."""
    root = os.path.abspath(root or _repo_root())
    names = list(passes) if passes else list(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise ValueError(f"unknown lint pass(es) {unknown}; "
                         f"available: {sorted(PASSES)}")
    t0 = time.perf_counter()
    problems: list[tuple[str, str]] = []
    sources = collect_sources(root, problems)
    report = LintReport(root=root, files=len(sources))
    for rel, msg in problems:
        # an unparseable file is an UNLINTED file — never report "ok"
        # over a tree a pass could not actually see
        report.violations.append(Violation("parse", rel, 0, msg))
    for name in names:
        found = PASSES[name](sources, root)
        kept = 0
        for v in found:
            src = sources.get(v.path)
            allow = (src.allowance(name, v.line)
                     if src is not None and v.line else None)
            if allow is None:
                report.violations.append(v)
                kept += 1
                continue
            reason, allow_line = allow
            if not reason:
                # an allow with no stated reason is itself a violation:
                # the suppression ledger is only useful if it explains
                report.violations.append(Violation(
                    name, v.path, allow_line,
                    f"suppression without a reason for: {v.message}"))
                kept += 1
                continue
            report.suppressions.append(Suppression(
                name, v.path, v.line, reason, v.message))
        report.per_pass[name] = kept
    report.elapsed_s = time.perf_counter() - t0
    return report


def render(report: LintReport) -> str:
    lines = [f"reval-lint: {len(report.per_pass)} pass(es) over "
             f"{report.files} files in {report.elapsed_s:.2f}s"]
    width = max(len(n) for n in report.per_pass)
    for name, count in report.per_pass.items():
        n_sup = sum(1 for s in report.suppressions if s.pass_name == name)
        status = "ok" if count == 0 else f"{count} violation(s)"
        sup = f", {n_sup} suppressed" if n_sup else ""
        lines.append(f"  {name:<{width}}  {status}{sup}")
    for v in report.violations:
        lines.append(f"  - {v}")
    if report.suppressions:
        lines.append(f"suppressions in force "
                     f"({len(report.suppressions)}):")
        for s in report.suppressions:
            lines.append(f"  * {s}")
    lines.append("reval-lint: "
                 + ("ok" if report.ok
                    else f"FAIL ({len(report.violations)} violation(s))"))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="reval_tpu lint",
        description="Codebase-native static analysis: lock discipline, "
                    "hot-path purity, jit-entry registry, host-sync "
                    "discipline, Pallas tile contracts, typed-error "
                    "boundary, env registry, metric/event namespaces, "
                    "determinism-matrix schema")
    parser.add_argument("passes", nargs="*", metavar="PASS",
                        help=f"passes to run (default: all of "
                             f"{', '.join(PASSES)})")
    parser.add_argument("--root", default=None,
                        help="tree to lint (default: this repo).  NOTE: "
                             "the spec-backed passes (env/metrics/events) "
                             "always lint against THIS repo's in-process "
                             "ENV/METRICS/EVENTS declarations — on a "
                             "foreign tree their spec-vs-tree findings "
                             "are expected noise; name the AST passes "
                             "(locks/hotpath/errors) explicitly there")
    parser.add_argument("--list", action="store_true",
                        help="list available passes and exit")
    args = parser.parse_args(argv)
    if args.list:
        for name in PASSES:
            print(name)
        return 0
    try:
        report = run_lint(args.root, args.passes or None)
    except ValueError as exc:
        print(f"reval-lint: {exc}")
        return 2
    print(render(report))
    return 0 if report.ok else 1
