"""Host-sync discipline pass (``hostsync``).

The paged engine's throughput design is "one dispatch, one fetch per
chunk": every OTHER device→host transfer inside a drive tick is a
hidden synchronization point that stalls the dispatch pipeline for a
full tunnel RTT (~100 ms on the tunneled v5e — PERF.md round 5 measured
the per-chunk host cost dominating decode).  The same APIs inside a
JITTED body are worse: forcing a tracer concrete either crashes at
trace time or constant-folds a device value into the compiled program.

Scope (lexical, nested defs included):

- functions marked ``# hot-path`` — the host half of the drive loop;
- jit-entry bodies — the ``def`` a ``# jit-entry:`` annotation compiles
  (the decorated function, or the same-file target a ``jax.jit(f)`` /
  ``partial(f, ...)`` / ``shard_map(f)`` names).

Banned calls:

- ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` on anything —
  each is a synchronous device→host round trip;
- ``jax.device_get`` and ``np.asarray`` / ``np.array`` /
  ``np.ascontiguousarray`` — the explicit transfer spellings; legal at
  the few deliberate fetch points, which must say so (below);
- inside jit bodies only: bare ``float()`` / ``int()`` / ``bool()``
  applied to a traced parameter — Python-level concretization of a
  tracer (static and partial-bound parameters are exempt: those are
  Python values at trace time).

Suppression: the deliberate sites carry an inline
``# host-sync: <why>`` (same line or the comment block above).  The
reason is mandatory — a bare marker is itself a violation — mirroring
the driver's ``# lint: allow`` policy but keeping the hot-path fetch
points self-documenting at the call site.  The runtime twin is the
jitcheck sanitizer's ``jax.transfer_guard`` over the drive tick
(``REVAL_TPU_JITCHECK=1``): what this pass cannot see lexically (a
transfer reached through a helper) trips the guard at test time.
"""

from __future__ import annotations

import ast
import re

from .core import SourceFile, Violation
from .core import call_chain as _call_chain
from . import jitreg

PASS = "hostsync"

_HOSTSYNC_RE = re.compile(r"#\s*host-sync\s*(?:[:—])\s*(\S.*)?$")

#: attribute tails that are a device→host sync on any receiver
_SYNC_TAILS = {"item", "tolist", "block_until_ready"}

#: (module root, tail) explicit-transfer spellings
_TRANSFER_CALLS = {("jax", "device_get"), ("np", "asarray"), ("np", "array"),
                   ("np", "ascontiguousarray"), ("numpy", "asarray"),
                   ("numpy", "array"), ("numpy", "ascontiguousarray")}

_CONCRETIZERS = {"float", "int", "bool"}



def _suppressed(src: SourceFile, line: int,
                out: list[Violation]) -> bool:
    """True when a reasoned ``# host-sync:`` covers ``line``; a marker
    WITHOUT a reason reports and still suppresses nothing."""
    for ln, comment in src.comment_block(line):
        m = _HOSTSYNC_RE.search(comment)
        if m:
            if not (m.group(1) or "").strip():
                out.append(Violation(
                    PASS, src.rel, ln,
                    "host-sync suppression without a reason — say WHY "
                    "this transfer is deliberate"))
                return False
            return True
    return False


def _check_body(src: SourceFile, fn, label: str, traced: set,
                out: list[Violation]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _call_chain(node.func)
        if not chain:
            continue
        denied = None
        if chain[-1] in _SYNC_TAILS and len(chain) > 1:
            denied = ".".join(chain)
        elif len(chain) >= 2 and (chain[0], chain[-1]) in _TRANSFER_CALLS:
            denied = ".".join(chain)
        elif (traced and len(chain) == 1 and chain[0] in _CONCRETIZERS
              and node.args):
            hit = sorted({n.id for n in ast.walk(node.args[0])
                          if isinstance(n, ast.Name) and n.id in traced})
            if hit:
                denied = (f"{chain[0]}() on traced parameter(s) "
                          f"{', '.join(hit)}")
        if denied is None:
            continue
        if _suppressed(src, node.lineno, out):
            continue
        out.append(Violation(
            PASS, src.rel, node.lineno,
            f"{label} performs an implicit device->host sync via "
            f"{denied} — move it off the hot path or mark the "
            f"deliberate fetch with '# host-sync: <why>'"))


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    for rel, src in sorted(sources.items()):
        if not rel.replace("\\", "/").startswith("reval_tpu"):
            continue
        ann = src.annotations()
        checked: set[int] = set()

        # hot-path host functions: the explicit-transfer APIs are the
        # hazard; Python float()/int() on host numpy values are fine
        if ann.hot:
            def walk(body, qual):
                for node in body:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fq = f"{qual}.{node.name}" if qual else node.name
                        if fq in ann.hot and id(node) not in checked:
                            checked.add(id(node))
                            _check_body(src, node,
                                        f"hot-path function {fq!r}",
                                        set(), out)
                        else:
                            walk(node.body, fq)
                    elif isinstance(node, ast.ClassDef):
                        walk(node.body, node.name)

            walk(src.tree.body, "")

        # jit-entry bodies: also ban Python concretization of tracers
        if jitreg.in_scope(rel):
            for entry in jitreg.collect_entries(src, None):
                fn = entry.target
                if fn is None or id(fn) in checked:
                    continue
                checked.add(id(fn))
                named, structural = jitreg._param_names(fn)
                traced = (named - set(entry.static or ())
                          - entry.bound - structural)
                _check_body(src, fn, f"jit entry {entry.name!r} body",
                            traced, out)
    return out
