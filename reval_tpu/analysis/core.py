"""Lint-framework core: parsed sources, annotations, suppressions.

Every pass consumes :class:`SourceFile` objects — the parsed AST plus a
line-indexed comment map (comments are where the contracts live: the
``# guarded-by:`` / ``# lock-held:`` / ``# hot-path`` annotations and
the ``# lint: allow(<pass>) — <reason>`` suppressions).  Comments come
from :mod:`tokenize`, not regexes over raw lines, so a ``#`` inside a
string literal can never masquerade as an annotation.

Suppression policy (ISSUE 6): a finding is only silenced by an inline
``# lint: allow(<pass>) — <reason>`` on the violating line or the
contiguous comment block directly above it.  The REASON is mandatory —
an allow without one is itself reported — and the driver counts every
suppression used so the report always says how much of the tree is
exempted, and why.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

__all__ = ["Violation", "Suppression", "SourceFile", "Annotations",
           "collect_sources", "GuardSpec", "call_chain"]

#: the suppression marker: allow(<passes>) followed by a mandatory reason
#: (the regexes below are written so their OWN doc comments cannot be
#: mistaken for annotations — never spell a full marker in a comment here)
_ALLOW_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*([a-z_,\s-]+?)\s*\)\s*(?:[—:–-]+\s*(\S.*))?$")

#: the guarded-field marker, with an optional writes-only qualifier
_GUARDED_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)\s*(\(writes\))?")

#: the deliberately-lock-free marker (reason after the colon)
_UNGUARDED_RE = re.compile(r"#\s*unguarded\s*[:—]")

#: the caller-holds-my-lock marker on a def line
_LOCKHELD_RE = re.compile(r"#\s*lock-held:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: the hot-function marker on a def line
_HOT_RE = re.compile(r"#\s*hot-path\b")


@dataclass
class Violation:
    pass_name: str
    path: str               # repo-relative
    line: int
    message: str

    def __str__(self) -> str:
        where = f"{self.path}:{self.line}" if self.line else self.path
        return f"{where}: [{self.pass_name}] {self.message}"


@dataclass
class Suppression:
    """One *used* ``# lint: allow`` (driver-counted and reported)."""

    pass_name: str
    path: str
    line: int
    reason: str
    message: str            # the finding it silenced

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_name}] "
                f"allowed — {self.reason}")


@dataclass
class GuardSpec:
    """One ``# guarded-by:`` declaration."""

    fieldname: str
    lock: str
    writes_only: bool
    line: int
    owner: str              # class name, function name, or "<module>"


@dataclass
class Annotations:
    """Everything the comment annotations of one file declare."""

    guards: dict[str, GuardSpec] = field(default_factory=dict)
    unguarded: set[str] = field(default_factory=set)
    #: lock names owned per scope: {"ClassName" | "<module>": {lock, ...}}
    locks: dict[str, set[str]] = field(default_factory=dict)
    #: function qualnames marked ``# lock-held: L`` -> lock name
    lock_held: dict[str, str] = field(default_factory=dict)
    #: function qualnames marked ``# hot-path``
    hot: set[str] = field(default_factory=set)
    #: annotation problems found while extracting (duplicate guards, …)
    problems: list[tuple[int, str]] = field(default_factory=list)


class SourceFile:
    """One parsed python file: text, AST, comments, suppressions."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self._lines = text.splitlines()
        self.tree = ast.parse(text)
        #: line -> raw comment text (without leading whitespace)
        self.comments: dict[int, str] = {}
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                self.comments[tok.start[0]] = tok.string
        #: line -> ({pass names} | {"*"}, reason or "")
        self.allows: dict[int, tuple[set[str], str]] = {}
        for line, comment in self.comments.items():
            m = _ALLOW_RE.search(comment)
            if m:
                names = {n.strip() for n in m.group(1).split(",") if n.strip()}
                reason = (m.group(2) or "").strip()
                # a reason may wrap onto following full-comment lines —
                # the ledger must carry the whole explanation
                nxt = line + 1
                while (nxt in self.comments
                       and nxt <= len(self._lines)
                       and self._lines[nxt - 1].lstrip().startswith("#")
                       and not _ALLOW_RE.search(self.comments[nxt])):
                    reason = (reason + " "
                              + self.comments[nxt].lstrip("# ").strip()).strip()
                    nxt += 1
                self.allows[line] = (names, reason)
        self._annotations: Annotations | None = None

    # -- comment lookups ---------------------------------------------------
    def comment_block(self, line: int) -> list[tuple[int, str]]:
        """The comment on ``line`` plus the contiguous comment block
        directly above it (annotations may ride either)."""
        out = []
        if line in self.comments:
            out.append((line, self.comments[line]))
        above = line - 1
        while above in self.comments:
            # only count FULL comment lines above (a trailing comment on
            # an unrelated statement must not leak downward)
            if (0 < above <= len(self._lines)
                    and self._lines[above - 1].lstrip().startswith("#")):
                out.append((above, self.comments[above]))
                above -= 1
            else:
                break
        return out

    def allowance(self, pass_name: str, line: int) -> tuple[str, int] | None:
        """(reason, line) when an allow for ``pass_name`` covers ``line``
        — same line or the contiguous comment block above."""
        for ln, _ in self.comment_block(line):
            hit = self.allows.get(ln)
            if hit and (pass_name in hit[0] or "*" in hit[0]):
                return hit[1], ln
        return None

    # -- annotations -------------------------------------------------------
    def annotations(self) -> Annotations:
        if self._annotations is None:
            self._annotations = _extract_annotations(self)
        return self._annotations


def _target_name(node: ast.stmt) -> tuple[str | None, bool]:
    """(name, is_self_attr) of a simple assignment statement target."""
    targets: list[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    for t in targets:
        if isinstance(t, ast.Name):
            return t.id, False
        if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            return t.attr, True
    return None, False


def _is_lock_ctor(value: ast.expr | None) -> bool:
    """Does this expression construct a threading Lock/RLock/Condition
    (anywhere inside it — ``Lock() if x else nullcontext()`` counts)?"""
    if value is None:
        return False
    for node in ast.walk(value):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("Lock", "RLock", "Condition")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("threading", "_threading")):
            return True
    return False


def _extract_annotations(src: SourceFile) -> Annotations:
    ann = Annotations()
    ann.locks = {}

    def scan_stmt(node: ast.stmt, class_owner: str,
                  local_owner: str) -> None:
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            return
        name, is_self = _target_name(node)
        if name is None:
            return
        owner = class_owner if is_self else local_owner
        value = getattr(node, "value", None)
        if _is_lock_ctor(value):
            ann.locks.setdefault(owner, set()).add(name)
            return
        block = src.comment_block(node.lineno)
        for _, comment in block:
            m = _GUARDED_RE.search(comment)
            if m:
                spec = GuardSpec(name, m.group(1), bool(m.group(2)),
                                 node.lineno, owner)
                prev = ann.guards.get(name)
                if prev is not None and (prev.lock != spec.lock
                                         or prev.writes_only != spec.writes_only):
                    ann.problems.append(
                        (node.lineno,
                         f"field {name!r} declared guarded-by {spec.lock!r} "
                         f"here but guarded-by {prev.lock!r} at line "
                         f"{prev.line} — one field, one lock"))
                ann.guards.setdefault(name, spec)
                return
            if _UNGUARDED_RE.search(comment):
                ann.unguarded.add(name)
                return

    def scan_body(body: list[ast.stmt], class_owner: str,
                  local_owner: str, qual: str) -> None:
        for node in body:
            scan_stmt(node, class_owner, local_owner)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fq = f"{qual}.{node.name}" if qual else node.name
                for _, comment in src.comment_block(node.lineno):
                    if _HOT_RE.search(comment):
                        ann.hot.add(fq)
                    m = _LOCKHELD_RE.search(comment)
                    if m:
                        ann.lock_held[fq] = m.group(1)
                # inside a function: self.X stays with the class, plain
                # names (dp_paged's local work queue) are function-scoped
                scan_body(node.body, class_owner, fq, fq)
            elif isinstance(node, ast.ClassDef):
                scan_body(node.body, node.name, node.name, node.name)
            else:
                # annotated assignments may sit inside if/with/try/for
                # blocks (conditional construction) — descend so their
                # guards register with the same owners
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(node, attr, None)
                    if sub:
                        scan_body(sub, class_owner, local_owner, qual)
                for handler in getattr(node, "handlers", []) or []:
                    scan_body(handler.body, class_owner, local_owner, qual)

    scan_body(src.tree.body, "<module>", "<module>", "")
    return ann


#: directories/files collected relative to the repo root
SCAN_DIRS = ("reval_tpu", "tools")
SCAN_FILES = ("bench.py", "__graft_entry__.py")


def collect_sources(root: str,
                    problems: list[tuple[str, str]] | None = None,
                    ) -> dict[str, SourceFile]:
    """rel-path -> SourceFile over the lintable tree (``reval_tpu/``,
    ``tools/``, ``bench.py``).  A file that cannot be parsed is recorded
    into ``problems`` (when given) — the driver turns those into
    violations, because a skipped file is an UNLINTED file and
    ``reval-lint: ok`` must never be printed over one silently."""
    out: dict[str, SourceFile] = {}
    paths: list[str] = [os.path.join(root, f) for f in SCAN_FILES]
    for d in SCAN_DIRS:
        for dirpath, _, filenames in os.walk(os.path.join(root, d)):
            if "__pycache__" in dirpath:
                continue
            paths.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                         if f.endswith(".py"))
    for path in paths:
        rel = os.path.relpath(path, root)
        try:
            with open(path) as f:
                text = f.read()
        except OSError as exc:
            if problems is not None and os.path.exists(path):
                problems.append((rel, f"cannot read: {exc}"))
            continue
        try:
            out[rel] = SourceFile(path, rel, text)
        except SyntaxError as exc:
            if problems is not None:
                problems.append((rel, f"cannot parse: {exc}"))
    return out


def call_chain(func) -> list:
    """Dotted call chain, outermost first: ``a.b.c(...)`` -> [a, b, c];
    non-name links truncate the front.  Shared by every AST pass that
    pattern-matches call sites (hotpath/hostsync/jit/tilecontract)."""
    import ast

    parts: list = []
    while isinstance(func, ast.Attribute):
        parts.append(func.attr)
        func = func.value
    if isinstance(func, ast.Name):
        parts.append(func.id)
    return list(reversed(parts))
