"""reval-lint: codebase-native static analysis for the serving stack.

The serving/observability arc (PRs 1–5) accumulated invariants that live
only in prose: which fields each ``threading.Lock`` guards, which calls
are allowed inside the ~µs drive-tick hot path, which exceptions may
cross the HTTP boundary, and which ``REVAL_TPU_*`` env knobs exist.
This package turns each of those contracts into an AST-level lint pass
over the tree, plus a runtime lock sanitizer for what static analysis
cannot see (acquisition ORDER, cross-thread writes at test time):

- :mod:`.locks`       — lock-discipline / race detector over
  ``# guarded-by:`` annotations;
- :mod:`.hotpath`     — no blocking/allocating calls in ``# hot-path``
  functions;
- :mod:`.errboundary` — the serving layer raises only the
  ``serving/errors.py`` taxonomy;
- :mod:`.envreg`      — every ``REVAL_TPU_*`` read goes through the
  declared ``reval_tpu/env.py::ENV`` spec, round-tripped against the
  README table;
- :mod:`.metrics_events` — the METRICS/EVENTS namespace checks that
  previously lived in ``tools/check_metrics.py``, migrated into the
  same pass framework (one driver, one report format);
- :mod:`.lockcheck`   — the runtime sanitizer (``REVAL_TPU_LOCKCHECK=1``).

Run everything with ``python tools/reval_lint.py`` or
``python -m reval_tpu lint``; the framework lives in :mod:`.core` and
the driver in :mod:`.driver`.
"""

from .core import Annotations, SourceFile, Suppression, Violation, collect_sources
from .driver import PASSES, run_lint

__all__ = ["Annotations", "SourceFile", "Suppression", "Violation",
           "collect_sources", "PASSES", "run_lint"]
