"""reval-lint: codebase-native static analysis for the serving stack.

The serving/observability arc (PRs 1–5) accumulated invariants that live
only in prose: which fields each ``threading.Lock`` guards, which calls
are allowed inside the ~µs drive-tick hot path, which exceptions may
cross the HTTP boundary, and which ``REVAL_TPU_*`` env knobs exist.
This package turns each of those contracts into an AST-level lint pass
over the tree, plus a runtime lock sanitizer for what static analysis
cannot see (acquisition ORDER, cross-thread writes at test time):

- :mod:`.locks`       — lock-discipline / race detector over
  ``# guarded-by:`` annotations;
- :mod:`.hotpath`     — no blocking/allocating calls in ``# hot-path``
  functions;
- :mod:`.jitreg`      — every ``jax.jit``/``shard_map`` constructor in
  the compiled core declares its ``# jit-entry:`` contract (static
  args, pow2-bucketed axes, warmup budget); no traced-value Python
  branching in annotated bodies;
- :mod:`.hostsync`    — no implicit device→host syncs in ``# hot-path``
  regions or jit-entry bodies (deliberate fetches carry
  ``# host-sync: <why>``);
- :mod:`.tilecontract` — every ``pallas_call`` in ``ops/`` declares a
  ``# tile: (sublane, lane)`` contract; resolvable BlockSpec/VMEM dims
  are lane/sublane-aligned;
- :mod:`.meshreg`     — every ``Mesh``/``NamedSharding``/
  ``PartitionSpec``/``shard_map`` constructor in the sharded core is
  covered by a ``# mesh: axes=(..)`` contract resolved against the
  ``parallel/mesh.py::AXES`` registry; shard_map ``in=``/``out=``
  specs round-trip; collectives name a contract axis;
- :mod:`.reshard`     — ``with_sharding_constraint`` / hot-region
  ``device_put`` / zero-arg ``PartitionSpec()`` carry a reasoned
  ``# reshard: <why>``;
- :mod:`.enginezoo`   — every engine class implements, delegates, or
  reasons away (``# not-supported:``) each declared surface member;
  the committed ``ENGINE_SURFACE.md`` parity matrix stays fresh;
- :mod:`.errboundary` — the serving layer raises only the
  ``serving/errors.py`` taxonomy;
- :mod:`.envreg`      — every ``REVAL_TPU_*`` read goes through the
  declared ``reval_tpu/env.py::ENV`` spec, round-tripped against the
  README table;
- :mod:`.kernelbench`  — kernel-CI leaderboard artifacts
  (``kernelbench-<ts>.json`` / ``KERNELBENCH_r*.json``) conform to the
  ``reval-kernelbench-v1`` schema: complete cell matrix, stale entries
  carry last-known value + commit, never a 0.0;
- :mod:`.metrics_events` — the METRICS/EVENTS namespace checks that
  previously lived in ``tools/check_metrics.py``, migrated into the
  same pass framework (one driver, one report format);
- :mod:`.lockcheck`   — the runtime lock sanitizer
  (``REVAL_TPU_LOCKCHECK=1``);
- :mod:`.jitcheck`    — the runtime recompile sanitizer + always-on
  compile-variant tracker (``REVAL_TPU_JITCHECK=1``);
- :mod:`.shardcheck`  — the runtime sharding sanitizer + always-on
  declared-vs-actual sharding counters (``REVAL_TPU_SHARDCHECK=1``).

Run everything with ``python tools/reval_lint.py`` or
``python -m reval_tpu lint``; the framework lives in :mod:`.core` and
the driver in :mod:`.driver`.
"""

# The production engines import the runtime half of this package
# (``analysis.jitcheck`` wraps their jit entry points), so the package
# __init__ must NOT eagerly pull in the lint framework — PEP 562 lazy
# attribute access keeps ``import reval_tpu.analysis.jitcheck`` free of
# the nine pass modules and the argparse/ast driver machinery.
_EXPORTS = {
    "Annotations": "core", "SourceFile": "core", "Suppression": "core",
    "Violation": "core", "collect_sources": "core",
    "PASSES": "driver", "run_lint": "driver",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module

    return getattr(import_module(f".{mod}", __name__), name)
