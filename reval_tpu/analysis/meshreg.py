"""Mesh-discipline pass (``mesh``).

ROADMAP item 3 collapses the nine-engine parallelism zoo into ONE
mesh-native paged engine over an explicit ``Mesh`` +
``NamedSharding``/``shard_map`` — a refactor that rewrites exactly the
axis names, partition specs, and collectives this pass pins down.
Today those contracts live in scattered string literals: a typo'd axis
(``"ttp"``) surfaces as a runtime XLA "unbound axis name" error deep in
a trace, and a spec drifting from its ``shard_map``'s ``in_specs`` is
the silent-resharding divergence the backend-reproducibility study
(PAPERS.md, arxiv 2605.19537) shows corrupting bit-identical parity.

The one registry is ``reval_tpu/parallel/mesh.py::AXES`` — a literal
dict of the canonical axis names (dp/pp/sp/ep/tp), read from the AST so
lint stays jax-free.  Every ``Mesh`` / ``NamedSharding`` /
``PartitionSpec`` / ``*shard_map`` constructor in the sharded core
(``parallel/``, ``models/``, ``inference/tpu/``) must be covered by a
one-line contract:

    # mesh: axes=(pp) in=(P(pp), P()) out=(P(),) via=(axis_name)

anchored on the constructor's statement (or the comment block above
it), or on the enclosing ``def`` — a def-level contract covers every
constructor and collective in the function body, which is how spec-rule
tables (``parallel/sharding.py``) declare once instead of per line.

Grammar (one line, statement-level wins over def-level):

- ``axes=(a, b)`` — mandatory.  The axis names this region may place or
  reduce over; each must be registered in ``AXES``, and every literal
  axis string inside a covered constructor must be in this set (a
  literal under ``axes=()`` is a violation).
- ``in=(...)`` / ``out=(...)`` — mandatory for ``shard_map``
  constructors.  Either the literal spec list, which must round-trip
  EXACTLY against the call's ``in_specs``/``out_specs`` literals
  (quotes and whitespace are normalised: ``P(pp)`` ≡ ``P("pp")``), or
  the word ``dynamic`` when the call computes its specs — but declaring
  ``dynamic`` over literal call specs (or literal specs over a computed
  call) is a violation: the annotation must be as precise as the code
  allows.
- ``via=(p, q)`` — parameter names through which axis names flow at
  call time (ring attention's ``axis_name``).  A collective whose axis
  argument is one of these names is accepted; any other non-literal
  axis is a violation.

Collectives (``lax.psum`` / ``pmean`` / ``pmax`` / ``pmin`` /
``all_gather`` / ``ppermute`` / ``all_to_all`` / ``pshuffle`` /
``psum_scatter`` / ``axis_index`` / ``pcast``) must sit inside a
contract and name an axis from it — literally, or through ``via=``.  A
collective outside any contract, or naming an undeclared axis, is a
lint violation instead of a runtime XLA error.

Suppression: ``# lint: allow(mesh) — <reason>`` (driver policy).
"""

from __future__ import annotations

import ast
import re

from .core import SourceFile, Violation
from .core import call_chain as _call_chain

PASS = "mesh"

#: directories whose mesh constructors must be declared
SCOPE_PREFIXES = ("reval_tpu/parallel/", "reval_tpu/models/",
                  "reval_tpu/inference/tpu/")

#: where the axis registry lives (parsed from the AST, never imported)
AXES_FILE = "reval_tpu/parallel/mesh.py"

#: jax.lax collective tails and where their axis argument sits
#: (positional index; kwarg fallbacks are handled uniformly)
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "ppermute": 1, "all_to_all": 1, "pshuffle": 1, "psum_scatter": 1,
    "axis_index": 0, "pcast": 1,
}

#: constructor class names (attribute tails); bare-name calls count
#: only when the file imports the class (possibly aliased)
_CTOR_NAMES = {"PartitionSpec", "NamedSharding", "Mesh"}

_MESH_RE = re.compile(r"#\s*mesh:\s*(.*)$")
_KEY_RE = re.compile(r"(axes|in|out|via)=\(")


class Contract:
    """One parsed ``# mesh:`` annotation."""

    def __init__(self, line: int):
        self.line = line
        self.axes: set[str] | None = None
        self.in_specs: list[str] | str | None = None    # list | "dynamic"
        self.out_specs: list[str] | str | None = None
        self.via: set[str] = set()


def _split_top(text: str) -> list[str]:
    """Split on top-level commas (parens nest)."""
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def _canon(spec: str) -> str:
    """Canonical spec text: quotes and whitespace stripped, the
    PartitionSpec spelling collapsed to ``P``."""
    out = re.sub(r"[\s'\"]", "", spec)
    return re.sub(r"^PartitionSpec\(", "P(", out)


def parse_contract(comment: str, line: int
                   ) -> tuple[Contract | None, str | None]:
    """(contract, error) from one comment line; (None, None) when the
    line carries no mesh marker at all."""
    m = _MESH_RE.search(comment)
    if not m:
        return None, None
    tail = m.group(1)
    contract = Contract(line)
    consumed: list[tuple[int, int]] = []
    for km in _KEY_RE.finditer(tail):
        depth, end = 1, km.end()
        while end < len(tail) and depth:
            if tail[end] == "(":
                depth += 1
            elif tail[end] == ")":
                depth -= 1
            end += 1
        if depth:
            return None, (f"mesh contract: unbalanced parens in "
                          f"{km.group(1)}=(...)")
        body = tail[km.end():end - 1]
        consumed.append((km.start(), end))
        key = km.group(1)
        if key == "axes":
            names = _split_top(body)
            bad = [n for n in names if not re.fullmatch(r"[a-z][a-z0-9_]*", n)]
            if bad:
                return None, f"mesh contract: malformed axis name(s) {bad}"
            contract.axes = set(names)
        elif key == "via":
            contract.via = set(_split_top(body))
        else:
            if body.strip() == "dynamic":
                value: list[str] | str = "dynamic"
            else:
                value = [_canon(s) for s in _split_top(body)]
            if key == "in":
                contract.in_specs = value
            else:
                contract.out_specs = value
    leftover = "".join(ch for i, ch in enumerate(tail)
                       if not any(a <= i < b for a, b in consumed)).strip()
    if leftover:
        return None, (f"mesh contract has unparseable tail {leftover!r} "
                      f"(grammar: axes=(..) in=(..) out=(..) via=(..))")
    if contract.axes is None:
        return None, "mesh contract missing the mandatory axes=(...) part"
    return contract, None


def registry_axes(sources: dict[str, SourceFile],
                  out: list[Violation]) -> set[str] | None:
    """The AXES names, parsed literally from parallel/mesh.py."""
    src = sources.get(AXES_FILE)
    if src is None:
        out.append(Violation(
            PASS, AXES_FILE, 0,
            "AXES registry file not found — the mesh pass needs the "
            "literal axis-name dict in parallel/mesh.py"))
        return None
    for node in src.tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if not (isinstance(target, ast.Name) and target.id == "AXES"):
            continue
        value = getattr(node, "value", None)
        if not isinstance(value, ast.Dict) or not all(
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                for k in value.keys):
            out.append(Violation(
                PASS, AXES_FILE, node.lineno,
                "AXES must be a literal dict of axis-name strings — the "
                "pass reads it from the AST"))
            return None
        return {k.value for k in value.keys}
    out.append(Violation(
        PASS, AXES_FILE, 0,
        "no module-level AXES dict found in parallel/mesh.py"))
    return None


def _ctor_aliases(src: SourceFile) -> set[str]:
    """Bare names that refer to the sharding constructor classes in this
    file (``from jax.sharding import PartitionSpec as P`` → {'P', ...})."""
    names: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax.sharding"
                or node.module.endswith(".sharding")):
            for alias in node.names:
                if alias.name in _CTOR_NAMES:
                    names.add(alias.asname or alias.name)
    return names


def _ctor_kind(call: ast.Call, aliases: set[str]) -> str | None:
    chain = _call_chain(call.func)
    if not chain:
        return None
    tail = chain[-1]
    if tail.endswith("shard_map"):
        return "shard_map"
    if tail in _CTOR_NAMES:
        return tail
    if len(chain) == 1 and tail in aliases:
        return "PartitionSpec"
    return None


def _literal_axis_strings(call: ast.Call) -> list[tuple[int, str]]:
    """(line, name) for every string constant in an axis position inside
    the call subtree.  Subscript slices (``div["kv_heads"]``) and dict
    keys are data lookups, not axis names, and stay out."""
    excluded: set[int] = set()
    for node in ast.walk(call):
        if isinstance(node, ast.Subscript):
            excluded.update(id(n) for n in ast.walk(node.slice))
        elif isinstance(node, ast.Dict):
            for key in node.keys:
                if key is not None:
                    excluded.update(id(n) for n in ast.walk(key))
    out = []
    for node in ast.walk(call):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and id(node) not in excluded):
            out.append((node.lineno, node.value))
    return out


def _literal_spec(node: ast.expr, aliases: set[str]) -> str | None:
    """Canonical text of one literal P(...) spec, else None."""
    if not (isinstance(node, ast.Call)
            and _ctor_kind(node, aliases) == "PartitionSpec"):
        return None
    parts = []
    for arg in node.args:
        if isinstance(arg, ast.Constant) and (
                arg.value is None or isinstance(arg.value, str)):
            parts.append("None" if arg.value is None else str(arg.value))
        elif isinstance(arg, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in arg.elts):
            parts.append("(" + ",".join(e.value for e in arg.elts) + ")")
        else:
            return None
    return "P(" + ",".join(parts) + ")"


def _literal_spec_list(node: ast.expr, aliases: set[str]
                       ) -> list[str] | None:
    """Canonical spec list of a literal in_specs/out_specs expression:
    a tuple/list of literal P(...) calls, or one bare literal P(...)."""
    single = _literal_spec(node, aliases)
    if single is not None:
        return [single]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            spec = _literal_spec(el, aliases)
            if spec is None:
                return None
            out.append(spec)
        return out
    return None


def _spec_axes(canon: str) -> list[str]:
    """Axis names inside one canonical ``P(...)`` spec string —
    ``None`` entries (and nested-tuple parens) are placement syntax,
    not axes."""
    body = canon.removeprefix("P(").removesuffix(")")
    return [part for part in re.split(r"[,()]", body)
            if part and part != "None"]


def _lax_aliases(src: SourceFile) -> dict[str, str]:
    """Bare names bound to jax.lax collectives in this file
    (``from jax.lax import psum as ps`` → {'ps': 'psum'})."""
    out: dict[str, str] = {}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax.lax" or node.module.endswith(".lax")):
            for alias in node.names:
                if alias.name in _COLLECTIVES:
                    out[alias.asname or alias.name] = alias.name
    return out


def _collective_tail(call: ast.Call,
                     lax_aliases: dict[str, str]) -> str | None:
    chain = _call_chain(call.func)
    if len(chain) >= 2 and chain[-2] == "lax" and chain[-1] in _COLLECTIVES:
        return chain[-1]
    if len(chain) == 1 and chain[0] in lax_aliases:
        return lax_aliases[chain[0]]
    return None


class _FileChecker:
    def __init__(self, src: SourceFile, axes_registry: set[str] | None,
                 out: list[Violation]):
        self.src = src
        self.registry = axes_registry
        self.out = out
        self.aliases = _ctor_aliases(src)
        self.lax_aliases = _lax_aliases(src)
        self.seen: set[int] = set()
        #: def line -> parsed contract (cached; None = parsed, absent)
        self._def_contracts: dict[int, Contract | None] = {}

    # -- contract lookup ---------------------------------------------------
    def _contract_at(self, lines: list[int]) -> Contract | None:
        for line in sorted(set(lines)):
            for ln, comment in self.src.comment_block(line):
                contract, err = parse_contract(comment, ln)
                if err:
                    self.out.append(Violation(PASS, self.src.rel, ln, err))
                    return None
                if contract is not None:
                    self._check_axes_registered(contract)
                    return contract
        return None

    def find_contract(self, stmt: ast.stmt, call: ast.Call,
                      def_stack: list) -> Contract | None:
        """Statement-level contract, else the nearest enclosing def's."""
        anchor = [stmt.lineno, call.lineno]
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            anchor.extend(d.lineno for d in stmt.decorator_list)
        contract = self._contract_at(anchor)
        if contract is not None:
            return contract
        for fn in reversed(def_stack):
            if fn.lineno not in self._def_contracts:
                lines = [fn.lineno] + [d.lineno for d in fn.decorator_list]
                self._def_contracts[fn.lineno] = self._contract_at(lines)
            if self._def_contracts[fn.lineno] is not None:
                return self._def_contracts[fn.lineno]
        return None

    def _check_axes_registered(self, contract: Contract) -> None:
        if self.registry is None:
            return
        for axis in sorted((contract.axes or set()) - self.registry):
            self.out.append(Violation(
                PASS, self.src.rel, contract.line,
                f"mesh contract names axis {axis!r} which is not "
                f"registered in parallel/mesh.py::AXES"))

    # -- constructor checks ------------------------------------------------
    def check_ctor(self, stmt: ast.stmt, call: ast.Call, kind: str,
                   def_stack: list) -> None:
        contract = self.find_contract(stmt, call, def_stack)
        if contract is None:
            self.out.append(Violation(
                PASS, self.src.rel, call.lineno,
                f"{kind} constructor without a '# mesh: axes=(..)' "
                f"contract — declare the axes this site may place "
                f"(statement- or def-level)"))
            return
        for line, name in _literal_axis_strings(call):
            if kind == "shard_map":
                break       # specs checked structurally below
            if name not in (contract.axes or set()):
                self.out.append(Violation(
                    PASS, self.src.rel, line,
                    f"axis {name!r} is not declared in the covering "
                    f"mesh contract axes="
                    f"{tuple(sorted(contract.axes or ()))} "
                    f"(line {contract.line})"))
        if kind == "shard_map":
            self._check_shard_map(call, contract)

    def _check_shard_map(self, call: ast.Call, contract: Contract) -> None:
        kwargs = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        # axis_names literal strings must be declared
        axis_names = kwargs.get("axis_names")
        if axis_names is not None:
            for node in ast.walk(axis_names):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and node.value not in (contract.axes or set())):
                    self.out.append(Violation(
                        PASS, self.src.rel, node.lineno,
                        f"shard_map axis_names names {node.value!r} "
                        f"outside the contract's axes=()"))
        for key, attr in (("in_specs", "in_specs"), ("out_specs",
                                                     "out_specs")):
            declared = getattr(contract, attr)
            label = "in" if key == "in_specs" else "out"
            if declared is None:
                self.out.append(Violation(
                    PASS, self.src.rel, contract.line,
                    f"shard_map contract must declare {label}=(...) "
                    f"(literal specs, or 'dynamic' for computed ones)"))
                continue
            expr = kwargs.get(key)
            if expr is None:
                self.out.append(Violation(
                    PASS, self.src.rel, call.lineno,
                    f"shard_map call has no {key}= keyword the contract "
                    f"can round-trip against"))
                continue
            literal = _literal_spec_list(expr, self.aliases)
            if literal is None and declared != "dynamic":
                self.out.append(Violation(
                    PASS, self.src.rel, contract.line,
                    f"mesh contract declares literal {label}=(...) but "
                    f"the call's {key} is computed — declare "
                    f"{label}=(dynamic) or make the specs literal"))
            elif literal is not None and declared == "dynamic":
                self.out.append(Violation(
                    PASS, self.src.rel, contract.line,
                    f"mesh contract declares {label}=(dynamic) but the "
                    f"call's {key} is literal — declare the specs so "
                    f"they are checked"))
            elif literal is not None and list(declared) != literal:
                self.out.append(Violation(
                    PASS, self.src.rel, contract.line,
                    f"mesh contract {label}=({', '.join(declared)}) does "
                    f"not round-trip against the call's {key}="
                    f"({', '.join(literal)})"))
            if literal is not None:
                for spec in literal:
                    for axis in _spec_axes(spec):
                        if axis not in (contract.axes or set()):
                            self.out.append(Violation(
                                PASS, self.src.rel, call.lineno,
                                f"{key} places axis {axis!r} outside "
                                f"the contract's axes=()"))

    # -- collective checks -------------------------------------------------
    def check_collective(self, stmt: ast.stmt, call: ast.Call, tail: str,
                         def_stack: list) -> None:
        contract = self.find_contract(stmt, call, def_stack)
        if contract is None:
            self.out.append(Violation(
                PASS, self.src.rel, call.lineno,
                f"collective lax.{tail} outside any '# mesh:' contract "
                f"— annotate the enclosing function with the axes it "
                f"reduces over"))
            return
        pos = _COLLECTIVES[tail]
        axis_expr = None
        if len(call.args) > pos:
            axis_expr = call.args[pos]
        else:
            for kw in call.keywords:
                if kw.arg in ("axis_name", "axis", "axes"):
                    axis_expr = kw.value
                    break
        if axis_expr is None:
            self.out.append(Violation(
                PASS, self.src.rel, call.lineno,
                f"collective lax.{tail} has no resolvable axis argument"))
            return
        elements = (list(axis_expr.elts)
                    if isinstance(axis_expr, (ast.Tuple, ast.List))
                    else [axis_expr])
        axes = contract.axes or set()
        for el in elements:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                if el.value not in axes:
                    self.out.append(Violation(
                        PASS, self.src.rel, call.lineno,
                        f"collective lax.{tail} names axis {el.value!r} "
                        f"outside the contract's axes="
                        f"{tuple(sorted(axes))} (line {contract.line})"))
            elif isinstance(el, ast.Name):
                if el.id not in contract.via:
                    self.out.append(Violation(
                        PASS, self.src.rel, call.lineno,
                        f"collective lax.{tail} takes its axis from "
                        f"{el.id!r}, which the contract does not declare "
                        f"in via=(...) — axis names flowing through "
                        f"parameters must be declared"))
            else:
                self.out.append(Violation(
                    PASS, self.src.rel, call.lineno,
                    f"collective lax.{tail} axis argument is not a "
                    f"literal or a declared via=() parameter"))

    # -- walk --------------------------------------------------------------
    def run(self) -> None:
        def own_exprs(stmt: ast.stmt):
            """Expressions belonging to ``stmt`` ITSELF — stopping at
            nested statements, so a call anchors its contract search at
            its OWN statement, never an enclosing block's."""
            stack = [c for c in ast.iter_child_nodes(stmt)
                     if not isinstance(c, ast.stmt)]
            while stack:
                node = stack.pop()
                yield node
                stack.extend(c for c in ast.iter_child_nodes(node)
                             if not isinstance(c, ast.stmt))

        def visit_stmt(stmt: ast.stmt, def_stack: list) -> None:
            for node in own_exprs(stmt):
                if not isinstance(node, ast.Call) or id(node) in self.seen:
                    continue
                kind = _ctor_kind(node, self.aliases)
                tail = _collective_tail(node, self.lax_aliases)
                if kind is None and tail is None:
                    continue
                self.seen.add(id(node))
                if kind is not None:
                    # nested ctors (P inside NamedSharding, specs inside
                    # shard_map) are part of this construct — one check
                    for sub in ast.walk(node):
                        if (isinstance(sub, ast.Call)
                                and _ctor_kind(sub, self.aliases)):
                            self.seen.add(id(sub))
                    self.check_ctor(stmt, node, kind, def_stack)
                else:
                    self.check_collective(stmt, node, tail, def_stack)

        def walk_body(body: list[ast.stmt], def_stack: list) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit_stmt(stmt, def_stack)     # decorators/defaults
                    walk_body(stmt.body, def_stack + [stmt])
                    continue
                if isinstance(stmt, ast.ClassDef):
                    visit_stmt(stmt, def_stack)     # decorators/bases
                    walk_body(stmt.body, def_stack)
                    continue
                visit_stmt(stmt, def_stack)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        walk_body(sub, def_stack)
                for handler in getattr(stmt, "handlers", []) or []:
                    walk_body(handler.body, def_stack)
                for case in getattr(stmt, "cases", []) or []:
                    walk_body(case.body, def_stack)

        walk_body(self.src.tree.body, [])


def in_scope(rel: str) -> bool:
    return rel.replace("\\", "/").startswith(SCOPE_PREFIXES)


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    registry = registry_axes(sources, out)
    for rel, src in sorted(sources.items()):
        if not in_scope(rel):
            continue
        _FileChecker(src, registry, out).run()
    return out
