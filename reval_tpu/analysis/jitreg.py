"""Jit-entry registry pass (``jit``).

Every ``jax.jit`` / ``jax.shard_map`` constructor in the compiled core
(``models/``, ``ops/``, ``inference/tpu/``, ``parallel/``) is a COMPILE
BOUNDARY: its static arguments and input-shape buckets decide how many
programs XLA builds and when the decode loop silently recompiles.  Those
contracts lived only in prose (PERF.md's "bounded compile variants"
folklore); this pass makes them annotations:

    # jit-entry: paged.decode_chunk static=(steps, filtered) bucketed=(span) warmup=64

on the statement that constructs the jit (or on the decorator of a
jitted ``def``) — ONE line, the parser does not follow backslash
continuations.  The grammar:

- ``<shape-key>`` (mandatory) — a dotted slug, unique across the tree;
  the runtime recompile sanitizer (:mod:`.jitcheck`) and the
  ``reval_jit_*`` metrics report per-entry variant counts under this
  name.
- ``static=(a, b)`` — the argument names traced as Python values.  Must
  round-trip EXACTLY with the call's ``static_argnames`` literal: the
  annotation cannot promise fewer (an undeclared static is an implicit
  recompile axis) or more (a ghost static is stale documentation).
- ``bucketed=(c, d)`` — the shape axes the host quantises to powers of
  two before dispatch (``pow2_bucket``); prose-checked documentation of
  WHY the variant count is bounded.
- ``warmup=N`` — the entry's compile-variant budget: the runtime
  sanitizer flags the N+1-th distinct lowering as a post-warmup
  recompile.  Must match the ``tracked_jit(..., warmup=N)`` literal when
  the entry is runtime-tracked.

Rules enforced:

1. every ``jax.jit`` / ``shard_map`` / ``partial(jax.jit, ...)``
   constructor in scope carries a ``# jit-entry:`` annotation;
2. shape-keys are unique (one entry, one name — the metrics/sanitizer
   would silently merge two entries otherwise);
3. ``static=`` ↔ ``static_argnames`` round-trips both directions, and
   ``static_argnames`` must be a literal (a computed value defeats the
   registry); ``static_argnums`` is banned outright — positional static
   indices go stale silently when a signature gains a parameter;
4. annotated bodies (the jitted ``def`` itself, or a same-file function
   the jit/``partial`` names) contain no data-dependent Python ``if`` /
   ``while`` on a traced parameter — branching on a tracer either
   crashes at trace time or, worse, bakes one branch into the compiled
   program and silently recompiles per value.  ``x is (not) None``
   structural tests and static/partial-bound parameters are exempt;
5. ``warmup=`` ↔ the ``tracked_jit`` wrapper's name/warmup literals.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .core import SourceFile, Violation
from .core import call_chain as _call_chain

PASS = "jit"

#: directories whose jit constructors must be declared
SCOPE_PREFIXES = ("reval_tpu/models/", "reval_tpu/ops/",
                  "reval_tpu/inference/tpu/", "reval_tpu/parallel/")

_ENTRY_RE = re.compile(r"#\s*jit-entry:\s*(\S+)(.*)$")
_PART_RE = re.compile(r"(static|bucketed)=\(([^)]*)\)|warmup=(\d+)")


@dataclass
class JitEntry:
    """One parsed ``# jit-entry:`` annotation bound to its constructor."""

    name: str
    line: int                      # annotation line
    call_line: int                 # the jit/shard_map constructor line
    static: tuple | None = None
    bucketed: tuple | None = None
    warmup: int | None = None
    #: same-file FunctionDef the entry compiles, when resolvable
    target: ast.FunctionDef | None = None
    #: kwargs bound by a ``partial`` (Python constants at trace time)
    bound: set = field(default_factory=set)


def _names(csv: str) -> tuple:
    return tuple(n.strip() for n in csv.split(",") if n.strip())


def parse_entry(comment: str, line: int) -> tuple[JitEntry | None, str | None]:
    """(entry, error) from one comment line; (None, None) when the line
    carries no jit-entry marker at all."""
    m = _ENTRY_RE.search(comment)
    if not m:
        return None, None
    name, tail = m.group(1), m.group(2)
    entry = JitEntry(name=name, line=line, call_line=line)
    for pm in _PART_RE.finditer(tail):
        if pm.group(1) == "static":
            entry.static = _names(pm.group(2))
        elif pm.group(1) == "bucketed":
            entry.bucketed = _names(pm.group(2))
        else:
            entry.warmup = int(pm.group(3))
    leftover = _PART_RE.sub("", tail).strip()
    if leftover:
        return None, (f"jit-entry annotation has unparseable tail "
                      f"{leftover!r} (grammar: static=(..) bucketed=(..) "
                      f"warmup=N)")
    if not re.fullmatch(r"[A-Za-z_][\w.-]*", name):
        return None, f"jit-entry shape-key {name!r} is not a dotted slug"
    return entry, None



def _is_jax_jit_ref(expr: ast.expr) -> bool:
    """``jax.jit`` (or bare ``jit``) used as a VALUE (partial's arg)."""
    chain = _call_chain(expr)
    return chain in (["jax", "jit"], ["jit"])


def _jit_ctor_kind(call: ast.Call) -> str | None:
    """"jit" | "shard_map" | "partial_jit" when ``call`` constructs a
    compile boundary; None otherwise."""
    chain = _call_chain(call.func)
    if not chain:
        return None
    if chain in (["jax", "jit"], ["jit"]):
        return "jit"
    if chain[-1].endswith("shard_map"):
        return "shard_map"
    if chain[-1] == "partial" and call.args and _is_jax_jit_ref(call.args[0]):
        return "partial_jit"
    return None


def _literal_str_tuple(node: ast.expr) -> tuple | None:
    """A literal str or tuple/list-of-str, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _static_argnames(call: ast.Call) -> tuple[tuple | None, bool, bool]:
    """(names, present, literal) for the call's ``static_argnames``."""
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = _literal_str_tuple(kw.value)
            return names, True, names is not None
    return None, False, True


def _has_static_argnums(call: ast.Call) -> bool:
    return any(kw.arg == "static_argnums" for kw in call.keywords)


def _target_ref(call: ast.Call, kind: str
                ) -> tuple[str | None, set]:
    """(function name the ctor compiles, partial-bound kwarg names).

    ``jax.jit(f)`` / ``jax.jit(partial(f, cfg=cfg))`` / ``shard_map(f)``
    — ``f`` as a Name or ``self.X`` attribute; lambdas and foreign
    values return None."""
    if kind == "partial_jit" or not call.args:
        return None, set()
    arg = call.args[0]
    bound: set = set()
    if isinstance(arg, ast.Call) and _call_chain(arg.func)[-1:] == ["partial"]:
        bound = {kw.arg for kw in arg.keywords if kw.arg}
        if not arg.args:
            return None, bound
        arg = arg.args[0]
    if isinstance(arg, ast.Name):
        return arg.id, bound
    if isinstance(arg, ast.Attribute):
        return arg.attr, bound
    return None, bound


def _param_names(fn: ast.FunctionDef) -> tuple[set, set]:
    """(named params, structural varargs/kwargs names)."""
    a = fn.args
    named = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    structural = set()
    if a.vararg:
        structural.add(a.vararg.arg)
    if a.kwarg:
        structural.add(a.kwarg.arg)
    return named, structural


def _own_exprs(stmt: ast.stmt):
    """Expression nodes belonging to ``stmt`` ITSELF — stopping at
    nested statements (a class/function body's jit calls must anchor
    their annotation search at their OWN assignment, not the enclosing
    ClassDef line)."""
    stack = [c for c in ast.iter_child_nodes(stmt)
             if not isinstance(c, ast.stmt)]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(c for c in ast.iter_child_nodes(node)
                     if not isinstance(c, ast.stmt))


def _defs_by_name(tree: ast.Module) -> dict[str, list[ast.FunctionDef]]:
    out: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _tracked_jit_literals(call: ast.Call) -> tuple[str | None, int | None]:
    """(name, warmup) literals of an enclosing ``tracked_jit(...)``."""
    name = None
    if (call.args and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)):
        name = call.args[0].value
    warmup = None
    for kw in call.keywords:
        if (kw.arg == "warmup" and isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, int)):
            warmup = kw.value.value
    return name, warmup


def _check_traced_branches(src: SourceFile, entry: JitEntry,
                           out: list[Violation]) -> None:
    fn = entry.target
    if fn is None:
        return
    named, structural = _param_names(fn)
    static = set(entry.static or ())
    traced = named - static - entry.bound - structural
    if not traced:
        return
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        # `x is None` / `x is not None` tests argument STRUCTURE
        # (retrace per structure is jit's documented contract) — exempt
        # only the NAME OCCURRENCES inside those comparisons, never the
        # name everywhere in the test: `if x is not None and x > 2:`
        # must still flag the data-dependent `x > 2` clause
        structural_occ: set[int] = set()
        for sub in ast.walk(node.test):
            if (isinstance(sub, ast.Compare)
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in sub.ops)):
                for piece in [sub.left] + sub.comparators:
                    structural_occ.update(
                        id(n) for n in ast.walk(piece))
        hit = sorted({n.id for n in ast.walk(node.test)
                      if isinstance(n, ast.Name)
                      and n.id in traced and id(n) not in structural_occ})
        if hit:
            out.append(Violation(
                PASS, src.rel, node.lineno,
                f"jit entry {entry.name!r}: Python "
                f"{'if' if isinstance(node, ast.If) else 'while'} on "
                f"traced parameter(s) {', '.join(hit)} — branch in jax "
                f"(jnp.where/lax.cond) or declare the name in "
                f"static=(...)"))


def collect_entries(src: SourceFile, out: list[Violation] | None = None
                    ) -> list[JitEntry]:
    """Every jit/shard_map constructor in ``src`` with its annotation
    (entries lacking one are reported into ``out`` and skipped)."""
    violations = out if out is not None else []
    defs = _defs_by_name(src.tree)
    entries: list[JitEntry] = []
    seen_calls: set[int] = set()

    def anchor_lines(stmt: ast.stmt, call: ast.Call) -> list[int]:
        lines = [stmt.lineno, call.lineno]
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lines.extend(d.lineno for d in stmt.decorator_list)
        return sorted(set(lines))

    def find_annotation(stmt: ast.stmt, call: ast.Call
                        ) -> tuple[JitEntry | None, bool]:
        for line in anchor_lines(stmt, call):
            for ln, comment in src.comment_block(line):
                entry, err = parse_entry(comment, ln)
                if err:
                    violations.append(Violation(PASS, src.rel, ln, err))
                    return None, True
                if entry is not None:
                    return entry, True
        return None, False

    def visit_stmt(stmt: ast.stmt) -> None:
        for call in _own_exprs(stmt):
            if not isinstance(call, ast.Call) or id(call) in seen_calls:
                continue
            kind = _jit_ctor_kind(call)
            if kind is None:
                continue
            # a partial(jax.jit, ...) decorator also exposes the inner
            # jax.jit Name — mark the whole subtree visited once
            for sub in ast.walk(call):
                if isinstance(sub, ast.Call) and _jit_ctor_kind(sub):
                    seen_calls.add(id(sub))
            entry, had_marker = find_annotation(stmt, call)
            if entry is None:
                if not had_marker:
                    violations.append(Violation(
                        PASS, src.rel, call.lineno,
                        f"undeclared jit entry point "
                        f"({'.'.join(_call_chain(call.func)) or 'jit'}) — "
                        f"annotate the statement with "
                        f"'# jit-entry: <shape-key> ...'"))
                continue
            entry.call_line = call.lineno
            _check_call_contract(src, entry, stmt, call, kind, defs,
                                 violations)
            entries.append(entry)

    def walk_body(body: list[ast.stmt]) -> None:
        for stmt in body:
            visit_stmt(stmt)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    walk_body(sub)
            for handler in getattr(stmt, "handlers", []) or []:
                walk_body(handler.body)

    walk_body(src.tree.body)
    return entries


def _check_call_contract(src: SourceFile, entry: JitEntry, stmt: ast.stmt,
                         call: ast.Call, kind: str,
                         defs: dict[str, list[ast.FunctionDef]],
                         out: list[Violation]) -> None:
    if _has_static_argnums(call):
        out.append(Violation(
            PASS, src.rel, call.lineno,
            f"jit entry {entry.name!r} uses static_argnums — positional "
            f"static indices silently go stale; use static_argnames"))
    declared, present, literal = _static_argnames(call)
    if present and not literal:
        out.append(Violation(
            PASS, src.rel, call.lineno,
            f"jit entry {entry.name!r}: static_argnames is not a string "
            f"literal/tuple — the registry cannot verify a computed "
            f"static set"))
    elif present and set(declared or ()) != set(entry.static or ()):
        out.append(Violation(
            PASS, src.rel, entry.line,
            f"jit entry {entry.name!r}: annotation static="
            f"{tuple(sorted(entry.static or ()))} does not match the "
            f"call's static_argnames={tuple(sorted(declared or ()))}"))
    elif not present and entry.static:
        out.append(Violation(
            PASS, src.rel, entry.line,
            f"jit entry {entry.name!r} declares static="
            f"{tuple(entry.static)} but the call has no static_argnames"))

    # resolve the compiled body for the traced-branch check
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and any(
            call is d or any(call is sub for sub in ast.walk(d))
            for d in stmt.decorator_list):
        entry.target = stmt
    else:
        name, bound = _target_ref(call, kind)
        entry.bound = bound
        if name is not None and len(defs.get(name, [])) == 1:
            entry.target = defs[name][0]
    _check_traced_branches(src, entry, out)

    # tracked_jit(name, jax.jit(...), warmup=N) cross-check: one entry,
    # one name, one budget — in the annotation AND the wrapper literal
    for outer in ast.walk(stmt):
        if (isinstance(outer, ast.Call)
                and _call_chain(outer.func)[-1:] == ["tracked_jit"]
                and any(call is sub for sub in ast.walk(outer))):
            tname, twarm = _tracked_jit_literals(outer)
            if tname is not None and tname != entry.name:
                out.append(Violation(
                    PASS, src.rel, outer.lineno,
                    f"tracked_jit name {tname!r} does not match the "
                    f"jit-entry shape-key {entry.name!r}"))
            if twarm != entry.warmup:
                out.append(Violation(
                    PASS, src.rel, outer.lineno,
                    f"jit entry {entry.name!r}: tracked_jit warmup="
                    f"{twarm!r} does not match the annotation's warmup="
                    f"{entry.warmup!r}"))
            break


def in_scope(rel: str) -> bool:
    return rel.replace("\\", "/").startswith(SCOPE_PREFIXES)


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    by_name: dict[str, tuple[str, int]] = {}
    for rel, src in sorted(sources.items()):
        if not in_scope(rel):
            continue
        for entry in collect_entries(src, out):
            prev = by_name.get(entry.name)
            if prev is not None:
                out.append(Violation(
                    PASS, rel, entry.line,
                    f"duplicate jit-entry shape-key {entry.name!r} "
                    f"(also declared at {prev[0]}:{prev[1]}) — the "
                    f"sanitizer and metrics would merge two entries"))
            else:
                by_name[entry.name] = (rel, entry.line)
    return out
