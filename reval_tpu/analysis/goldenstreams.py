"""``goldenstreams`` pass: the committed golden-stream registry is sound.

``GOLDEN_STREAMS.json`` (written by ``tools/golden_streams.py
--record``) is the cross-commit upgrade gate for greedy token streams:
a registry that quietly rotted — truncated JSON, digests that no longer
recompute from the stored streams, or a recording poisoned by a
leftover ``REVAL_TPU_DETERMINISM_PERTURB`` drill — would either gate
every clean run red or wave a real divergence through.  This pass
validates the committed file against the declared schema
(``obs/determinism.py::validate_golden`` — ONE checker shared with the
tool's pre-write self-check and the tests) WITHOUT running the model,
so it fits the <10 s lint bar; the full re-run-and-diff gate is the
tool's ``--check`` mode.

No registry at the repo root = nothing to lint (clean): a tree that has
never blessed a stream set has no gate to corrupt.  An unreadable or
invalid registry IS a violation — a broken gate must never read as a
passing one.
"""

from __future__ import annotations

import json
import os

from .core import Violation

__all__ = ["run"]


def run(sources, root: str) -> list[Violation]:
    from ..obs.determinism import GOLDEN_FILE, validate_golden

    path = os.path.join(root, GOLDEN_FILE)
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        return [Violation("goldenstreams", GOLDEN_FILE, 0,
                          f"unreadable golden-stream registry: "
                          f"{type(e).__name__}: {e}")]
    return [Violation("goldenstreams", GOLDEN_FILE, 0, err)
            for err in validate_golden(obj)]
