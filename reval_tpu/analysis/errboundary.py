"""Typed-error boundary pass (``errors``).

The HTTP boundary maps the ``serving/errors.py`` taxonomy to stable
statuses and wire-safe bodies (429/503/504/500 + code); anything else a
handler or the session driver raises reaches clients as a sanitized 500
whose real cause exists only in the log.  The taxonomy only works if the
serving layer actually speaks it, so this pass bans UNTYPED raises in
``reval_tpu/serving/``:

- ``raise RuntimeError(...)`` / ``raise Exception(...)`` /
  ``raise BaseException(...)`` are violations — wrap the condition in a
  taxonomy member (``EngineFailure`` exists precisely for "an untyped
  engine fault crossed the handle");
- bare ``raise`` (re-raise) is fine — propagation is classification's
  job upstream;
- ``ValueError``/``TypeError`` (client-input errors the server maps to
  400) and ``TimeoutError`` (waiter contract) stay allowed, as do the
  taxonomy members themselves and anything else typed.
"""

from __future__ import annotations

import ast

from .core import SourceFile, Violation

PASS = "errors"

_BANNED = {"RuntimeError", "Exception", "BaseException"}

#: the serving layer: HTTP handlers, the session driver, the mock engine
_SCOPE = "reval_tpu/serving/"


def _raised_name(node: ast.Raise) -> str | None:
    exc = node.exc
    if exc is None:
        return None                     # bare re-raise
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Name):
        return exc.id
    if isinstance(exc, ast.Attribute):
        return exc.attr
    return None


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    for rel, src in sorted(sources.items()):
        if not rel.replace("\\", "/").startswith(_SCOPE):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Raise):
                continue
            name = _raised_name(node)
            if name in _BANNED:
                out.append(Violation(
                    PASS, rel, node.lineno,
                    f"bare `raise {name}` in the serving path — raise a "
                    f"serving/errors.py taxonomy member (EngineFailure "
                    f"wraps untyped engine faults) so the HTTP boundary "
                    f"maps it to a stable status"))
    return out
