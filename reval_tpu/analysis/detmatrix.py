"""``detmatrix`` pass: determinism-matrix artifacts conform to schema.

The determinism observatory's whole value is *coverage you can trust*:
a backend silently missing from ``tpu_watch/determinism-<ts>.json``
reads as "everything agrees" when it means "nobody looked".  This pass
validates every matrix artifact on disk against the declared schema
(``obs/determinism.py::validate_matrix`` — ONE checker shared with the
CLI's pre-write self-check and the tests):

- the schema version is the one this tree writes;
- the declared reference cell is present with status ``ref``;
- every cell of the declared taxonomy (``default_cells()``) appears,
  either executed or skipped WITH a reason — a cell can be unloadable,
  filtered, or broken, but never silently absent;
- run cells carry their observables (tokens/answers/fingerprint/
  logits fingerprint) and compared cells carry their diff.

No artifacts on disk = nothing to lint (clean): the artifacts are
generated, untracked scratch.  An unreadable or truncated artifact IS a
violation — a half-written report must never pass for a clean audit.
"""

from __future__ import annotations

import glob
import json
import os

from .core import Violation

__all__ = ["run"]


def run(sources, root: str) -> list[Violation]:
    from ..obs.determinism import validate_matrix

    out: list[Violation] = []
    pattern = os.path.join(root, "tpu_watch", "determinism-*.json")
    for path in sorted(glob.glob(pattern)):
        rel = os.path.relpath(path, root)
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            out.append(Violation("detmatrix", rel, 0,
                                 f"unreadable matrix artifact: "
                                 f"{type(e).__name__}: {e}"))
            continue
        for err in validate_matrix(obj):
            out.append(Violation("detmatrix", rel, 0, err))
    return out
