"""Pallas tile-contract pass (``tilecontract``).

TPU vector memory is tiled (sublane, lane) = (8, 128) for f32: a
BlockSpec or VMEM scratch whose minor dim is not lane-aligned (or whose
second-minor dim breaks sublane alignment) either fails Mosaic lowering
with an opaque "must be aligned to tiling" error — found the hard way
on this repo's first real-chip compile, PERF.md round 5 — or silently
pads, burning VMEM.  The ragged paged-attention kernel (ROADMAP item 1)
will rewrite the most shape-sensitive BlockSpecs in the tree; this pass
pins the discipline BEFORE that rewrite so a misaligned tile is a lint
failure, not a chip-session debugging night.

Contract: every ``pl.pallas_call`` in ``ops/`` carries

    # tile: (8, 128)

on its statement (or the comment block above) declaring the
(sublane, lane) tiling the kernel was shaped for.  The pass checks:

1. the annotation exists — an unannotated kernel has no declared shape
   discipline for reviewers or the ragged rewrite to inherit;
2. the declared tile is itself legal: sublane a positive multiple of 8,
   lane a positive multiple of 128 (the f32 native tile; bf16/int8
   kernels still address VMEM in f32-tile multiples in this codebase —
   head_dim rides the lane dim at 128+);
3. every ``pl.BlockSpec`` / ``pltpu.VMEM`` shape in the enclosing
   function whose minor (or second-minor) dim is a RESOLVABLE integer —
   a literal, or a name bound to an integer constant at module or
   function scope — satisfies ``minor % lane == 0`` and
   ``second_minor % sublane == 0``.  Symbolic dims (``page_size``,
   ``head_dim`` parameters) are runtime-shaped and stay out of lint
   scope; the kernel parity tests cover them.

Suppression: ``# lint: allow(tilecontract) — <reason>`` (driver
policy, reason mandatory) for a deliberately sub-tile shape.
"""

from __future__ import annotations

import ast
import re

from .core import SourceFile, Violation
from .core import call_chain as _call_chain

PASS = "tilecontract"

SCOPE_PREFIX = "reval_tpu/ops/"

_TILE_RE = re.compile(r"#\s*tile:\s*\(\s*(\d+)\s*,\s*(\d+)\s*\)")

#: call tails whose first (or ``block_shape=``) tuple is a tiled shape
_SHAPE_CALLS = {"BlockSpec", "VMEM"}



def _const_env(tree: ast.Module, fn: ast.FunctionDef) -> dict[str, int]:
    """Names bound to a single integer constant at module scope or in
    ``fn``'s body (simple ``NAME = <int>`` assignments only)."""
    env: dict[str, int] = {}
    rebound: set[str] = set()

    def scan(body):
        for node in body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)
                    and not isinstance(node.value.value, bool)):
                name = node.targets[0].id
                if name in env:
                    rebound.add(name)
                env[name] = node.value.value

    scan(tree.body)
    scan(fn.body)
    for name in rebound:
        env.pop(name, None)
    return env


def _resolve(node: ast.expr, env: dict[str, int]) -> int | None:
    if (isinstance(node, ast.Constant) and isinstance(node.value, int)
            and not isinstance(node.value, bool)):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    return None


def _shape_tuple(call: ast.Call) -> ast.Tuple | None:
    for kw in call.keywords:
        if kw.arg == "block_shape" and isinstance(kw.value, ast.Tuple):
            return kw.value
    if call.args and isinstance(call.args[0], ast.Tuple):
        return call.args[0]
    return None


def _check_shapes(src: SourceFile, fn: ast.FunctionDef, env: dict[str, int],
                  sublane: int, lane: int, out: list[Violation]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _call_chain(node.func)
        if not chain or chain[-1] not in _SHAPE_CALLS:
            continue
        shape = _shape_tuple(node)
        if shape is None or not shape.elts:
            continue
        minor = _resolve(shape.elts[-1], env)
        if minor is not None and minor % lane:
            out.append(Violation(
                PASS, src.rel, node.lineno,
                f"{chain[-1]} minor dim {minor} is not a multiple of "
                f"the declared lane tile {lane}"))
        if len(shape.elts) >= 2:
            second = _resolve(shape.elts[-2], env)
            if second is not None and second != 1 and second % sublane:
                out.append(Violation(
                    PASS, src.rel, node.lineno,
                    f"{chain[-1]} second-minor dim {second} is not a "
                    f"multiple of the declared sublane tile {sublane}"))


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    for rel, src in sorted(sources.items()):
        if not rel.replace("\\", "/").startswith(SCOPE_PREFIX):
            continue
        seen: set[int] = set()

        def enclosing_walk(body, fn):
            for stmt in body:
                cur = stmt if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn
                for call in (ast.walk(stmt)
                             if not isinstance(stmt, (ast.FunctionDef,
                                                      ast.AsyncFunctionDef,
                                                      ast.ClassDef))
                             else ()):
                    if (isinstance(call, ast.Call)
                            and _call_chain(call.func)[-1:] == ["pallas_call"]
                            and id(call) not in seen):
                        seen.add(id(call))
                        check_call(stmt, call, fn)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        enclosing_walk(sub, cur)
                for handler in getattr(stmt, "handlers", []) or []:
                    enclosing_walk(handler.body, cur)

        def check_call(stmt, call, fn):
            tile = None
            for line in sorted({stmt.lineno, call.lineno}):
                for ln, comment in src.comment_block(line):
                    m = _TILE_RE.search(comment)
                    if m:
                        tile = (int(m.group(1)), int(m.group(2)), ln)
                        break
                if tile:
                    break
            if tile is None:
                out.append(Violation(
                    PASS, src.rel, call.lineno,
                    "pallas_call without a '# tile: (sublane, lane)' "
                    "contract — declare the tiling the kernel's "
                    "BlockSpecs were shaped for"))
                return
            sublane, lane, ln = tile
            if sublane <= 0 or sublane % 8:
                out.append(Violation(
                    PASS, src.rel, ln,
                    f"declared sublane tile {sublane} is not a positive "
                    f"multiple of 8"))
                return
            if lane <= 0 or lane % 128:
                out.append(Violation(
                    PASS, src.rel, ln,
                    f"declared lane tile {lane} is not a positive "
                    f"multiple of 128"))
                return
            if fn is not None:
                _check_shapes(src, fn, _const_env(src.tree, fn),
                              sublane, lane, out)

        enclosing_walk(src.tree.body, None)
    return out
