"""Hot-path purity pass (``hotpath``).

Functions marked ``# hot-path`` on their ``def`` line run at drive-tick
cadence — the paged engine's ``_drive_tick``/``_tick``, the
chunk-processing half, the flight recorder's ``record``, the mock
engine's tick.  PERF.md prices these in single-digit microseconds; one
stray ``json.dumps`` or log format in them silently eats the whole
budget, and a ``time.sleep``/file write turns a 2 µs tick into a stall
the watchdog has to explain.

The rule is lexical: the body of a hot function (nested defs included —
they are usually per-tick callbacks) may not CALL a known
blocking/allocating API: sleeps, file/socket/subprocess IO, json/pickle
serialisation, ``print``, structured-log emission (``log_event``),
``logging`` calls, registry rendering (``render_prometheus``/
``snapshot``), or time formatting.  Exceptional branches that genuinely
must log (a deadlock raise) carry an inline
``# lint: allow(hotpath) — <reason>`` and are counted by the driver.
"""

from __future__ import annotations

import ast

from .core import SourceFile, Violation
from .core import call_chain as _call_chain

PASS = "hotpath"

#: bare-name calls that never belong in a hot path
_DENY_NAMES = {"open", "print", "input", "breakpoint", "sleep",
               "log_event"}

#: attribute-call TAILS denied regardless of receiver
_DENY_TAILS = {"sleep", "render_prometheus", "snapshot", "strftime",
               "format_exc", "urlopen", "makedirs", "system", "popen"}

#: module roots whose every call is IO/serialisation by construction
_DENY_MODULES = {"json", "pickle", "subprocess", "urllib", "requests",
                 "socket", "logging", "shutil"}



def _denied(chain: list[str]) -> str | None:
    if not chain:
        return None
    name = ".".join(chain)
    if len(chain) == 1 and chain[0] in _DENY_NAMES:
        return name
    if chain[-1] in _DENY_TAILS or chain[-1] in _DENY_NAMES:
        return name
    if chain[0] in _DENY_MODULES:
        return name
    return None


def _check_function(src: SourceFile, node, qual: str,
                    out: list[Violation]) -> None:
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        denied = _denied(_call_chain(sub.func))
        if denied is not None:
            out.append(Violation(
                PASS, src.rel, sub.lineno,
                f"hot-path function {qual!r} calls blocking/allocating "
                f"API {denied!r}"))


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    for rel, src in sorted(sources.items()):
        if not rel.startswith("reval_tpu"):
            continue
        ann = src.annotations()
        if not ann.hot:
            continue

        def walk(body, qual):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fq = f"{qual}.{node.name}" if qual else node.name
                    if fq in ann.hot:
                        _check_function(src, node, fq, out)
                    else:
                        walk(node.body, fq)
                elif isinstance(node, ast.ClassDef):
                    walk(node.body, node.name)

        walk(src.tree.body, "")
    return out
