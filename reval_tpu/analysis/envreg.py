"""Env/config registry pass (``env``).

``reval_tpu/env.py::ENV`` declares every ``REVAL_TPU_*`` knob once
(mirroring METRICS/EVENTS).  This pass closes the loop in all four
directions:

1. **No raw reads.**  ``os.environ[...]`` / ``os.environ.get`` /
   ``os.getenv`` of a ``REVAL_TPU_*`` literal anywhere in ``reval_tpu/``
   outside ``env.py`` itself is a violation — reads go through the typed
   accessors, which enforce declaration at runtime too.  WRITES
   (``os.environ["REVAL_TPU_X"] = ...``) stay legal: tools and benches
   set knobs for downstream readers.
2. **Routed names are declared.**  Every ``env_str/int/float/flag/raw``
   call with a string literal names a declared var (and a NON-literal
   name is flagged — a computed env name defeats the registry).
3. **README round-trip.**  The ENV spec and the README environment
   table match, both directions (same contract as the metric/event
   tables).
4. **No zombies.**  A declared var referenced nowhere in the tree
   (sources under lint plus ``tests/``) is dead config — delete it or
   wire it up.
"""

from __future__ import annotations

import ast
import glob
import os
import re

from .core import SourceFile, Violation

PASS = "env"

_ACCESSORS = {"env_raw", "env_str", "env_int", "env_float", "env_flag"}

_SPEC_REL = os.path.join("reval_tpu", "env.py")

_README_ROW_RE = re.compile(r"^\s*\|\s*`(REVAL_TPU_[A-Z0-9_]+)`")


def _spec() -> dict:
    from .. import env as env_mod

    return env_mod.ENV


def _env_name_arg(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _is_environ(expr: ast.expr) -> bool:
    """``os.environ`` or a bare ``environ`` name."""
    return ((isinstance(expr, ast.Attribute) and expr.attr == "environ")
            or (isinstance(expr, ast.Name) and expr.id == "environ"))


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    env = _spec()
    for rel, src in sorted(sources.items()):
        posix = rel.replace("\\", "/")
        if not posix.startswith("reval_tpu/") or posix == "reval_tpu/env.py":
            continue
        for node in ast.walk(src.tree):
            # raw reads: os.environ.get("REVAL_TPU_X") / os.getenv(...)
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute):
                    if (func.attr in ("get", "pop", "setdefault")
                            and _is_environ(func.value)):
                        name = _env_name_arg(node)
                        if (name and name.startswith("REVAL_TPU_")
                                and func.attr != "setdefault"):
                            out.append(Violation(
                                PASS, rel, node.lineno,
                                f"raw os.environ.{func.attr}({name!r}) — "
                                f"read it through reval_tpu.env "
                                f"(env_str/env_int/env_float/env_flag)"))
                    elif func.attr == "getenv":
                        name = _env_name_arg(node)
                        if name and name.startswith("REVAL_TPU_"):
                            out.append(Violation(
                                PASS, rel, node.lineno,
                                f"raw os.getenv({name!r}) — read it "
                                f"through reval_tpu.env"))
                    if func.attr in _ACCESSORS:
                        _check_routed(node, rel, env, out)
                elif isinstance(func, ast.Name) and func.id in _ACCESSORS:
                    _check_routed(node, rel, env, out)
                elif isinstance(func, ast.Name) and func.id == "getenv":
                    # `from os import getenv` must not evade the ban
                    name = _env_name_arg(node)
                    if name and name.startswith("REVAL_TPU_"):
                        out.append(Violation(
                            PASS, rel, node.lineno,
                            f"raw getenv({name!r}) — read it through "
                            f"reval_tpu.env"))
            # raw subscript READ: os.environ["REVAL_TPU_X"] (stores are
            # writes — configuring subprocesses/downstream readers)
            elif (isinstance(node, ast.Subscript)
                  and isinstance(node.ctx, ast.Load)
                  and _is_environ(node.value)
                  and isinstance(node.slice, ast.Constant)
                  and isinstance(node.slice.value, str)
                  and node.slice.value.startswith("REVAL_TPU_")):
                out.append(Violation(
                    PASS, rel, node.lineno,
                    f"raw os.environ[{node.slice.value!r}] read — route "
                    f"it through reval_tpu.env"))

    out.extend(_check_readme(root, env))
    out.extend(_check_zombies(root, sources, env))
    return out


def _check_routed(call: ast.Call, rel: str, env: dict,
                  out: list[Violation]) -> None:
    name = _env_name_arg(call)
    if name is None:
        out.append(Violation(
            PASS, rel, call.lineno,
            "env accessor called with a non-literal name — the registry "
            "(and this lint) can only track literal REVAL_TPU_* names"))
        return
    if name not in env:
        out.append(Violation(
            PASS, rel, call.lineno,
            f"env var {name!r} is not declared in reval_tpu.env.ENV"))


def _readme_env_names(root: str) -> set[str] | None:
    try:
        with open(os.path.join(root, "README.md")) as f:
            text = f.read()
    except OSError:
        return None
    names = set()
    for line in text.splitlines():
        m = _README_ROW_RE.match(line)
        if m:
            names.add(m.group(1))
    return names


def _check_readme(root: str, env: dict) -> list[Violation]:
    out: list[Violation] = []
    documented = _readme_env_names(root)
    if documented is None:
        return [Violation(PASS, "README.md", 0, "cannot read README.md")]
    for name in env:
        if name not in documented:
            out.append(Violation(
                PASS, "README.md", 0,
                f"{name}: declared in reval_tpu.env.ENV but missing from "
                f"the README environment table"))
    for name in documented:
        if name not in env:
            out.append(Violation(
                PASS, "README.md", 0,
                f"{name}: in the README environment table but not "
                f"declared in reval_tpu.env.ENV"))
    return out


def _check_zombies(root: str, sources: dict[str, SourceFile],
                   env: dict) -> list[Violation]:
    """A declared var no source (lint tree + tests/) mentions is dead."""
    corpus = [src.text for rel, src in sources.items()
              if rel.replace("\\", "/") != "reval_tpu/env.py"]
    for path in glob.glob(os.path.join(root, "tests", "*.py")):
        try:
            with open(path) as f:
                corpus.append(f.read())
        except OSError:
            pass
    blob = "\n".join(corpus)
    out: list[Violation] = []
    for name in env:
        # word-boundary match: REVAL_TPU_LOG must not count a reference
        # just because REVAL_TPU_LOG_LEVEL appears somewhere
        if not re.search(re.escape(name) + r"(?![A-Z0-9_])", blob):
            out.append(Violation(
                PASS, _SPEC_REL, 0,
                f"{name}: declared in reval_tpu.env.ENV but referenced "
                f"nowhere in the tree — dead config"))
    return out
