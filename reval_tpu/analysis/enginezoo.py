"""Engine-surface conformance pass (``enginezoo``).

MULTICHIP dry-runs nine parallelism legs but each is its own engine
class, so every feature (prefix cache, AOT cache, warm restarts, spec
decode) lands N times or not at all — ROADMAP item 3 exists to collapse
the zoo into ONE mesh-native engine.  Until that lands, this pass makes
the zoo's feature skew EXPLICIT: the shared engine surface is declared
once (:data:`SURFACE`), and every engine class must *implement* each
member, *delegate* it (inherit from a registered base), or carry a
reasoned ``# not-supported: <member> — <why>`` marker in its class
body.  A new engine method that is not part of the declared surface is
an ORPHAN — the "lands in one engine out of nine" failure mode — and
must either join :data:`SURFACE` (forcing a zoo-wide decision) or be
marked ``# engine-local: <why>`` at its ``def``.

The resulting engine × member matrix is COMMITTED as
``ENGINE_SURFACE.md`` (regenerate with
``python tools/reval_lint.py --write-engine-matrix``); the pass fails
when the artifact goes stale, so item-3 collapse progress — and any new
skew — is visible in every diff.

Suppression: ``# lint: allow(enginezoo) — <reason>`` (driver policy).
"""

from __future__ import annotations

import ast
import re

from .core import SourceFile, Violation

PASS = "enginezoo"

#: the artifact the matrix is committed as, repo-relative
ARTIFACT = "ENGINE_SURFACE.md"

#: engine class -> defining file (repo-relative)
ENGINES: dict[str, str] = {
    "TPUEngine": "reval_tpu/inference/tpu/engine.py",
    "PagedTPUEngine": "reval_tpu/inference/tpu/paged_engine.py",
    "DataParallelPagedEngine": "reval_tpu/inference/tpu/dp_paged.py",
    "PipelinedTPUEngine": "reval_tpu/inference/tpu/pp_engine.py",
    "MockStepEngine": "reval_tpu/serving/mock_engine.py",
}

#: the shared engine surface: member -> one-line meaning.  Adding a
#: member here forces a zoo-wide decision (implement / delegate /
#: reasoned not-supported) for EVERY engine.
SURFACE: dict[str, str] = {
    "from_pretrained": "construct from a checkpoint path",
    "generate": "whole-batch generation entry point",
    "close": "release driver threads / pools / native runtime state",
    "stats": "the EngineStats counters/histograms surface",
    "jit_counters": "compile-variant snapshot of the tracked jit entries",
    "aot_counters": "persistent AOT executable-cache counters",
    "prefix_cache_counters": "radix prefix-cache hit/eviction counters",
    "warm_state": "warm-restart snapshot (prefix chains, template stats)",
    "rewarm": "replay a warm-state snapshot through real prefill",
    "submit_request": "continuous-batching request admission",
    "release_request": "continuous-batching request teardown",
    "new_drive_state": "fresh per-driver drive-loop state",
    "encode_clipped": "tokenize a prompt clipped to the engine's budget",
    "request_keys": "per-request PRNG keys for sampled decode",
    "spec_counters": "speculative-decoding accept/draft counter snapshot",
    "grammar_state": "compile a grammar name into the engine's "
                     "constraint tables, returning its start state",
    "receipt_context": "serving-config fingerprint input for "
                       "reproducibility receipts (obs/receipts.py)",
}

_NOT_SUPPORTED_RE = re.compile(
    r"#\s*not-supported:\s*([A-Za-z_][A-Za-z0-9_]*)\s*(?:[—:–-]+\s*(\S.*))?$")
_ENGINE_LOCAL_RE = re.compile(r"#\s*engine-local\s*(?:[:—])\s*(\S.*)?$")


class EngineInfo:
    def __init__(self, name: str, rel: str, node: ast.ClassDef):
        self.name = name
        self.rel = rel
        self.node = node
        self.bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
        #: member -> def line (methods, properties, self.X ctor attrs)
        self.members: dict[str, int] = {}
        #: member -> (reason, line) from ``# not-supported:`` markers
        self.not_supported: dict[str, tuple[str, int]] = {}
        #: public defs in the class body: name -> (line, has engine-local)
        self.public_defs: dict[str, tuple[int, bool]] = {}


def _collect_engine(src: SourceFile, name: str) -> EngineInfo | None:
    for node in src.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            break
    else:
        return None
    info = EngineInfo(name, src.rel, node)
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.members[stmt.name] = stmt.lineno
            if not stmt.name.startswith("_"):
                local = any(_ENGINE_LOCAL_RE.search(c)
                            for _, c in src.comment_block(stmt.lineno))
                info.public_defs[stmt.name] = (stmt.lineno, local)
            # attributes assigned in the ctor count as implemented
            # (EngineStats rides ``self.stats = ...``)
            if stmt.name == "__init__":
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Assign):
                        for t in sub.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                info.members.setdefault(t.attr, sub.lineno)
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    info.members[t.id] = stmt.lineno
    end = getattr(node, "end_lineno", None) or node.lineno
    for line in range(node.lineno, end + 1):
        comment = src.comments.get(line)
        if not comment:
            continue
        m = _NOT_SUPPORTED_RE.search(comment)
        if m:
            info.not_supported[m.group(1)] = ((m.group(2) or "").strip(),
                                              line)
    return info


def _resolve(member: str, info: EngineInfo,
             infos: dict[str, EngineInfo]) -> tuple[str, str]:
    """('implemented' | 'delegated' | 'not-supported' | 'missing',
    detail) for one engine × member cell."""
    if member in info.members:
        return "implemented", ""
    if member in info.not_supported:
        return "not-supported", info.not_supported[member][0]
    for base in info.bases:
        base_info = infos.get(base)
        if base_info is None:
            continue
        status, detail = _resolve(member, base_info, infos)
        if status == "implemented" or status == "delegated":
            return "delegated", base
        if status == "not-supported":
            return "not-supported", f"via {base}: {detail}" if detail else \
                f"via {base}"
    return "missing", ""


def collect(sources: dict[str, SourceFile], out: list[Violation]
            ) -> dict[str, EngineInfo]:
    infos: dict[str, EngineInfo] = {}
    for name, rel in ENGINES.items():
        src = sources.get(rel)
        if src is None:
            out.append(Violation(
                PASS, rel, 0,
                f"engine file for {name} not found — update the "
                f"enginezoo ENGINES registry"))
            continue
        info = _collect_engine(src, name)
        if info is None:
            out.append(Violation(
                PASS, rel, 0,
                f"engine class {name} not found in {rel} — update the "
                f"enginezoo ENGINES registry"))
            continue
        infos[name] = info
    return infos


def check(infos: dict[str, EngineInfo], out: list[Violation]) -> None:
    for name, info in infos.items():
        for member in SURFACE:
            status, _ = _resolve(member, info, infos)
            if status == "missing":
                out.append(Violation(
                    PASS, info.rel, info.node.lineno,
                    f"engine {name} neither implements, inherits, nor "
                    f"declares '# not-supported: {member} — <why>' for "
                    f"surface member {member!r}"))
        for member, (reason, line) in info.not_supported.items():
            if member not in SURFACE:
                out.append(Violation(
                    PASS, info.rel, line,
                    f"not-supported marker for {member!r}, which is not "
                    f"a declared surface member"))
            elif not reason:
                out.append(Violation(
                    PASS, info.rel, line,
                    f"not-supported marker for {member!r} without a "
                    f"reason — say WHY this engine lacks it"))
            elif member in info.members:
                out.append(Violation(
                    PASS, info.rel, line,
                    f"zombie not-supported marker: {name} DOES "
                    f"implement {member!r} (line "
                    f"{info.members[member]}) — remove the marker"))
        for member, (line, local) in info.public_defs.items():
            if member in SURFACE or local:
                continue
            out.append(Violation(
                PASS, info.rel, line,
                f"orphan engine method {name}.{member}: public but not "
                f"a declared surface member — add it to "
                f"analysis/enginezoo.py::SURFACE (zoo-wide decision) or "
                f"mark the def '# engine-local: <why>'"))


def render_matrix(infos: dict[str, EngineInfo]) -> str:
    """The committed feature-parity matrix (ENGINE_SURFACE.md)."""
    names = [n for n in ENGINES if n in infos]
    lines = [
        "# Engine feature-parity matrix",
        "",
        "Generated by the `enginezoo` lint pass — DO NOT EDIT.",
        "Regenerate with `python tools/reval_lint.py "
        "--write-engine-matrix`.",
        "",
        "Legend: `yes` implemented here, `-> Base` delegated to a base "
        "class, `NO: <why>` a reasoned gap.  Every `NO` is a feature "
        "the ROADMAP item-3 engine collapse erases; the per-engine "
        "coverage row is the collapse-progress metric.",
        "",
        "| member | " + " | ".join(names) + " |",
        "|" + "---|" * (len(names) + 1),
    ]
    coverage = {n: 0 for n in names}
    for member, meaning in SURFACE.items():
        cells = []
        for n in names:
            status, detail = _resolve(member, infos[n], infos)
            if status == "implemented":
                cells.append("yes")
                coverage[n] += 1
            elif status == "delegated":
                cells.append(f"-> {detail}")
                coverage[n] += 1
            elif status == "not-supported":
                cells.append(f"NO: {detail}" if detail else "NO")
            else:
                cells.append("MISSING")
        lines.append(f"| `{member}` — {meaning} | " + " | ".join(cells)
                     + " |")
    total = len(SURFACE)
    lines.append("| **coverage** | " + " | ".join(
        f"{coverage[n]}/{total}" for n in names) + " |")
    return "\n".join(lines) + "\n"


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    import os

    out: list[Violation] = []
    infos = collect(sources, out)
    if not infos:
        return out
    check(infos, out)
    # the committed artifact must match the tree it describes
    expected = render_matrix(infos)
    path = os.path.join(root, ARTIFACT)
    try:
        with open(path) as f:
            actual = f.read()
    except OSError:
        out.append(Violation(
            PASS, ARTIFACT, 0,
            f"feature-parity matrix artifact {ARTIFACT} missing — "
            f"generate it with tools/reval_lint.py --write-engine-matrix"))
        return out
    if actual != expected:
        out.append(Violation(
            PASS, ARTIFACT, 0,
            f"{ARTIFACT} is stale — the engine surface changed; "
            f"regenerate with tools/reval_lint.py --write-engine-matrix"))
    return out
