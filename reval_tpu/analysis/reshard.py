"""Resharding-discipline pass (``reshard``).

A ``with_sharding_constraint`` is a compiled-in data movement order: the
wrong spec (or a bare ``P()``) makes XLA all-gather a sharded activation
onto every chip — gigabytes of ICI traffic that look like "the model got
slower" with nothing in any log.  ``device_put`` and zero-arg
``PartitionSpec()`` (full replication) inside the latency-critical
regions are the same hazard one level up: a replicated transient on a
hot path costs mesh-size× HBM and a transfer per tick.  This pass makes
every such site carry its reasoning, the way ``hostsync`` forces
``# host-sync: <why>`` on deliberate device→host fetches:

- ``jax.lax.with_sharding_constraint`` ANYWHERE in the sharded core
  (``parallel/``, ``models/``, ``inference/tpu/``) needs an inline
  ``# reshard: <why>`` (same line or the comment block above; the
  reason is mandatory — a bare marker reports and silences nothing);
- inside ``# hot-path`` functions and jit-entry bodies (the same
  regions :mod:`.hostsync` guards), ``jax.device_put`` and zero-arg
  ``PartitionSpec()``/``P()`` constructors need one too — an accidental
  full replication in a drive tick or compiled chunk is exactly the
  silent resharding the runtime shardcheck sanitizer
  (``REVAL_TPU_SHARDCHECK=1``) counts at test time.

Suppression: the reasoned ``# reshard: <why>`` IS the suppression (the
reason lands in the report's annotation, not the driver ledger);
``# lint: allow(reshard) — <reason>`` also works (driver policy).
"""

from __future__ import annotations

import ast
import re

from .core import SourceFile, Violation
from .core import call_chain as _call_chain
from . import jitreg

PASS = "reshard"

SCOPE_PREFIXES = ("reval_tpu/parallel/", "reval_tpu/models/",
                  "reval_tpu/inference/tpu/")

_RESHARD_RE = re.compile(r"#\s*reshard\s*(?:[:—])\s*(\S.*)?$")


def _reasoned(src: SourceFile, line: int, out: list[Violation]) -> bool:
    """True when ANY ``# reshard:`` marker covers ``line``.  A marker
    with no reason is itself reported (ONE violation, anchored at the
    marker — never a second 'marker missing' report at the call site,
    which would misdirect the fix toward adding a duplicate marker)."""
    for ln, comment in src.comment_block(line):
        m = _RESHARD_RE.search(comment)
        if m:
            if not (m.group(1) or "").strip():
                out.append(Violation(
                    PASS, src.rel, ln,
                    "reshard marker without a reason — say WHY this "
                    "data movement is intended"))
            return True
    return False


def _spec_aliases(src: SourceFile) -> set[str]:
    names = {"PartitionSpec"}
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax.sharding"
                or node.module.endswith(".sharding")):
            for alias in node.names:
                if alias.name == "PartitionSpec":
                    names.add(alias.asname or alias.name)
    return names


def _check_region(src: SourceFile, fn, label: str, aliases: set[str],
                  out: list[Violation], seen: set[int]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) or id(node) in seen:
            continue
        chain = _call_chain(node.func)
        if not chain:
            continue
        denied = None
        if chain[-1] == "device_put":
            denied = f"{'.'.join(chain)} (host→device placement)"
        elif (chain[-1] in aliases and not node.args
              and not node.keywords):
            denied = "zero-arg PartitionSpec() (full replication)"
        if denied is None:
            continue
        seen.add(id(node))
        if _reasoned(src, node.lineno, out):
            continue
        out.append(Violation(
            PASS, src.rel, node.lineno,
            f"{label} performs {denied} — an unintended reshard/"
            f"replication here is a silent all-gather; mark the "
            f"deliberate movement with '# reshard: <why>'"))


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    for rel, src in sorted(sources.items()):
        if not rel.replace("\\", "/").startswith(SCOPE_PREFIXES):
            continue
        aliases = _spec_aliases(src)
        seen: set[int] = set()

        # 1. every with_sharding_constraint in scope carries a reason
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and _call_chain(node.func)[-1:]
                    == ["with_sharding_constraint"]):
                seen.add(id(node))
                if not _reasoned(src, node.lineno, out):
                    out.append(Violation(
                        PASS, src.rel, node.lineno,
                        "with_sharding_constraint without a "
                        "'# reshard: <why>' — a constraint is a "
                        "compiled-in data movement order; say what it "
                        "prevents"))

        # 2. hot-path functions + jit-entry bodies: device_put and
        # zero-arg PartitionSpec need a reason too
        ann = src.annotations()
        checked: set[int] = set()
        if ann.hot:
            def walk(body, qual):
                for node in body:
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fq = f"{qual}.{node.name}" if qual else node.name
                        if fq in ann.hot and id(node) not in checked:
                            checked.add(id(node))
                            _check_region(src, node,
                                          f"hot-path function {fq!r}",
                                          aliases, out, seen)
                        else:
                            walk(node.body, fq)
                    elif isinstance(node, ast.ClassDef):
                        walk(node.body, node.name)

            walk(src.tree.body, "")
        if jitreg.in_scope(rel):
            for entry in jitreg.collect_entries(src, None):
                fn = entry.target
                if fn is None or id(fn) in checked:
                    continue
                checked.add(id(fn))
                _check_region(src, fn, f"jit entry {entry.name!r} body",
                              aliases, out, seen)
    return out
