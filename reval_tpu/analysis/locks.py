"""Lock-discipline / race detector pass (``locks``).

The contract is declared where the state lives: a shared field carries
``# guarded-by: <lock>`` (optionally ``(writes)`` when lock-free reads
are deliberate) on its initialising assignment, a deliberately lock-free
field carries ``# unguarded: <why>``, and a helper that is only ever
called with a lock already held carries ``# lock-held: <lock>`` on its
``def`` line.  This pass then enforces, lexically, over every function
in the file:

1. every read/write of a guarded field is inside a ``with <base>.<lock>``
   block whose BASE expression matches the access (``self._inflight``
   under ``with self._acct_lock``, ``other._metrics`` under ``with
   other._lock``), or inside a method declared lock-held for that lock;
2. a declared guard names a lock that actually exists in its module
   (a typo'd lock name is a silent no-op contract otherwise);
3. every lock-owning class classifies its shared mutable containers:
   each ``self.x = {}/[]/set()/deque()`` in ``__init__`` must be either
   ``# guarded-by:`` one of the module's locks or explicitly
   ``# unguarded: <reason>`` — unclassified shared mutable state in a
   threaded class is exactly how the next data race ships.

``__init__``/``__post_init__`` bodies are exempt from (1) for ``self``
accesses (construction happens before publication), module-level
statements run under the import lock and are likewise exempt, nested
function bodies reset the held set (they execute later, outside the
enclosing ``with``), and the declaring line itself never violates.
"""

from __future__ import annotations

import ast

from .core import (_LOCKHELD_RE, _target_name, GuardSpec, SourceFile,
                   Violation)

PASS = "locks"

#: constructors whose result is shared-mutable enough to demand a
#: guarded-by / unguarded classification in lock-owning classes
_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter"}

_CTOR_NAMES = ("__init__", "__post_init__")


def _is_function_owner(spec: GuardSpec) -> bool:
    """Guards declared on plain names inside a function body (dp_paged's
    local work queue) vs class fields / module globals."""
    return spec.owner != "<module>" and ("." in spec.owner
                                         or spec.owner[:1].islower())


def _mutable_value(value: ast.expr | None) -> bool:
    if value is None:
        return False
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.BinOp):       # [None] * n
        return _mutable_value(value.left) or _mutable_value(value.right)
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        return name in _MUTABLE_CTORS
    return False


def _decl_held(src: SourceFile, node) -> list[tuple[str, str]]:
    """Initial held set from a ``# lock-held: L`` def annotation (the
    caller holds SELF's lock; that is the only sane contract here)."""
    for _, comment in src.comment_block(node.lineno):
        m = _LOCKHELD_RE.search(comment)
        if m:
            return [("self", m.group(1))]
    return []


class _FunctionChecker(ast.NodeVisitor):
    """Walk ONE function body tracking which (base, lock) pairs are
    lexically held."""

    def __init__(self, src: SourceFile, attr_guards: dict[str, GuardSpec],
                 name_guards: dict[str, GuardSpec], lock_names: set[str],
                 out: list[Violation], initial_held, exempt_self: bool):
        self.src = src
        self.attr_guards = attr_guards
        self.name_guards = name_guards
        self.lock_names = lock_names
        self.out = out
        self.held: list[tuple[str, str]] = list(initial_held)
        self.exempt_self = exempt_self

    # -- lock scopes -------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            expr = item.context_expr
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.attr in self.lock_names):
                acquired.append((expr.value.id, expr.attr))
            elif isinstance(expr, ast.Name) and expr.id in self.lock_names:
                acquired.append(("", expr.id))
        self.held.extend(acquired)
        for sub in node.body:
            self.visit(sub)
        for _ in acquired:
            self.held.pop()

    def _nested(self, node) -> None:
        """A nested def's body runs LATER — fresh held set (its own
        ``# lock-held`` annotation, if any, still applies)."""
        sub = _FunctionChecker(self.src, self.attr_guards, self.name_guards,
                               self.lock_names, self.out,
                               _decl_held(self.src, node), exempt_self=False)
        for stmt in node.body:
            sub.visit(stmt)

    def visit_FunctionDef(self, node) -> None:
        self._nested(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass                # executes later; treated like a nested def

    # -- guarded accesses --------------------------------------------------
    def _flag(self, node, name: str, base: str, spec: GuardSpec) -> None:
        verb = "read" if isinstance(node.ctx, ast.Load) else "write"
        dotted = f"{base}.{name}" if base else name
        lock = f"{base}.{spec.lock}" if base else spec.lock
        self.out.append(Violation(
            PASS, self.src.rel, node.lineno,
            f"{verb} of {dotted} (guarded-by {spec.lock!r}) outside "
            f"`with {lock}`"))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)
        spec = self.attr_guards.get(node.attr)
        if spec is None or not isinstance(node.value, ast.Name):
            return
        base = node.value.id
        if spec.writes_only and isinstance(node.ctx, ast.Load):
            return
        if node.lineno == spec.line:
            return                      # the declaring assignment
        if self.exempt_self and base == "self":
            return                      # constructor: pre-publication
        if (base, spec.lock) not in self.held:
            self._flag(node, node.attr, base, spec)

    def visit_Name(self, node: ast.Name) -> None:
        spec = self.name_guards.get(node.id)
        if spec is None:
            return
        if spec.writes_only and isinstance(node.ctx, ast.Load):
            return
        if node.lineno == spec.line:
            return
        if ("", spec.lock) not in self.held:
            self._flag(node, node.id, "", spec)


def run(sources: dict[str, SourceFile], root: str) -> list[Violation]:
    out: list[Violation] = []
    for rel, src in sorted(sources.items()):
        if not rel.startswith("reval_tpu"):
            continue
        ann = src.annotations()
        for line, problem in ann.problems:
            out.append(Violation(PASS, rel, line, problem))
        if not ann.guards and not ann.locks:
            continue
        lock_names: set[str] = set()
        for names in ann.locks.values():
            lock_names |= names
        for spec in ann.guards.values():
            if spec.lock not in lock_names:
                out.append(Violation(
                    PASS, rel, spec.line,
                    f"field {spec.fieldname!r} declared guarded-by "
                    f"{spec.lock!r}, but no such lock is created in this "
                    f"module (typo?)"))
        attr_guards = {n: s for n, s in ann.guards.items()
                       if not _is_function_owner(s) and s.owner != "<module>"}
        name_guards = {n: s for n, s in ann.guards.items()
                       if _is_function_owner(s) or s.owner == "<module>"}
        out.extend(_check_containers(src, ann))
        _walk_functions(src, src.tree.body, attr_guards, name_guards,
                        lock_names, out)
    return out


def _check_containers(src: SourceFile, ann) -> list[Violation]:
    out: list[Violation] = []
    for node in src.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        if not ann.locks.get(node.name):
            continue
        ctor = next((n for n in node.body if isinstance(n, ast.FunctionDef)
                     and n.name in _CTOR_NAMES), None)
        if ctor is None:
            continue
        for stmt in ast.walk(ctor):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            name, is_self = _target_name(stmt)
            if not is_self or name is None:
                continue
            if not _mutable_value(getattr(stmt, "value", None)):
                continue
            if name in ann.guards or name in ann.unguarded:
                continue
            out.append(Violation(
                PASS, src.rel, stmt.lineno,
                f"class {node.name} owns a lock but its shared mutable "
                f"attribute {name!r} is neither '# guarded-by: <lock>' "
                f"nor '# unguarded: <reason>'"))
    return out


def _walk_functions(src, body, attr_guards, name_guards, lock_names,
                    out) -> None:
    for node in body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            checker = _FunctionChecker(
                src, attr_guards, name_guards, lock_names, out,
                _decl_held(src, node),
                exempt_self=node.name in _CTOR_NAMES)
            for sub in node.body:
                checker.visit(sub)
        elif isinstance(node, ast.ClassDef):
            _walk_functions(src, node.body, attr_guards, name_guards,
                            lock_names, out)
