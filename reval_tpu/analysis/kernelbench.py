"""``kernelbench`` pass: kernel-CI leaderboard artifacts conform to schema.

The self-healing kernel CI's whole value is *instrument honesty*: a cell
silently missing from a ``kernelbench-<ts>.json`` leaderboard reads as
"nothing regressed" when it means "nobody measured", and a stale cell
rendered as a bare number reads as a fresh measurement.  This pass
validates every leaderboard artifact on disk against the declared schema
(``reval_tpu/kernelbench.py::validate_leaderboard`` — ONE checker shared
with the CLI's pre-write self-check and the tests):

- the schema version is the one this tree writes;
- the cell matrix is COMPLETE for its tier (tiny/full): every taxonomy
  cell appears as ``run``, ``stale``, or ``skipped`` WITH a reason —
  never vanished, and never a 0.0 measurement;
- stale entries carry their last-known value + the commit it was
  measured at;
- a declared winner is a fresh run cell and emits a loadable
  serving-config pick.

Artifacts are scanned in ``tpu_watch/`` (generated, untracked scratch)
AND as committed ``KERNELBENCH_r*.json`` driver records at the repo root
(which may nest the artifact under ``"parsed"``).  None on disk =
nothing to lint (clean); an unreadable/truncated artifact IS a violation
— a half-written leaderboard must never pass for a clean round.
"""

from __future__ import annotations

import glob
import json
import os

from .core import Violation

__all__ = ["run"]


def run(sources, root: str) -> list[Violation]:
    from ..kernelbench import SCHEMA, validate_leaderboard

    out: list[Violation] = []
    paths = (sorted(glob.glob(os.path.join(root, "tpu_watch",
                                           "kernelbench-*.json")))
             + sorted(glob.glob(os.path.join(root, "KERNELBENCH_r*.json"))))
    for path in paths:
        rel = os.path.relpath(path, root)
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            out.append(Violation("kernelbench", rel, 0,
                                 f"unreadable leaderboard artifact: "
                                 f"{type(e).__name__}: {e}"))
            continue
        # driver records nest the harness's artifact under "parsed"
        if (isinstance(obj, dict) and obj.get("schema") != SCHEMA
                and isinstance(obj.get("parsed"), dict)):
            obj = obj["parsed"]
        for err in validate_leaderboard(obj):
            out.append(Violation("kernelbench", rel, 0, err))
    return out
