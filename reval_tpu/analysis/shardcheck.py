"""Runtime sharding sanitizer (``REVAL_TPU_SHARDCHECK=1``) + the
always-on sharding-mismatch counters behind ``reval_shard_*``.

The static ``mesh``/``reshard`` passes prove the DECLARED placement
contracts (axis names, shard_map specs, reasoned reshards); what they
cannot see is dynamic: whether the arrays flowing through the engines'
jit entries actually CARRY the declared shardings once real shapes and
donation run.  A silently-resharded operand is the worst kind of perf
bug — XLA inserts the all-gather for you, results stay correct, and the
only symptom is a mesh-size× step time — and per the backend-
reproducibility study (PAPERS.md, arxiv 2605.19537) implicit
replication differences are exactly what corrupts cross-backend parity.
Two layers close the gap (mirroring ``lockcheck``/``jitcheck``):

- :class:`ShardGuard` — ALWAYS ON where an engine has a mesh: a thin
  wrapper around a tracked jit entry that, per call, compares selected
  input/output arrays' actual ``.sharding`` against the engine's
  declared :class:`~jax.sharding.NamedSharding` via
  ``Sharding.is_equivalent_to`` (attribute reads only — never a sync).
  Every comparison bumps ``reval_shard_checks_total``; every divergence
  bumps ``reval_shard_respec_total`` (each mismatched call is one
  unintended cross-device transfer) and emits ONE ``shard.respec``
  warning event per distinct (entry, site, actual) signature, so a
  steady-state respec storm is a counter slope, not a log flood.

- :class:`ShardSanitizer` — test-time (``REVAL_TPU_SHARDCHECK=1`` via
  conftest, or :func:`install` directly).  While installed, each
  distinct divergence is also recorded as a violation naming the
  DECLARED spec and the ACTUAL sharding; violations accumulate (a
  sanitizer must not change program behavior) and the conftest wiring
  fails the pytest session if any exist — the same
  accumulate-then-fail contract as lockcheck/jitcheck.  Use
  :func:`scoped` in tests that seed violations deliberately, so a
  session-level install never inherits them.

Pytree values (the paged KV cache) are checked leaf-wise: every jax
array leaf whose rank can carry the declared spec is compared; lower-
rank leaves (int8 scale arrays under a pool spec) are skipped — their
placement is derived from the checked pool arrays at construction.
"""

from __future__ import annotations

import threading

from ..obs.logging import log_event
from ..obs.metrics import SHARD_CHECKS, SHARD_RESPECS

__all__ = ["ShardSanitizer", "ShardGuard", "install", "uninstall",
           "current", "scoped"]


class ShardSanitizer:
    """Violation ledger for declared-vs-actual sharding divergences."""

    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock (writes)
        # (conftest reads the ledger once, after the session drained)
        self.violations: list[dict] = []

    def record(self, entry: str, site: str, declared: str,
               actual: str) -> None:
        with self._lock:
            self.violations.append({
                "kind": "sharding-respec",
                "entry": entry,
                "detail": f"entry {entry!r} {site}: declared sharding "
                          f"{declared} but the array actually carries "
                          f"{actual} — an unintended cross-device "
                          f"reshard (XLA inserts the transfer silently)"})


_current: ShardSanitizer | None = None


def install() -> ShardSanitizer:
    """Activate the sanitizer (idempotent per process): every distinct
    divergence a :class:`ShardGuard` observes becomes a violation."""
    global _current
    if _current is None:
        _current = ShardSanitizer()
    return _current


def uninstall() -> None:
    global _current
    _current = None


def current() -> ShardSanitizer | None:
    return _current


class scoped:
    """Temporarily swap the process-global sanitizer: a FRESH ledger
    when ``active`` (or none at all when not), restoring whatever was
    installed before on exit — how tests seed violations without
    polluting a session-level ``REVAL_TPU_SHARDCHECK=1`` install."""

    def __init__(self, active: bool = True):
        self._active = active
        self._prev: ShardSanitizer | None = None

    def __enter__(self) -> ShardSanitizer | None:
        global _current
        self._prev = _current
        _current = ShardSanitizer() if self._active else None
        return _current

    def __exit__(self, *exc):
        global _current
        _current = self._prev
        return False


def _describe(sharding) -> str:
    spec = getattr(sharding, "spec", None)
    if spec is not None:
        return f"NamedSharding({spec})"
    return type(sharding).__name__


def _leaves(value):
    import jax

    return [leaf for leaf in jax.tree_util.tree_leaves(value)
            if hasattr(leaf, "sharding") and hasattr(leaf, "ndim")]


class ShardGuard:
    """Declared-sharding check around one jit entry (see module
    docstring).  ``in_checks``: {positional index | kwarg name →
    expected NamedSharding}; ``out_checks``: {output tuple index →
    expected NamedSharding} (index 0 checks a non-tuple result).
    Attribute access delegates to the wrapped entry, so ``variants``/
    ``misses``/``name`` keep riding ``jit_counters()`` unchanged."""

    __slots__ = ("_fn", "name", "_in", "_out", "_registry", "_seen",
                 "_lock")

    def __init__(self, name: str, fn, in_checks=None, out_checks=None,
                 registry=None):
        self._fn = fn
        self.name = name
        # unguarded: written once at construction, read-only afterwards
        self._in = dict(in_checks or {})
        # unguarded: written once at construction, read-only afterwards
        self._out = dict(out_checks or {})
        # registry may be the MetricsRegistry or a zero-arg callable
        # returning it (engines hand a callable — see TrackedJit)
        self._registry = registry
        # guarded-by: _lock (writes)
        # distinct (site, actual) signatures already eventted/recorded
        self._seen: set = set()
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        checks = respecs = 0
        for key, expected in self._in.items():
            value = (kwargs.get(key) if isinstance(key, str)
                     else (args[key] if key < len(args) else None))
            c, r = self._check(f"input {key!r}", value, expected)
            checks += c
            respecs += r
        outs = out if isinstance(out, tuple) else (out,)
        for idx, expected in self._out.items():
            value = outs[idx] if idx < len(outs) else None
            c, r = self._check(f"output [{idx}]", value, expected)
            checks += c
            respecs += r
        reg = self._registry
        if callable(reg):
            reg = reg()
        if reg is not None and checks:
            reg.counter(SHARD_CHECKS).add(checks)
            if respecs:
                reg.counter(SHARD_RESPECS).add(respecs)
        return out

    def _check(self, site: str, value, expected) -> tuple[int, int]:
        """(comparisons, mismatches) for one declared site."""
        if value is None:
            # a declared check that does not resolve against the actual
            # call shape (arg index past len(args), kwarg absent, output
            # index past the tuple) means the call site drifted from the
            # guard's wiring — an inert guard reads exactly like a clean
            # one, so say so loudly (once per site) instead of silently
            # checking nothing forever
            self._flag(site, "unresolved — the declared check did not "
                             "match the call shape (argument/output "
                             "absent); the guard is inert at this site")
            return 0, 0
        checks = respecs = 0
        rank = len(expected.spec)
        for leaf in _leaves(value):
            if leaf.ndim < rank:
                continue        # derived lower-rank leaf (scales)
            try:
                ok = leaf.sharding.is_equivalent_to(expected, leaf.ndim)
            except Exception:
                continue        # foreign sharding type — unverifiable
            checks += 1
            if ok:
                continue
            respecs += 1
            self._flag(site, _describe(leaf.sharding),
                       declared=_describe(expected))
        return checks, respecs

    def _flag(self, site: str, actual: str,
              declared: str | None = None) -> None:
        """Report one divergence (or an unresolved check) ONCE per
        distinct (site, actual) signature: event + sanitizer ledger."""
        sig = (site, actual)
        with self._lock:
            if sig in self._seen:
                return
            self._seen.add(sig)
        log_event("shard.respec", level="warning", entry=self.name,
                  site=site, declared=declared or "<check wiring>",
                  actual=actual)
        san = _current
        if san is not None:
            san.record(self.name, site, declared or "<check wiring>",
                       actual)

    def __getattr__(self, item):
        return getattr(self._fn, item)
