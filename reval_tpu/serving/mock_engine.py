"""MockStepEngine: a host-only engine speaking the session driver contract.

``serve --mock`` and the fast-tier lifecycle tests need the FULL serving
stack — admission control, deadlines, the watchdog, graceful drain — with
none of the jit-compile cost a real (even tiny) model pays.  This engine
implements exactly the surface :class:`~reval_tpu.serving.session.
ContinuousSession` drives (``encode_clipped`` / ``request_keys`` /
``submit_request`` / ``release_request`` / ``new_drive_state`` /
``_drive_tick`` / ``stats`` / ``heartbeat``), generating a fixed response
string a few tokens per tick, so every lifecycle path is exercised in
milliseconds and the chaos hooks (stalled step, mid-batch exception)
behave exactly as they would around a real decode step.

``step_s`` inserts a per-tick sleep — the knob deadline/drain tests use
to make "mid-decode" a real, controllable interval.

``echo=True`` makes the canned response a deterministic function of the
PROMPT (a crc32 tag over its token ids) instead of one fixed string —
the knob the fleet-router chaos drill turns so "bit-identical greedy
outputs regardless of which replica answered" is a real assertion, not
a tautology over identical constants.

Warm restarts ride the mock too (the rolling-restart drill is
host-only): with ``REVAL_TPU_AOT_CACHE_DIR`` set, boot loads its two
simulated programs ("mock.prefill", "mock.decode_chunk") through the
REAL :class:`~reval_tpu.inference.tpu.aot_cache.AOTCache` — a cold
boot "compiles" (counted in ``fresh_compiles``) and stores; a warm
restart loads both (cache hits, zero fresh compiles) — and
``warm_state()`` / ``rewarm()`` give the session's snapshot/restore
path a host-only engine to drive (``rewarm_s`` paces the replay so the
``warming`` readiness state is observable in tests).
"""

from __future__ import annotations

import json
import time
import zlib
from types import SimpleNamespace

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs.flightrec import FlightRecorder

__all__ = ["MockStepEngine"]


class MockStepEngine:
    # Engine-surface gaps (enginezoo pass):
    # not-supported: from_pretrained — host-only mock: the canned string IS the model
    # not-supported: generate — speaks only the session driver contract (submit/tick)
    # not-supported: jit_counters — no jitted programs; AOT simulation reports via aot_counters
    # not-supported: prefix_cache_counters — warm chains are a list, not a radix cache
    page_size = 128

    def __init__(self, response: str = "mock_model_gen", step_s: float = 0.0,
                 tokens_per_step: int = 16, max_slots: int = 8,
                 max_seq_len: int = 8192, echo: bool = False,
                 rewarm_s: float = 0.0):
        from ..inference.tpu.engine import EngineStats
        from ..inference.tpu.tokenizer import ByteTokenizer

        self.tokenizer = ByteTokenizer()
        self.stats = EngineStats()
        self.response = response
        self.echo = bool(echo)
        self.step_s = float(step_s)
        self.rewarm_s = float(rewarm_s)
        self.tokens_per_step = int(tokens_per_step)
        self.max_slots = int(max_slots)
        self.max_pages_per_seq = max(1, int(max_seq_len) // self.page_size)
        self._resp_ids = [t for t in self.tokenizer.encode(response)
                          if t != self.tokenizer.bos_id]
        self._next_seq = 0
        #: submitted-but-unreleased sequences — the invariant tests assert
        #: drops back to zero after every cancel/expiry/failure path
        self.live = 0
        self.heartbeat = time.monotonic()
        #: same per-step ring the paged engine feeds — serve --mock
        #: exercises the flight-recorder/postmortem path host-only
        self.flightrec = FlightRecorder()
        #: warm-state the snapshot carries: page-aligned prompt prefixes
        #: seen (the mock's stand-in for the radix tree) + per-template
        #: tags (crc32 of the first prompt page's token ids — the same
        #: token-space key the paged engine keeps; NOT the router's
        #: char-window hash)
        self._warm_chains: list[list[int]] = []
        self._template_stats: dict[int, int] = {}
        # receipt config axes (obs/receipts.py), snapshotted at build
        # like the real engine's trace-time knobs: the kernel-dot knob is
        # meaningless to the mock's canned generation but rides the
        # fingerprint anyway so the router's fingerprint-skew drill
        # (flip REVAL_TPU_KERNEL_DOT on ONE replica) is host-only real
        from ..env import env_str

        self._receipt_ctx = {
            "engine": "mock", "response": self.response,
            "echo": self.echo, "tokens_per_step": self.tokens_per_step,
            "max_slots": self.max_slots,
            "dot_mode": env_str("REVAL_TPU_KERNEL_DOT", "swap") or "swap"}
        self._boot_aot()

    # -- warm restarts ------------------------------------------------------
    def _boot_aot(self) -> None:
        """Boot the two simulated programs through the REAL AOT cache
        (when ``REVAL_TPU_AOT_CACHE_DIR`` is set): a variant on disk is
        a hit (no "compile" paid); a cold/corrupt/mismatched one is
        counted+logged by the cache and "compiled" fresh (stored for
        the next boot).  ``fresh_compiles`` is the drill's "zero
        compilations of already-cached entries" observable."""
        from ..inference.tpu.aot_cache import (cache_from_env, fingerprint,
                                               runtime_context)

        self.fresh_compiles = 0
        self._aot_cache = cache_from_env(
            registry=lambda: self.stats.registry)
        if self._aot_cache is None:
            return
        fp = fingerprint(runtime_context(
            engine="mock", response=self.response,
            tokens_per_step=self.tokens_per_step,
            max_slots=self.max_slots))
        for entry in ("mock.prefill", "mock.decode_chunk"):
            sig = (entry, ("tokens_per_step", self.tokens_per_step))
            fn = self._aot_cache.load(entry, sig, fp,
                                      deserialize=self._mock_codec)
            if fn is None:
                # the mock's stand-in for trace+lower: pay the "compile"
                # and serialize it so the NEXT boot loads instead
                self.fresh_compiles += 1
                payload = json.dumps({"entry": entry}).encode()
                self._aot_cache.store(entry, sig, fp, payload,
                                      compile_s=0.5,
                                      signature_repr=repr(sig))

    @staticmethod
    def _mock_codec(payload: bytes):
        """The mock payload codec: a JSON blob → a callable naming its
        program.  Raises on garbage exactly like ``jax.export.
        deserialize`` would, so the cache's corrupt-entry degradation is
        exercisable host-only."""
        doc = json.loads(payload)
        if not isinstance(doc, dict) or "entry" not in doc:
            raise ValueError("not a mock AOT payload")
        return lambda: doc["entry"]

    def aot_counters(self) -> dict:
        """Same shape as :meth:`PagedTPUEngine.aot_counters`."""
        if self._aot_cache is None:
            return {"enabled": False}
        return {"enabled": True, "fresh_compiles": self.fresh_compiles,
                **self._aot_cache.counters()}

    def warm_state(self) -> dict:
        return {"prefix_chains": list(self._warm_chains),
                "template_stats": {str(k): v
                                   for k, v in self._template_stats.items()}}

    def rewarm(self, state: dict) -> int:
        """Replay a snapshot's chains: each re-registers as a warm
        prefix (and counts as prefilled tokens — the mock's analog of
        committing KV).  ``rewarm_s`` paces each chain so tests can
        observe the ``warming`` readiness window."""
        warmed = 0
        for chain in state.get("prefix_chains") or []:
            if not isinstance(chain, list) or not chain:
                continue
            if self.rewarm_s:
                time.sleep(self.rewarm_s)
            ids = [int(t) for t in chain]
            if ids not in self._warm_chains:
                self._warm_chains.append(ids)
            self.stats.prefill_tokens += len(ids)
            self.heartbeat = time.monotonic()
            warmed += 1
        from ..inference.tpu.engine import restore_template_stats

        restore_template_stats(self._template_stats,
                               state.get("template_stats"))
        return warmed

    # -- the session driver contract --------------------------------------
    def encode_clipped(self, prompt: str, max_new_tokens: int) -> list[int]:
        from ..inference.tpu.engine import clip_prompt_ids

        return clip_prompt_ids(self.tokenizer, prompt, max_new_tokens,
                               self.max_pages_per_seq * self.page_size)

    def request_keys(self, n: int) -> np.ndarray:
        return np.zeros((n, 2), np.uint32)

    def grammar_state(self, name: str):
        """Validate-only grammar support (the serve --mock smoke passes
        ``grammar=`` end-to-end): unknown names raise the same
        ``ValueError`` the paged engine raises — the server maps it to
        400 — and every valid name starts at the FREE state (the mock's
        canned response is not actually masked)."""
        from ..decoding import validate_grammar

        validate_grammar(name)
        return 0

    def spec_counters(self) -> dict:
        """Same shape as :meth:`PagedTPUEngine.spec_counters` (all-zero
        unless a grammar rode through — the mock never drafts)."""
        return self.stats.spec_counters()

    def receipt_context(self) -> dict:
        """Same contract as :meth:`PagedTPUEngine.receipt_context`: the
        config axes the reproducibility receipt fingerprints, stable per
        engine instance."""
        return dict(self._receipt_ctx)

    def submit_request(self, ids: list[int], max_new_tokens: int,
                       grammar: str | None = None):
        if grammar:
            self.grammar_state(grammar)     # ValueError on unknown names
            self.stats.grammar_requests += 1
        self._next_seq += 1
        self.live += 1
        self.stats.prefill_tokens += len(ids)
        # warm-state accounting (same token-space keys as the paged
        # engine): the first prompt page is both the template tag and
        # the "prefix chain" a snapshot carries across a restart
        from ..inference.tpu.engine import bump_template_stats

        tag = zlib.crc32(np.asarray(ids[:self.page_size],
                                    np.int32).tobytes())
        bump_template_stats(self._template_stats, tag)
        chain = [int(t) for t in ids[:self.page_size]]
        if len(ids) >= self.page_size and chain not in self._warm_chains \
                and len(self._warm_chains) < 64:
            self._warm_chains.append(chain)
        return self._next_seq, None

    def release_request(self, seq_id: int, req) -> None:
        self.live -= 1
        if req is not None:
            req.node = None

    def new_drive_state(self):
        return SimpleNamespace(active={}, dirty=True, pending=None)

    def _resp_ids_for(self, req) -> list[int]:
        """The response token stream for one request: the fixed canned
        string, or (``echo``) a deterministic crc32 tag over the prompt
        ids — any two replicas given the same prompt produce the same
        bytes, so cross-replica failover is output-checkable."""
        if not self.echo:
            return self._resp_ids
        tag = zlib.crc32("|".join(map(str, req.ids)).encode())
        text = f"{self.response}-echo-{tag:08x}"
        return [t for t in self.tokenizer.encode(text)
                if t != self.tokenizer.bos_id]

    def close(self) -> None:
        pass

    def _drive_tick(self, reqs: dict, st) -> None:   # hot-path
        """One mock decode step: every live request gains up to
        ``tokens_per_step`` tokens of the canned response, then EOS.
        Stamps the same lifecycle fields the paged engine keeps
        (admit/first/done) and observes the same step/latency
        histograms, so ``serve --mock`` exercises the whole
        observability path host-only."""
        t0 = time.perf_counter()
        self.heartbeat = time.monotonic()
        if self.step_s:
            # lint: allow(hotpath) — step_s is the mock's deliberate pacing
            # knob (deadline/drain tests need a controllable step interval)
            time.sleep(self.step_s)
        now = time.perf_counter()
        for seq_id, req in list(reqs.items()):
            if req.done:
                continue
            if req.t_admit is None:
                req.t_admit = now
            pos = len(req.generated)
            chunk = self._resp_ids_for(req)[pos:pos + self.tokens_per_step]
            if not chunk:
                chunk = [self.tokenizer.eos_id]
            chunk = chunk[:max(1, req.max_new - pos)]
            req.generated.extend(chunk)
            if req.t_first is None:
                req.t_first = time.perf_counter()
            self.stats.generated_tokens += len(chunk)
            if (len(req.generated) >= req.max_new
                    or self.tokenizer.eos_id in chunk
                    or req.scanner.hit_new(chunk)):
                req.done = True
                req.t_done = time.perf_counter()
                self.stats.observe_request(req)
                self.release_request(seq_id, req)
            if req.notify is not None:
                req.notify(req)
        dt = time.perf_counter() - t0
        self.stats.registry.histogram(obs_metrics.ENGINE_STEP).observe(dt)
        if self.flightrec.enabled:
            # in-flight steps field is 0: the mock mirrors the ragged
            # engine's one-dispatch-per-tick contract (every tick fetches
            # its own output; nothing is ever parked in flight), so the
            # step-cadence fields postmortems read stay meaningful
            self.flightrec.record(
                sum(1 for r in reqs.values() if not r.done), 0, 0, 0, 0, 0,
                0, 0, 0, dt,
                time.monotonic() - self.heartbeat, tuple(reqs))
