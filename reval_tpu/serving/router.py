"""Prefix-affinity fleet router: one HTTP tier in front of N EngineServers.

Scale-out past one box (ROADMAP item 3).  ``MultiSession`` already routes
around unready replicas *inside* one process; this module is the
standalone tier for a fleet of separately-launched ``reval_tpu serve``
processes — the vLLM/TGI serving comparison (PAPERS.md, arxiv
2511.17593) shows routing and overload policy, not raw kernels, dominate
tail behavior at that scale.

**Routing.**  REval's workload is millions of tiny probe requests whose
prompts share long per-dataset×template prefixes (50-72% of every
prompt's tokens are few-shot template — ``tools/prefix_stats.py``
measures it).  The router consistent-hashes the *affinity key* — a crc32
of the prompt's first ``window_chars`` characters, i.e. of its template
prefix — onto a ring of virtual nodes, so every request carrying one
template lands on the replica whose radix prefix cache is warm for it.
A ``prefix_stats.py --json`` affinity table seeds the window (the
shortest template length, so one window fits every task's template) and
names each template's key for ``/statusz`` placement inspection.

**Robustness is the headline.**

- Per-replica health: a poller drives ``GET /readyz`` per replica;
  passive accounting counts consecutive forward failures.  Either path
  ejects a replica (``eject_fails`` strikes); an ejected replica sits
  out ``cooldown_s`` and then admits ONE half-open probe (or a
  successful health poll) to rejoin.  One bad replica degrades
  capacity, never availability.
- Failover: a forward that dies in transport (connection refused/reset,
  timeout) or returns a retry-shaped status (429/500/502/503) moves to
  the next replica on the hash ring — bounded by the replica count, one
  forward per candidate.  Client-shaped responses (400/404/413/504)
  pass through verbatim: a bad request or a spent deadline is not the
  replica's fault.
- Fleet-wide admission: when every replica sheds (429), the router
  sheds with ``429`` + the largest replica ``Retry-After`` hint; when
  no replica is reachable at all it answers ``503``/``fleet_unavailable``
  + ``Retry-After`` — both through the typed
  :mod:`~reval_tpu.serving.errors` contract the client's
  :class:`~reval_tpu.resilience.RetryPolicy` already honors.
- Drain/rejoin: ``POST /admin/drain`` takes a replica out of rotation
  without touching its in-flight forwards (they complete; ``/statusz``
  shows the count draining to zero); ``POST /admin/rejoin`` restores it.
- Fingerprint-pinned placement: every replica's ``/readyz`` detail now
  carries its reproducibility-receipt config fingerprint
  (:mod:`~reval_tpu.obs.receipts`), so the health poller sees the
  fleet's config set for free.  When READY replicas disagree the router
  raises an edge-triggered ``router.fingerprint_skew`` event and bumps
  ``reval_receipt_skew_total`` — a half-upgraded fleet is an
  observability event, not a silent determinism hazard.  Tenants listed
  in ``pin_tenants`` (env ``REVAL_TPU_ROUTER_PIN_TENANTS``) are PINNED:
  the first fingerprint that serves such a tenant sticks, and every
  later forward skips replicas whose fingerprint diverges from the pin —
  shedding a typed 429 (``Overloaded``) when only divergent replicas
  remain, because for a reproducibility run a silently different config
  is worse than a retry.
- Runtime resize: ``POST /admin/add_replica`` / ``POST
  /admin/remove_replica`` change the MEMBERSHIP itself — the autoscaler's
  surface.  The hash ring is rebuilt and swapped atomically (consistent
  hashing keeps every surviving replica's keys in place); in-flight
  forwards hold their replica objects and complete regardless.  Every
  admin action (drain/rejoin/resize) lands in a bounded action log that
  ``/statusz`` exposes — the ``reval_tpu watch`` fleet view renders it
  as the live autoscaler story.

**Per-tenant QoS.**  Completion requests may carry a ``tenant`` field
(the serving schema validates it).  With a fleet concurrency ceiling
configured (``max_inflight`` / env ``REVAL_TPU_ROUTER_MAX_INFLIGHT``),
admission is WEIGHTED: each tenant owns a quota proportional to its
configured weight, spare capacity is borrowable, but the last
``headroom`` slots below the ceiling are reserved for tenants still
under quota — so a noisy tenant sheds (429, typed ``Overloaded``)
before it starves the others (:func:`weighted_admission` is the pure
math).  Per-tenant request/shed counters and a router-side e2e latency
histogram ride the registry as ``tenant=``-labeled series; completed
forwards also feed the goodput counters (completion within the
request's declared ``deadline_s``).

**Federation.**  ``GET /metrics`` scrapes every replica's exposition,
merges by the registry rule (counters and histogram buckets SUM, gauges
take last), folds in the router's own counters
(``reval_router_*``), and re-renders one parseable exposition — one
scrape sees the whole fleet.  ``GET /statusz`` is the JSON twin with
per-replica state (health, in-flight, last error, cached ``/readyz``
detail).  ``GET /readyz`` aggregates: the fleet is ready while ANY
replica is (the client handshake treats "some replicas ready" as ready).

Request ids pass through untouched in both directions (``X-Request-Id``
in, echoed out), so a client retry, a router failover, and the serving
replica's logs all name the same request.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
import urllib.error
import urllib.request
import zlib
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..env import env_float, env_int, env_str
from ..obs import metrics as obs_metrics
from ..obs.logging import log_event
from ..obs.metrics import MetricsRegistry, labeled, parse_prometheus
from ..resilience.retry import retry_after_from_headers
from .errors import FleetUnavailable, Overloaded, ServingError

__all__ = ["FleetRouter", "HashRing", "affinity_key", "federate_metrics",
           "load_affinity_table", "parse_tenant_weights", "sanitize_tenant",
           "weighted_admission"]

#: statuses a *different* replica may be able to serve: shed (429),
#: internal fault (500), bad gateway (502), draining/wedged (503).
#: 400/404/413 are the request's fault and 504 is the request's own
#: deadline — re-spending it elsewhere would only double the damage.
FAILOVER_STATUSES = frozenset({429, 500, 502, 503})

_RID_RE = re.compile(r"[^A-Za-z0-9._-]")


def _h32(text: str) -> int:
    return zlib.crc32(text.encode("utf-8", "replace")) & 0xFFFFFFFF


def affinity_key(prompt: str, window_chars: int) -> int:
    """The consistent-hash key for one prompt: crc32 of its first
    ``window_chars`` characters — the few-shot template prefix, which is
    what the replica-side radix prefix cache keys on.  Requests sharing
    a template share a key and therefore a (healthy) replica."""
    return _h32(prompt[:max(1, int(window_chars))])


class HashRing:
    """Consistent hashing with virtual nodes.  ``order(key)`` walks the
    ring clockwise from the key and returns every member once, nearest
    first — the failover candidate order.  Removing a member (ejection
    skips it at lookup time; membership itself is fixed) moves only the
    keys that hashed to it, which is the point: a replica loss must not
    reshuffle every other replica's warm prefix cache."""

    def __init__(self, members: list[str], vnodes: int = 64):
        self.members = list(members)
        self.vnodes = int(vnodes)
        points = []
        for m in self.members:
            for v in range(self.vnodes):
                points.append((_h32(f"{m}#{v}"), m))
        points.sort()
        self._points = points

    def order(self, key: int) -> list[str]:
        if not self._points:
            return []
        import bisect

        i = bisect.bisect_left(self._points, (key & 0xFFFFFFFF, ""))
        seen: set[str] = set()
        out: list[str] = []
        n = len(self._points)
        for j in range(n):
            member = self._points[(i + j) % n][1]
            if member not in seen:
                seen.add(member)
                out.append(member)
                if len(out) == len(self.members):
                    break
        return out


def load_affinity_table(source) -> dict:
    """Validate an affinity table (``tools/prefix_stats.py --json``) —
    a path or an already-parsed dict — and return it.  Raises
    ``ValueError`` on anything that is not a v1 table (a wrong file
    silently setting a 4-char window would scatter every template)."""
    table = source
    if isinstance(source, str):
        with open(source) as f:
            table = json.load(f)
    if (not isinstance(table, dict)
            or table.get("format") != "reval-affinity-v1"):
        raise ValueError(
            "affinity table must be the reval-affinity-v1 JSON that "
            "`tools/prefix_stats.py --json` emits")
    window = table.get("window_chars")
    if not isinstance(window, int) or window < 1:
        raise ValueError(f"affinity table window_chars must be a positive "
                         f"integer, got {window!r}")
    return table


# -- per-tenant QoS ----------------------------------------------------------

_TENANT_RE = re.compile(r"[^A-Za-z0-9._-]")

#: the tenant every request without (or with a garbage) ``tenant`` field
#: accounts under — one shared bucket, never a dropped sample
DEFAULT_TENANT = "default"

#: distinct wire-minted tenant identities the router will track (metric
#: label series are PERMANENT — a client minting a fresh tenant name per
#: request must not grow the registry or the /metrics body without
#: bound); configured-weight tenants always count, and everyone past the
#: cap folds into one shared bucket — which also pools their admission
#: quota, so minting tenants cannot dodge the weighted shed either
TENANT_LABEL_CAP = 32
OVERFLOW_TENANT = "other"


def sanitize_tenant(value) -> str:
    """The registry-safe tenant label for a wire ``tenant`` field: the
    allowed charset only, capped, :data:`DEFAULT_TENANT` when empty or
    not a string (wire values flow into metric label names and logs)."""
    if not isinstance(value, str):
        return DEFAULT_TENANT
    return _TENANT_RE.sub("", value)[:32] or DEFAULT_TENANT


def parse_tenant_weights(spec) -> dict[str, float]:
    """``"alpha:3,beta:1"``, a JSON-object string, or an already-parsed
    dict → ``{name: weight}``.  THE one parse of the tenant-weights
    surface (the router CLI and ``tools/loadgen.py`` both call it);
    every malformed shape — non-numeric, non-positive, or non-finite
    weight, empty name, empty spec — raises ``ValueError`` with a
    usage-shaped message, never a traceback mid-flag-parse."""
    if isinstance(spec, dict):
        items = list(spec.items())
    else:
        text = str(spec).strip()
        if text.startswith(("{", "[")):     # JSON-shaped: object or bust
            try:
                obj = json.loads(text)
            except json.JSONDecodeError as exc:
                raise ValueError(f"bad tenant-weights JSON: {exc}") from None
            if not isinstance(obj, dict):
                raise ValueError("tenant-weights JSON must be an object")
            items = list(obj.items())
        else:
            items = []
            for part in text.split(","):
                part = part.strip()
                if not part:
                    continue
                name, _, weight = part.partition(":")
                items.append((name.strip(), weight if weight else 1.0))
    out: dict[str, float] = {}
    for name, weight in items:
        if not str(name):
            raise ValueError("tenant-weights: empty tenant name")
        try:
            w = float(weight)
        except (TypeError, ValueError):
            raise ValueError(f"tenant-weights: weight for {name!r} must "
                             f"be a number, got {weight!r}") from None
        if not math.isfinite(w) or w <= 0:
            raise ValueError(f"tenant-weights: weight for {name!r} must "
                             f"be a finite number > 0, got {w!r}")
        out[str(name)] = w
    if not out:
        raise ValueError(f"tenant-weights: no tenants in {spec!r}")
    return out


def weighted_admission(tenant: str, inflight: dict, weights: dict,
                       max_inflight: int, headroom: int | None = None) -> str:
    """The weighted-admission verdict for ONE arriving request —
    ``"admit"``, ``"shed_tenant"`` (the tenant is past its weighted
    share while the fleet is under pressure), or ``"shed_fleet"`` (the
    concurrency ceiling itself is spent).  Pure math over a snapshot,
    so the policy is unit-testable without a fleet:

    - each tenant's quota is its weight share of ``max_inflight``
      (unlisted tenants weigh 1.0), floored at one slot;
    - spare capacity is borrowable — an over-quota tenant keeps
      admitting while the fleet has room — EXCEPT the last ``headroom``
      slots (default ``max(1, max_inflight // 8)``), which stay
      reserved for tenants still under quota.  That reserve is what
      makes a noisy tenant shed *before* it starves a quiet one.

    ``max_inflight <= 0`` disables the ceiling entirely."""
    if max_inflight <= 0:
        return "admit"
    if headroom is None:
        headroom = max(1, max_inflight // 8)
    total = sum(inflight.values())
    if total >= max_inflight:
        return "shed_fleet"
    total_weight = sum(weights.values()) if weights else 0.0
    weight = weights.get(tenant, 1.0)
    if tenant not in weights:
        total_weight += 1.0
    share = weight / total_weight if total_weight > 0 else 1.0
    quota = max(1, math.ceil(share * max_inflight))
    if inflight.get(tenant, 0) >= quota and total >= max_inflight - headroom:
        return "shed_tenant"
    return "admit"


class _Replica:
    """One routed endpoint and its health state machine:

    ``healthy`` → (``eject_fails`` consecutive failures) → ``ejected``
    → (``cooldown_s`` elapses; ONE half-open probe or a clean health
    poll succeeds) → ``healthy``.  ``draining`` is an operator state
    (admin drain/rejoin) orthogonal to health: no new forwards, the
    in-flight ones finish.

    All transitions go through the methods below; callers never touch
    the fields directly (the lock discipline the ``locks`` lint pass
    enforces).  Transition *events* are returned to the caller so the
    counting/logging happens outside the lock."""

    def __init__(self, rid: str, base_url: str, *, eject_fails: int,
                 cooldown_s: float, clock=time.monotonic):
        self.id = rid
        self.base_url = base_url.rstrip("/")
        self.eject_fails = int(eject_fails)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "healthy"      # guarded-by: _lock
        self.fails = 0              # guarded-by: _lock — consecutive FORWARD failures
        self.poll_fails = 0         # guarded-by: _lock — consecutive dead health polls
        self.inflight = 0           # guarded-by: _lock
        self.probing = False        # guarded-by: _lock
        self.ready = False          # guarded-by: _lock — poller's last verdict
        self.ready_detail: dict = {}    # guarded-by: _lock
        self.last_error: str | None = None  # guarded-by: _lock
        self.ejected_at = 0.0       # guarded-by: _lock

    # -- routing side ------------------------------------------------------
    def try_acquire(self) -> str | None:
        """May this replica take a forward right now?  Returns the grant
        kind — ``"normal"``, or ``"probe"`` when this forward IS the one
        admitted half-open probe of an ejected replica past its cooldown
        (pass it back to :meth:`release`) — or None for no.  Draining
        replicas take nothing."""
        with self._lock:
            if self.state == "draining":
                return None
            grant = "normal"
            if self.state == "ejected":
                if (self._clock() - self.ejected_at < self.cooldown_s
                        or self.probing):
                    return None
                self.probing = True
                grant = "probe"
            self.inflight += 1
            return grant

    def release(self, grant: str, outcome: str,
                error: str | None = None) -> tuple:
        """Record a forward's outcome: ``ok`` (served), ``busy`` (HTTP
        answered 429/503 — alive, just loaded), ``fail`` (transport
        death or 5xx fault).  ``grant`` is what :meth:`try_acquire`
        returned — only the probe forward may close the half-open gate
        (a pre-ejection forward finishing must not re-open it to a
        thundering herd of concurrent "probes").  Returns transition
        events (``"ejected"``/``"recovered"``) for the router to
        count."""
        events = []
        with self._lock:
            self.inflight -= 1
            if grant == "probe":
                self.probing = False
            if outcome in ("ok", "busy"):
                # an HTTP answer of any status is proof of life: reset
                # the strike counts; a half-open probe that got through
                # (even shedding) rejoins the rotation
                self.fails = 0
                self.poll_fails = 0
                self.last_error = None if outcome == "ok" else error
                if self.state == "ejected":
                    self.state = "healthy"
                    events.append("recovered")
            else:
                self.fails += 1
                self.last_error = error
                if self.state == "ejected":
                    self.ejected_at = self._clock()     # re-arm cooldown
                elif self.state == "healthy" and self.fails >= self.eject_fails:
                    self.state = "ejected"
                    self.ejected_at = self._clock()
                    events.append("ejected")
        return tuple(events)

    # -- health-poller side ------------------------------------------------
    def note_health(self, alive: bool, ready: bool,
                    detail: dict | None = None,
                    error: str | None = None) -> tuple:
        """Fold one ``/readyz`` poll result in.  ``alive`` means the
        replica answered HTTP at all (a 503-unready replica is alive).
        Poll strikes are counted SEPARATELY from forward strikes: a
        replica whose listener answers health checks while its forwards
        fail must still eject on the forward count — a clean poll only
        resets its own counter, never the forwards'."""
        events = []
        with self._lock:
            self.ready = bool(alive and ready)
            if detail is not None:
                self.ready_detail = detail
            if alive:
                self.poll_fails = 0
                if (self.state == "ejected" and not self.probing
                        and self._clock() - self.ejected_at >= self.cooldown_s):
                    self.state = "healthy"
                    self.fails = 0
                    events.append("recovered")
            else:
                self.last_error = error
                if self.state == "healthy":
                    self.poll_fails += 1
                    if self.poll_fails >= self.eject_fails:
                        self.state = "ejected"
                        self.ejected_at = self._clock()
                        events.append("ejected")
        return tuple(events)

    # -- operator side -----------------------------------------------------
    def set_draining(self, draining: bool) -> None:
        with self._lock:
            if draining:
                self.state = "draining"
            elif self.state == "draining":
                self.state = "healthy"
                self.fails = 0

    def is_ready(self) -> bool:
        with self._lock:
            return self.ready and self.state == "healthy"

    def fingerprint(self) -> str | None:
        """The replica's receipt config fingerprint, as its last
        ``/readyz`` poll reported it (None until a poll lands or when
        the replica's engine predates receipts)."""
        with self._lock:
            fp = self.ready_detail.get("fingerprint")
            return fp if isinstance(fp, str) else None

    def snapshot(self) -> dict:
        with self._lock:
            return {"id": self.id, "url": self.base_url,
                    "state": self.state, "ready": self.ready,
                    # a restarting replica replaying its warm-state
                    # snapshot: alive (the poll answers, no strikes
                    # accumulate), just not ready yet — the poller flips
                    # it ready the moment the rewarm finishes
                    "warming": bool(self.ready_detail.get("warming")),
                    "fails": self.fails, "poll_fails": self.poll_fails,
                    "inflight": self.inflight,
                    "last_error": self.last_error,
                    "readyz": self.ready_detail}


# -- metrics federation ------------------------------------------------------

_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (\w+)$")
_LABEL_RE = re.compile(r"\{.*\}$")


def _series_base(series: str, types: dict[str, str]) -> str:
    """The declaring metric of one sample series: strip labels, then the
    histogram suffix when the stripped prefix is a declared histogram."""
    name = _LABEL_RE.sub("", series)
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) == "histogram":
                return base
    return name


def federate_metrics(texts: list[str]) -> str:
    """Merge N Prometheus expositions into one, by the registry merge
    rule: counters and histogram series SUM across replicas, gauges take
    the LAST merged value.  Series order follows first appearance, so
    bucket lines stay in their (ascending, cumulative) order and the
    result re-parses with :func:`~reval_tpu.obs.metrics.parse_prometheus`.
    Unparseable inputs raise — a scrape must fail loudly, not merge
    garbage into the fleet view."""
    types: dict[str, str] = {}
    values: dict[str, float] = {}
    bases: dict[str, str] = {}
    order: list[str] = []
    for text in texts:
        local_types: dict[str, str] = {}
        for line in text.splitlines():
            m = _TYPE_RE.match(line)
            if m:
                local_types[m.group(1)] = m.group(2)
        for series, value in parse_prometheus(text).items():
            base = _series_base(series, local_types)
            mtype = local_types.get(base, "untyped")
            types.setdefault(base, mtype)
            if series not in values:
                order.append(series)
                values[series] = value
                bases[series] = base
            elif types[base] == "gauge":
                values[series] = value
            else:
                values[series] += value
    lines: list[str] = []
    emitted: set[str] = set()
    spec = obs_metrics.METRICS
    for series in order:
        base = bases[series]
        if base not in emitted:
            emitted.add(base)
            help_text = spec.get(base, {}).get("help", "")
            lines.append(f"# HELP {base} {help_text}")
            lines.append(f"# TYPE {base} {types[base]}")
        v = values[series]
        rendered = str(int(v)) if float(v).is_integer() else repr(float(v))
        lines.append(f"{series} {rendered}")
    return "\n".join(lines) + "\n"


# -- the router --------------------------------------------------------------

class FleetRouter:
    """HTTP front tier over ``replicas`` (``["host:port", ...]`` or bare
    ports).  ``start()`` serves on a daemon thread; ``shutdown()`` stops
    the poller and listener (replica servers are not this tier's to
    stop).

    Knobs (constructor args override the ``REVAL_TPU_ROUTER_*`` env
    defaults): ``vnodes`` per replica on the hash ring, ``eject_fails``
    consecutive failures before ejection, ``cooldown_s`` before a
    half-open probe, ``window_chars`` for the affinity key,
    ``health_interval_s`` between ``/readyz`` polls.
    ``affinity_table`` (path or dict from ``prefix_stats.py --json``)
    overrides ``window_chars`` and names the expected template keys."""

    def __init__(self, replicas: list, port: int = 0,
                 host: str = "127.0.0.1", *, model_id: str = "reval-fleet",
                 vnodes: int | None = None, eject_fails: int | None = None,
                 cooldown_s: float | None = None,
                 window_chars: int | None = None,
                 health_interval_s: float | None = None,
                 affinity_table=None, forward_timeout_s: float = 600.0,
                 max_body_bytes: int = 64 << 20, clock=time.monotonic,
                 tenant_weights: dict | None = None,
                 max_inflight: int | None = None,
                 pin_tenants=None):
        self.model_id = model_id
        vnodes = vnodes if vnodes is not None else \
            env_int("REVAL_TPU_ROUTER_VNODES", 64)
        eject_fails = eject_fails if eject_fails is not None else \
            env_int("REVAL_TPU_ROUTER_EJECT_FAILS", 3)
        cooldown_s = cooldown_s if cooldown_s is not None else \
            env_float("REVAL_TPU_ROUTER_COOLDOWN_S", 5.0)
        self.window_chars = window_chars if window_chars is not None else \
            env_int("REVAL_TPU_ROUTER_AFFINITY_WINDOW", 1024)
        self.health_interval_s = (
            health_interval_s if health_interval_s is not None
            else env_float("REVAL_TPU_ROUTER_HEALTH_INTERVAL_S", 1.0))
        self.forward_timeout_s = float(forward_timeout_s)
        self.max_body_bytes = int(max_body_bytes)
        self.affinity: dict = {}    # unguarded: built once here, read-only thereafter
        if affinity_table is not None:
            table = load_affinity_table(affinity_table)
            self.window_chars = int(table["window_chars"])
            self.affinity = table
        # -- per-tenant QoS ------------------------------------------------
        #: tenant -> weight for weighted admission (unlisted tenants
        #: weigh 1.0); unguarded: built once here, read-only thereafter
        self.tenant_weights = {sanitize_tenant(k): float(v)  # unguarded: built once here, read-only thereafter
                               for k, v in (tenant_weights or {}).items()}
        self.max_inflight = (max_inflight if max_inflight is not None
                             else env_int("REVAL_TPU_ROUTER_MAX_INFLIGHT", 0))
        # -- fingerprint-pinned placement ----------------------------------
        if pin_tenants is None:
            pin_tenants = [p.strip() for p in
                           env_str("REVAL_TPU_ROUTER_PIN_TENANTS", "").split(",")
                           if p.strip()]
        #: tenants that must only ever see ONE config fingerprint;
        #: unguarded: built once here, read-only thereafter
        self.pin_tenants = frozenset(sanitize_tenant(t) for t in pin_tenants)
        self._tenant_pins: dict = {}    # guarded-by: _adm_lock — tenant -> pinned fingerprint
        #: edge-trigger memory for the skew event (poll thread only)
        self._skewed = False
        self._adm_lock = threading.Lock()
        self._tenant_inflight: dict = {}    # guarded-by: _adm_lock
        #: tenant identities granted their own label series (weights
        #: pre-seed it; past TENANT_LABEL_CAP → OVERFLOW_TENANT)
        self._tenant_seen: set = set(self.tenant_weights)   # guarded-by: _adm_lock
        #: the last 64 admin actions (drain/rejoin/resize, with the
        #: caller's reason — the autoscaler names itself here), newest
        #: last; the `reval_tpu watch` fleet view renders the tail
        self._admin_log: deque = deque(maxlen=64)   # guarded-by: _adm_lock
        # membership knobs kept for runtime resize (admin add_replica)
        self._eject_fails = eject_fails
        self._cooldown_s = cooldown_s
        self._clock = clock
        self._vnodes = vnodes
        #: serialises membership changes; READERS never take it — they
        #: snapshot the _replicas/_ring references, which are replaced
        #: wholesale (never mutated in place) under this lock
        self._resize_lock = threading.Lock()
        # unguarded: reference swapped wholesale under _resize_lock;
        # readers snapshot the reference (per-replica mutable state lives
        # behind each _Replica's lock)
        self._replicas: dict[str, _Replica] = {}
        for rep in replicas:
            rid = str(rep) if ":" in str(rep) else f"127.0.0.1:{rep}"
            self._replicas[rid] = _Replica(
                rid, f"http://{rid}", eject_fails=eject_fails,
                cooldown_s=cooldown_s, clock=clock)
        # unguarded: reference swapped wholesale under _resize_lock
        self._ring = HashRing(list(self._replicas), vnodes=vnodes)
        #: router-level counters/gauges, merged into the federation
        self._obs = MetricsRegistry()
        self._poll_stop = threading.Event()
        self._poll_thread: threading.Thread | None = None
        self._thread: threading.Thread | None = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, payload: dict,
                      headers: dict | None = None,
                      request_id: str | None = None) -> None:
                self._send_bytes(code, json.dumps(payload).encode(),
                                 "application/json", headers, request_id)

            def _send_bytes(self, code: int, body: bytes, ctype: str,
                            headers: dict | None = None,
                            request_id: str | None = None) -> None:
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    if request_id is not None:
                        self.send_header("X-Request-Id", request_id)
                    for key, value in (headers or {}).items():
                        self.send_header(key, value)
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    pass        # client hung up; nothing shared is harmed

            def do_GET(self):
                path = self.path.rstrip("/")
                rid = (_RID_RE.sub("", self.headers.get("X-Request-Id", ""))
                       [:64] or None)
                if path in ("/healthz", "/v1/healthz"):
                    self._send(200, {"status": "ok", "router": True,
                                     "model": outer.model_id},
                               request_id=rid)
                elif path in ("/readyz", "/v1/readyz"):
                    body = outer.readiness()
                    self._send(200 if body["ready"] else 503, body,
                               None if body["ready"] else {"Retry-After": "1"},
                               request_id=rid)
                elif path in ("/metrics", "/v1/metrics"):
                    self._send_bytes(
                        200, outer.metrics_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                        request_id=rid)
                elif path in ("/statusz", "/v1/statusz"):
                    self._send(200, outer.statusz(), request_id=rid)
                elif path == "/v1/models":
                    self._proxy_models(rid)
                else:
                    self._send(404, {"error": {
                        "code": "not_found",
                        "message": f"unknown route {self.path}"}},
                        request_id=rid)

            def _proxy_models(self, rid) -> None:
                for rep in outer._candidates(0):
                    grant = rep.try_acquire()
                    if grant is None:
                        continue
                    try:
                        req = urllib.request.Request(rep.base_url + "/v1/models")
                        with urllib.request.urlopen(req, timeout=10) as resp:
                            body = resp.read()
                        # a successful models proxy can BE the half-open
                        # probe: count/log the recovery like any forward
                        outer._note(rep.release(grant, "ok"), rep)
                        self._send_bytes(200, body, "application/json",
                                         request_id=rid)
                        return
                    except Exception as exc:    # noqa: BLE001 — any dead
                        # replica just moves the proxy to the next one
                        outer._note(rep.release(grant, "fail", repr(exc)),
                                    rep)
                self._send(503, {"error": {
                    "code": FleetUnavailable.code,
                    "message": "no replica answered /v1/models"}},
                    {"Retry-After": "1"}, request_id=rid)

            def do_POST(self):
                rid = (_RID_RE.sub("", self.headers.get("X-Request-Id", ""))
                       [:64] or None)
                path = self.path.rstrip("/")
                if path == "/admin/drain":
                    self._admin(rid, draining=True)
                    return
                if path == "/admin/rejoin":
                    self._admin(rid, draining=False)
                    return
                if path in ("/admin/add_replica", "/admin/remove_replica"):
                    self._admin_resize(rid, add=path.endswith("add_replica"))
                    return
                if path != "/v1/completions":
                    self._send(404, {"error": {
                        "code": "not_found",
                        "message": f"unknown route {self.path}"}},
                        request_id=rid)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    length = -1
                if length < 0 or length > outer.max_body_bytes:
                    self._send(413 if length > 0 else 400, {"error": {
                        "code": "request_too_large" if length > 0
                                else "invalid_request",
                        "message": "bad or oversized request body"}},
                        request_id=rid)
                    return
                body = self.rfile.read(length)
                try:
                    outer._route_completion(self, body, rid)
                except ServingError as exc:
                    headers = None
                    if exc.retry_after is not None:
                        headers = {"Retry-After":
                                   str(int(math.ceil(exc.retry_after)))}
                    self._send(exc.status, {"error": {
                        "code": exc.code, "message": str(exc),
                        **({"request_id": rid} if rid else {})}},
                        headers, request_id=rid)

            def _admin_body(self) -> dict:
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(max(0, length)) or b"{}")
                    return req if isinstance(req, dict) else {}
                except Exception:
                    return {}

            def _admin(self, rid, *, draining: bool) -> None:
                req = self._admin_body()
                target = str(req.get("replica", ""))
                rep = outer._replicas.get(target)
                if rep is None:
                    self._send(404, {"error": {
                        "code": "not_found",
                        "message": f"no such replica {target!r}"}},
                        request_id=rid)
                    return
                rep.set_draining(draining)
                log_event("router.drain", replica=rep.id,
                          draining=draining)
                outer._admin_record("drain" if draining else "rejoin",
                                    rep.id, req.get("reason"))
                self._send(200, {"replica": rep.snapshot()}, request_id=rid)

            def _admin_resize(self, rid, *, add: bool) -> None:
                req = self._admin_body()
                target = str(req.get("replica", ""))
                reason = req.get("reason")
                try:
                    if add:
                        out = outer.add_replica(target, reason=reason)
                    else:
                        out = outer.remove_replica(target, reason=reason)
                except ValueError as exc:
                    self._send(400, {"error": {
                        "code": "invalid_request", "message": str(exc)}},
                        request_id=rid)
                    return
                self._send(200, out, request_id=rid)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]

    # -- candidate selection ----------------------------------------------
    def _candidates(self, key: int) -> list[_Replica]:
        """Replicas in failover order for one affinity key: the hash
        ring's clockwise walk, with READY replicas ahead of merely-alive
        ones (an unready replica would only shed or stall a request a
        ready sibling has room for)."""
        # snapshot both references; a resize may swap them between the
        # two reads, so a ring member absent from the dict is skipped
        # (next lookup sees the settled membership)
        replicas = self._replicas
        ordered = [rep for rep in (replicas.get(rid)
                                   for rid in self._ring.order(key))
                   if rep is not None]
        ready, rest = [], []
        for rep in ordered:
            # ONE is_ready() per replica: a readiness flip between two
            # passes must not land the same replica in both lists (the
            # loop would then forward to it twice for one request)
            (ready if rep.is_ready() else rest).append(rep)
        return ready + rest

    def _note(self, events: tuple, rep: _Replica) -> None:
        """Count + log replica state transitions (outside replica locks)."""
        for event in events:
            if event == "ejected":
                self._obs.counter(obs_metrics.ROUTER_EJECTIONS).add(1)
                log_event("router.eject", level="warning", replica=rep.id,
                          error=rep.snapshot()["last_error"])
            elif event == "recovered":
                self._obs.counter(obs_metrics.ROUTER_RECOVERIES).add(1)
                log_event("router.recover", replica=rep.id)
        if events:
            self._set_ready_gauge()

    def _set_ready_gauge(self) -> None:
        self._obs.gauge(obs_metrics.ROUTER_REPLICAS_READY).set(
            sum(1 for r in self._replicas.values() if r.is_ready()))

    # -- runtime membership (the autoscaler's surface) ----------------------
    def _admin_record(self, action: str, replica: str,
                      reason=None) -> None:
        """Append one admin action to the bounded log ``/statusz``
        exposes (the `watch` fleet view's autoscaler story)."""
        with self._adm_lock:
            self._admin_log.append(
                {"ts": round(time.time(), 3), "action": action,
                 "replica": replica,
                 "reason": str(reason) if reason is not None else None})

    def add_replica(self, endpoint: str, *, reason=None) -> dict:
        """Join ``endpoint`` (``host:port`` or a bare port) to the ring
        at runtime.  The membership dict and ring are REBUILT and the
        references swapped (readers snapshot them; in-flight forwards
        hold their replica objects either way) — consistent hashing
        keeps every existing replica's keys in place.  Raises
        ``ValueError`` on a malformed or duplicate endpoint."""
        rid = str(endpoint).strip()
        if not rid:
            raise ValueError("add_replica needs a replica endpoint")
        if ":" not in rid:
            rid = f"127.0.0.1:{rid}"
        with self._resize_lock:
            if rid in self._replicas:
                raise ValueError(f"replica {rid!r} is already a member")
            replicas = dict(self._replicas)
            replicas[rid] = _Replica(
                rid, f"http://{rid}", eject_fails=self._eject_fails,
                cooldown_s=self._cooldown_s, clock=self._clock)
            # dict first, ring second: a reader holding the NEW ring must
            # always find every member in the dict it reads next
            self._replicas = replicas
            self._ring = HashRing(list(replicas), vnodes=self._vnodes)
            members = list(replicas)
        log_event("router.resize", action="add", replica=rid,
                  reason=reason, members=len(members))
        self._admin_record("add_replica", rid, reason)
        self._set_ready_gauge()
        return {"added": rid, "members": members}

    def remove_replica(self, endpoint: str, *, reason=None) -> dict:
        """Remove ``endpoint`` from the ring at runtime.  In-flight
        forwards to it complete (they hold the replica object); it just
        stops being a candidate.  Refuses to remove the LAST member
        (an empty ring routes nothing — drain the fleet instead) and
        unknown endpoints, both ``ValueError``."""
        rid = str(endpoint).strip()
        if ":" not in rid and rid:
            rid = f"127.0.0.1:{rid}"
        with self._resize_lock:
            if rid not in self._replicas:
                raise ValueError(f"no such replica {rid!r}")
            if len(self._replicas) == 1:
                raise ValueError(
                    "refusing to remove the last replica (an empty ring "
                    "cannot route; drain it instead)")
            replicas = {k: v for k, v in self._replicas.items() if k != rid}
            # ring first, dict second: a reader holding the OLD dict may
            # still serve the removed member this instant (harmless); a
            # reader holding the new ring never names it
            self._ring = HashRing(list(replicas), vnodes=self._vnodes)
            self._replicas = replicas
            members = list(replicas)
        log_event("router.resize", action="remove", replica=rid,
                  reason=reason, members=len(members))
        self._admin_record("remove_replica", rid, reason)
        self._set_ready_gauge()
        return {"removed": rid, "members": members}

    # -- the forward path ---------------------------------------------------
    def _route_completion(self, handler, body: bytes, rid: str | None) -> None:
        self._obs.counter(obs_metrics.ROUTER_REQUESTS).add(1)
        try:
            req = json.loads(body or b"{}")
        except Exception:
            req = {}
        if not isinstance(req, dict):
            req = {}
        tenant = sanitize_tenant(req.get("tenant"))
        with self._adm_lock:
            # cardinality bound: a fresh identity past the cap folds
            # into the shared overflow bucket for BOTH accounting and
            # admission (pooling its quota with every other late-comer)
            if (tenant in self._tenant_seen
                    or len(self._tenant_seen) < TENANT_LABEL_CAP):
                self._tenant_seen.add(tenant)
            else:
                tenant = OVERFLOW_TENANT
            verdict = weighted_admission(
                tenant, self._tenant_inflight, self.tenant_weights,
                self.max_inflight)
            if verdict == "admit":
                self._tenant_inflight[tenant] = \
                    self._tenant_inflight.get(tenant, 0) + 1
        self._obs.counter(labeled(obs_metrics.TENANT_REQUESTS,
                                  tenant=tenant)).add(1)
        if verdict != "admit":
            self._count_shed(tenant)
            log_event("router.shed", level="warning", request_id=rid,
                      attempted=0, tenant=tenant,
                      reason=f"weighted admission: {verdict}")
            if verdict == "shed_tenant":
                raise Overloaded(
                    f"tenant {tenant!r} is over its weighted share of "
                    f"the fleet's {self.max_inflight} in-flight slots")
            raise Overloaded(
                f"fleet concurrency ceiling of {self.max_inflight} "
                f"in-flight forwards reached")
        try:
            self._forward_completion(handler, body, rid, req, tenant)
        finally:
            with self._adm_lock:
                n = self._tenant_inflight.get(tenant, 1) - 1
                if n > 0:
                    self._tenant_inflight[tenant] = n
                else:
                    self._tenant_inflight.pop(tenant, None)

    def _count_shed(self, tenant: str) -> None:
        self._obs.counter(obs_metrics.ROUTER_SHEDS).add(1)
        self._obs.counter(labeled(obs_metrics.TENANT_SHEDS,
                                  tenant=tenant)).add(1)

    def _count_completed(self, tenant: str, elapsed_s: float,
                         deadline_s) -> None:
        """Goodput accounting for one DELIVERED forward: within the
        request's declared deadline (or no deadline at all) is goodput;
        a late delivery is an SLO miss.  Sheds never reach here."""
        self._obs.histogram(labeled(obs_metrics.TENANT_E2E,
                                    tenant=tenant)).observe(elapsed_s)
        if (isinstance(deadline_s, (int, float)) and deadline_s > 0
                and elapsed_s > float(deadline_s)):
            self._obs.counter(obs_metrics.ROUTER_SLO_MISS).add(1)
        else:
            self._obs.counter(obs_metrics.ROUTER_GOODPUT).add(1)

    def _forward_completion(self, handler, body: bytes, rid: str | None,
                            req: dict, tenant: str) -> None:
        t0 = time.perf_counter()
        prompts = req.get("prompt", "")
        first = prompts if isinstance(prompts, str) else \
            (prompts[0] if isinstance(prompts, list) and prompts
             and isinstance(prompts[0], str) else "")
        key = affinity_key(first, self.window_chars)
        stream = bool(req.get("stream"))
        deadline_s = req.get("deadline_s")
        timeout = (min(float(deadline_s) + 30.0, self.forward_timeout_s)
                   if isinstance(deadline_s, (int, float)) and deadline_s > 0
                   else self.forward_timeout_s)
        ring_order = self._ring.order(key)
        primary = ring_order[0] if ring_order else None
        attempted = 0
        all_busy = True
        retry_hint = 0.0
        last_error = "no eligible replica (ejected/draining/cooldown)"
        pinned = tenant in self.pin_tenants
        pin_fp = None
        pin_skipped = 0
        if pinned:
            with self._adm_lock:
                pin_fp = self._tenant_pins.get(tenant)
        for rep in self._candidates(key):
            if pinned:
                fp = rep.fingerprint()
                if pin_fp is None and fp is not None:
                    # first fingerprinted replica this tenant would land
                    # on establishes the pin (setdefault: a concurrent
                    # request may have pinned first — its pin wins)
                    with self._adm_lock:
                        pin_fp = self._tenant_pins.setdefault(tenant, fp)
                if pin_fp is not None and fp != pin_fp:
                    # divergent config: for a pinned tenant this replica
                    # does not exist.  A shed is honest; a silently
                    # different kernel/dtype/spec config is not.
                    pin_skipped += 1
                    last_error = (f"replica {rep.id} fingerprint "
                                  f"{fp!r} diverges from tenant pin")
                    continue
            grant = rep.try_acquire()
            if grant is None:
                continue
            attempted += 1
            if rep.id == primary and attempted == 1:
                self._obs.counter(obs_metrics.ROUTER_ROUTED).add(1)
            else:
                self._obs.counter(obs_metrics.ROUTER_FAILOVERS).add(1)
                log_event("router.failover", request_id=rid,
                          replica=rep.id, attempt=attempted,
                          reason=last_error)
            headers = {"Content-Type": "application/json"}
            if rid:
                headers["X-Request-Id"] = rid
            fwd = urllib.request.Request(
                rep.base_url + "/v1/completions", data=body,
                headers=headers, method="POST")
            try:
                resp = urllib.request.urlopen(fwd, timeout=timeout)
            except urllib.error.HTTPError as exc:
                err_body = exc.read()
                hint = retry_after_from_headers(exc.headers)
                if exc.code in FAILOVER_STATUSES:
                    busy = exc.code in (429, 503)
                    outcome = "busy" if busy else "fail"
                    all_busy = all_busy and busy
                    retry_hint = max(retry_hint, hint or 0.0)
                    last_error = f"HTTP {exc.code} from {rep.id}"
                    self._note(rep.release(grant, outcome, last_error), rep)
                    continue
                # client-shaped response (400/404/413/504): the verdict
                # stands wherever it runs — pass it through verbatim
                self._note(rep.release(grant, "ok"), rep)
                if exc.code == 504:
                    # the replica spent the request's own deadline: an
                    # SLO miss, not a shed (the request WAS attempted)
                    self._obs.counter(obs_metrics.ROUTER_SLO_MISS).add(1)
                pass_headers = {}
                if hint is not None:
                    pass_headers["Retry-After"] = str(int(math.ceil(hint)))
                handler._send_bytes(
                    exc.code, err_body, "application/json", pass_headers,
                    request_id=rid or exc.headers.get("X-Request-Id"))
                return
            except Exception as exc:    # noqa: BLE001 — transport death
                # (refused/reset/timeout) is exactly what failover is for
                all_busy = False
                last_error = repr(exc)
                self._note(rep.release(grant, "fail", last_error), rep)
                continue
            try:
                if stream:
                    upstream_err = self._pipe_stream(handler, resp, rid)
                else:
                    out = resp.read()
                    # the replica mints an id when the caller sent none:
                    # surface it so the one-request-one-id contract holds
                    # through the extra hop
                    handler._send_bytes(
                        resp.status, out, "application/json",
                        request_id=rid or resp.headers.get("X-Request-Id"))
                    upstream_err = None
            except Exception as exc:    # noqa: BLE001 — the replica died
                # between accepting the forward and delivering the body
                # (reset mid-read, pre-headers stream death): NOTHING has
                # reached the client yet, so the next candidate may serve
                resp.close()
                all_busy = False
                last_error = repr(exc)
                self._note(rep.release(grant, "fail", last_error), rep)
                continue
            resp.close()
            if upstream_err is not None:
                # bytes already reached the client (no retransmit), but
                # the truncation is the REPLICA's strike — a replica that
                # keeps resetting mid-stream must accumulate toward
                # ejection, not read as healthy
                self._note(rep.release(grant, "fail", upstream_err), rep)
            else:
                self._note(rep.release(grant, "ok"), rep)
                self._count_completed(tenant, time.perf_counter() - t0,
                                      deadline_s)
            return
        # every candidate was unavailable, saturated, or failed
        self._count_shed(tenant)
        log_event("router.shed", level="warning", request_id=rid,
                  attempted=attempted, reason=last_error)
        if pin_skipped:
            # at least one willing replica was withheld strictly by the
            # fingerprint pin (dead/saturated candidates may also have
            # been tried — the pin story still names WHY this request
            # could not be served honestly): the typed-429 contract
            # (retryable) — the client's RetryPolicy re-sends once the
            # fleet converges
            raise Overloaded(
                f"tenant {tenant!r} is pinned to config fingerprint "
                f"{str(pin_fp)[:16]} and {pin_skipped} replica(s) with a "
                f"divergent fingerprint were withheld",
                retry_after=max(1.0, retry_hint))
        if attempted and all_busy:
            raise Overloaded(
                f"all {len(self._replicas)} replicas are saturated",
                retry_after=max(1.0, retry_hint))
        raise FleetUnavailable(
            f"no replica could take the request "
            f"({attempted} forwards failed; last: {last_error})")

    @staticmethod
    def _pipe_stream(handler, resp, rid: str | None) -> str | None:
        """Byte-transparent SSE proxy.  Returns None when the stream
        completed (or the CLIENT hung up — not the replica's fault), or
        an error string when the UPSTREAM died mid-stream: the client
        got a truncated 200 (append-only SSE cannot retract), and the
        caller records the strike against the replica.  An upstream
        death BEFORE the first byte raises instead, so the caller can
        still fail over — nothing has touched the client socket yet."""
        def read_chunk() -> bytes:
            return (resp.read1(65536) if hasattr(resp, "read1")
                    else resp.read(65536))

        chunk = read_chunk()    # pre-headers: a death here propagates
        try:
            handler.send_response(resp.status)
            handler.send_header("Content-Type",
                                resp.headers.get("Content-Type",
                                                 "text/event-stream"))
            handler.send_header("Cache-Control", "no-cache")
            rid_out = rid or resp.headers.get("X-Request-Id")
            if rid_out:
                handler.send_header("X-Request-Id", rid_out)
            handler.end_headers()
        except OSError:
            return None         # client gone before headers; replica fine
        while chunk:
            try:
                handler.wfile.write(chunk)
                handler.wfile.flush()
            except OSError:
                return None     # client hung up: stream done, replica fine
            try:
                chunk = read_chunk()
            except Exception as exc:    # noqa: BLE001 — the replica reset
                # under an in-flight stream
                return f"upstream died mid-stream: {exc!r}"
        return None

    # -- health poller ------------------------------------------------------
    def _each_replica(self, fn, join_timeout_s: float = 10.0) -> None:
        """Run ``fn(replica)`` for every replica CONCURRENTLY (one
        short-lived thread each — replica counts are small) so one hung
        socket cannot stretch every sibling's health cadence or stall a
        fleet scrape behind serial 5 s timeouts."""
        threads = [threading.Thread(target=fn, args=(rep,), daemon=True)
                   for rep in self._replicas.values()]
        for t in threads:
            t.start()
        deadline = time.monotonic() + join_timeout_s
        for t in threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))

    def _poll_one(self, rep: _Replica) -> None:
        try:
            with urllib.request.urlopen(rep.base_url + "/readyz",
                                        timeout=5) as resp:
                detail = json.loads(resp.read())
            events = rep.note_health(True, True, detail)
        except urllib.error.HTTPError as exc:
            try:
                detail = json.loads(exc.read())
            except Exception:
                detail = {}
            events = rep.note_health(True, False, detail,
                                     f"HTTP {exc.code}")
        except Exception as exc:    # noqa: BLE001 — a dead poll is
            # exactly the health signal being collected
            events = rep.note_health(False, False, None, repr(exc))
        self._note(events, rep)

    def _poll(self) -> None:
        while not self._poll_stop.wait(self.health_interval_s):
            self._each_replica(self._poll_one)
            self._set_ready_gauge()
            self._check_fingerprint_skew()

    # -- receipt fingerprints -----------------------------------------------
    def fleet_fingerprints(self) -> dict[str, list[str]]:
        """``{fingerprint: [replica ids]}`` across READY replicas, as
        the health poller last saw them.  One key = a converged fleet;
        more = a half-upgraded (or mis-flagged) one."""
        fps: dict[str, list[str]] = {}
        for rep in self._replicas.values():
            fp = rep.fingerprint()
            if fp is not None and rep.is_ready():
                fps.setdefault(fp, []).append(rep.id)
        return fps

    def _check_fingerprint_skew(self) -> None:
        """Edge-triggered skew alarm: the poll cadence calls this every
        round, but the event/counter fire once per healthy→skewed
        transition (a skewed fleet polled at 1 Hz must not melt the
        event log)."""
        fps = self.fleet_fingerprints()
        skewed = len(fps) > 1
        if skewed and not self._skewed:
            self._obs.counter(obs_metrics.RECEIPT_SKEW).add(1)
            log_event("router.fingerprint_skew", level="warning",
                      fingerprints={fp: ids for fp, ids in fps.items()})
        self._skewed = skewed

    # -- introspection ------------------------------------------------------
    def readiness(self) -> dict:
        """The aggregate ``/readyz`` body: ready while ANY replica is —
        degraded capacity still serves (the client handshake treats
        "some replicas ready" as ready)."""
        reps = [r.snapshot() for r in self._replicas.values()]
        ready_n = sum(1 for r in reps if r["ready"] and r["state"] == "healthy")
        fps = sorted(self.fleet_fingerprints())
        return {"status": "ready" if ready_n else "unready",
                "ready": ready_n > 0, "router": True,
                "replicas_ready": ready_n, "replicas_total": len(reps),
                # the fleet-wide receipt story in one field: a single
                # fingerprint when converged, the full divergent set
                # otherwise (watch renders this row)
                "fingerprint": fps[0] if len(fps) == 1 else None,
                "fingerprints": fps,
                "replicas": reps}

    def statusz(self) -> dict:
        with self._adm_lock:
            admin_log = list(self._admin_log)
            tenant_inflight = dict(self._tenant_inflight)
            tenant_pins = dict(self._tenant_pins)
        out = {"router": True, "model": self.model_id,
               "window_chars": self.window_chars,
               "ring": {"members": self._ring.members,
                        "vnodes": self._ring.vnodes},
               "replicas": [r.snapshot() for r in self._replicas.values()],
               "admin_log": admin_log,
               "tenants": {"weights": self.tenant_weights,
                           "max_inflight": self.max_inflight,
                           "inflight": tenant_inflight,
                           "pinned": sorted(self.pin_tenants),
                           "pins": tenant_pins},
               "fingerprints": self.fleet_fingerprints(),
               "metrics": self._obs.snapshot()}
        if self.affinity:
            placement = {}
            for task, row in (self.affinity.get("tasks") or {}).items():
                try:
                    key = int(str(row.get("key")), 16)
                except (TypeError, ValueError):
                    continue
                order = self._ring.order(key)
                placement[task] = {"key": row.get("key"),
                                   "replica": order[0] if order else None}
            out["affinity"] = {"window_chars": self.window_chars,
                               "placement": placement}
        return out

    def metrics_text(self) -> str:
        """The federated exposition: every reachable replica's scrape +
        the router's own counters, merged by the registry rule.  A
        replica that cannot be scraped — or whose text does not PARSE
        (a proxy error page, a foreign exposition dialect) — contributes
        nothing this round (its last state is visible in ``/statusz``);
        replicas are scraped concurrently so one hung socket cannot
        stall the whole fleet view."""
        texts = [self._obs.render_prometheus()]
        texts_lock = threading.Lock()

        def scrape(rep: _Replica) -> None:
            try:
                with urllib.request.urlopen(rep.base_url + "/metrics",
                                            timeout=5) as resp:
                    text = resp.read().decode()
                parse_prometheus(text)      # reject garbage BEFORE merge
            except Exception:   # noqa: BLE001 — an unscrapeable replica
                return          # must not take the fleet view down
            with texts_lock:
                texts.append(text)

        self._each_replica(scrape)
        return federate_metrics(texts)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="fleet-router")
            self._thread.start()
        if self._poll_thread is None:
            self._poll_thread = threading.Thread(
                target=self._poll, daemon=True, name="fleet-router-poller")
            self._poll_thread.start()
        return self

    def serve_forever(self) -> None:
        self.start()
        try:
            while self._thread is not None and self._thread.is_alive():
                self._thread.join(timeout=1.0)
        except KeyboardInterrupt:
            pass

    def shutdown(self) -> None:
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10)
            self._poll_thread = None
        if self._thread is not None:
            # only a RUNNING serve loop can acknowledge shutdown();
            # calling it on a never-started server would block forever
            self._httpd.shutdown()
            self._thread.join(timeout=10)
            self._thread = None
        self._httpd.server_close()
