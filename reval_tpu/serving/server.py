"""OpenAI-compatible completions server over the resident TPU engine.

Protocol surface (exactly what the client backend + reference harness use;
reference inference.py:110-131, start_server.sh):

- ``GET /v1/models``           → ``{"data": [{"id": <model_id>}]}``
- ``GET /healthz``             → pure LIVENESS: the process answers.
- ``GET /readyz``              → READINESS: engine loaded, driver alive,
  heartbeat fresh, queue below the admission watermark, not draining —
  503 with per-condition detail otherwise (per-replica for a dp set).
  The client handshake polls this one.
- ``GET /metrics``             → Prometheus text exposition (0.0.4) of
  every engine/session registry merged with the server's own counters —
  TTFT/TPOT/e2e/queue-wait histograms, engine counters, gauges.  No
  prometheus_client dependency; the renderer is obs/metrics.py.
- ``GET /statusz``             → the JSON twin: the same merged metrics
  as a snapshot dict plus model id and readiness detail.
- ``GET /debugz``              → the live postmortem bundle (flight
  records, in-flight request table, span tail, recent structured-log
  events) — what a crash dump would contain right now, without writing
  one.  ``reval_tpu watch`` polls this plus ``/statusz``.

Request ids: every request gets one — the client's ``X-Request-Id``
header when sent (sanitised), a minted one otherwise — and EVERY
response echoes it back as ``X-Request-Id`` (success included), so
client-side retry logs and server logs name the same request.  Error
bodies and SSE error events carry it too.
- ``POST /v1/completions``     → prompt (string or list), ``max_tokens``,
  ``temperature``, ``stop``, optional ``deadline_s`` (the client's
  remaining budget — the server cancels the request engine-side when it
  expires), optional ``tenant`` (accounting identity: the fleet
  router's weighted admission and per-tenant counters key on it)
  → ``{"choices": [{"index", "text"}]}``;
  with ``"stream": true`` → Server-Sent Events, one
  ``data: {"choices": [{"index", "text": <delta>}]}`` event per decode
  chunk and a final ``data: [DONE]`` — the protocol the reference's
  clients speak to vLLM's server (reference inference.py:115-131 sets
  ``stream=True`` and accumulates deltas).

Overload & lifecycle semantics (serving/session.py carries the state):

- admission control full → ``429`` + ``Retry-After`` (code ``overloaded``)
- graceful drain in progress → ``503`` (code ``draining``)
- watchdog tripped → ``503`` (code ``engine_wedged``)
- request deadline expired → ``504`` (code ``deadline_exceeded``)
- anything unexpected → ``500`` with a stable code + request id ONLY;
  the stack trace goes to the server log, never the wire.

Implementation notes:
- stdlib ``ThreadingHTTPServer``; each request handles its own socket but
  engine calls are serialised with a lock — the engine owns device state
  (KV cache, scheduler) and is single-owner by design.  Batching comes
  from *list prompts in one request* (the client backend sends whole
  task batches), which the engine schedules together; concurrent separate
  requests queue on the lock.
- streaming rides the engine's ``on_progress`` hook (decode-chunk
  granularity, ~32 tokens).  BPE detokenisation is not strictly
  prefix-stable at chunk edges, so a delta is emitted only when the new
  text extends what was already sent; a non-extending revision is held
  back until it stabilises (the common case is plain extension).
- ``shutdown()`` is a graceful drain: stop admitting (new POSTs get 503),
  let in-flight requests finish, join SSE workers, close the session,
  THEN tear the listener down — and it is idempotent.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
import traceback
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs import metrics as obs_metrics
from ..obs.flightrec import PostmortemWriter, build_bundle
from ..obs.logging import log_event
from ..obs.metrics import MetricsRegistry
from .errors import ServingError

__all__ = ["EngineServer", "serve_config"]

MAX_BODY_BYTES = 64 << 20   # request-body cap: a garbage multi-GB POST
                            # must die at the socket, not in the tokenizer.
                            # 64 MB clears the fleet's fused mega-batch
                            # (every task's prompts in ONE request) with
                            # room; config key ``max_body_bytes`` tunes it


def _hold_stop_prefix(text: str, stop: list[str]) -> str:
    """Trim a trailing substring that is a proper prefix of any stop
    string — it might complete into the stop next chunk, in which case
    the final text would retract it (append-only streams cannot)."""
    if not stop:
        return text
    max_hold = max(len(s) for s in stop) - 1
    for k in range(min(max_hold, len(text)), 0, -1):
        tail = text[-k:]
        if any(s.startswith(tail) for s in stop):
            return text[:-k]
    return text


def _err(code: str, message: str, request_id: str | None = None) -> dict:
    body = {"code": code, "message": message}
    if request_id is not None:
        body["request_id"] = request_id
    return {"error": body}


def _wire_payload(exc: ServingError, rid: str) -> dict:
    """Error body for a typed serving failure: the authored message
    verbatim — or, for wire-UNSAFE taxonomy members (EngineFailure
    carries whatever the engine raised), a sanitized stand-in with the
    real text logged server-side.  Every typed-error response (plain and
    SSE) goes through here, so the no-internals-on-the-wire invariant
    has exactly one enforcement point."""
    if exc.wire_safe:
        return _err(exc.code, str(exc), rid)
    log_event("server.request_error", level="error", request_id=rid,
              exc=exc, where="serving")
    return _err(exc.code, "internal error (see server log)", rid)


_RID_RE = re.compile(r"[^A-Za-z0-9._-]")


def _request_id(headers) -> str:
    """The caller's ``X-Request-Id``, sanitised (header values flow into
    logs and response headers — strip anything that could smuggle a
    newline or control byte, cap the length), or a fresh mint."""
    rid = headers.get("X-Request-Id", "") if headers is not None else ""
    rid = _RID_RE.sub("", rid)[:64]
    return rid or uuid.uuid4().hex[:12]


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def _validate_request(req: dict, max_tokens_cap: int | None) -> dict:
    """Parse + validate one completions request body.

    Raises ``ValueError`` with a CLIENT-safe message (everything here is
    authored text, never engine internals).  Garbage numerics — NaN
    temperature, negative/zero ``top_p``, absurd ``max_tokens`` — are a
    400, not a wedged or OOMed engine; ``max_tokens`` is clamped to the
    engine's sequence budget."""
    prompts = req.get("prompt", "")
    single = isinstance(prompts, str)
    if single:
        prompts = [prompts]
    if (not isinstance(prompts, list)
            or not all(isinstance(p, str) for p in prompts)):
        raise ValueError("'prompt' must be a string or a list of strings")
    stop = req.get("stop") or []
    if isinstance(stop, str):
        stop = [stop]
    if (not isinstance(stop, list)
            or not all(isinstance(s, str) for s in stop)):
        raise ValueError("'stop' must be a string or a list of strings")
    max_tokens = req.get("max_tokens", 256)
    if not _finite(max_tokens) or int(max_tokens) < 1:
        raise ValueError(f"'max_tokens' must be a positive integer, "
                         f"got {max_tokens!r}")
    max_tokens = int(max_tokens)
    if max_tokens_cap is not None:
        # clamp, don't reject: the OpenAI protocol treats max_tokens as a
        # budget, and the engine's own clipping keeps prompt+generation
        # inside max_seq_len
        max_tokens = min(max_tokens, max_tokens_cap)
    temperature = req.get("temperature", 0.0)
    if not _finite(temperature) or temperature < 0:
        raise ValueError(f"'temperature' must be a finite number >= 0, "
                         f"got {temperature!r}")
    top_k = req.get("top_k", 0)
    if not _finite(top_k) or int(top_k) < 0:
        raise ValueError(f"'top_k' must be a non-negative integer, "
                         f"got {top_k!r}")
    top_p = req.get("top_p", 1.0)
    if not _finite(top_p) or not 0.0 < float(top_p) <= 1.0:
        raise ValueError(f"'top_p' must be a finite number in (0, 1], "
                         f"got {top_p!r}")
    deadline_s = req.get("deadline_s")
    if deadline_s is not None and (not _finite(deadline_s) or deadline_s <= 0):
        raise ValueError(f"'deadline_s' must be a finite number > 0, "
                         f"got {deadline_s!r}")
    tenant = req.get("tenant")
    if tenant is not None and not isinstance(tenant, str):
        # accounting identity only (the router's weighted admission and
        # per-tenant counters key on it); the engine never sees it
        raise ValueError(f"'tenant' must be a string, got {tenant!r}")
    grammar = req.get("grammar")
    if grammar is not None:
        if not isinstance(grammar, str) or not grammar:
            raise ValueError(f"'grammar' must be a non-empty string "
                             f"(an answer-shape name), got {grammar!r}")
        from ..decoding import validate_grammar

        # an unknown shape name is the request's fault: 400 here, never
        # a driver-side fault after admission
        validate_grammar(grammar)
    return {"prompts": prompts, "single": single, "stop": stop,
            "max_tokens": max_tokens, "temperature": float(temperature),
            "top_k": int(top_k), "top_p": float(top_p),
            "stream": bool(req.get("stream", False)),
            "grammar": grammar, "tenant": tenant,
            "deadline_s": float(deadline_s) if deadline_s is not None else None}


class EngineServer:
    """Serve ``generate_fn(prompts, max_tokens, temperature, stop) ->
    list[str]`` over the OpenAI completions protocol.  A ``generate_fn``
    that also accepts ``on_progress`` gets chunk-granular SSE streaming
    (``deadline_s`` likewise forwards when accepted); otherwise
    ``"stream": true`` requests receive the buffered result in SSE
    framing.

    ``ready_fn`` (→ dict with at least ``{"ready": bool}``) backs
    ``/readyz``; without one the route reports ready whenever the server
    is not draining (the engine was loaded before construction).
    ``max_tokens_cap`` clamps per-request token budgets to the engine's
    sequence capacity."""

    def __init__(self, generate_fn, model_id: str, port: int = 3000,
                 host: str = "127.0.0.1", serialize: bool = True,
                 ready_fn=None, max_tokens_cap: int | None = None,
                 max_body_bytes: int = MAX_BODY_BYTES,
                 drain_timeout_s: float = 120.0,
                 stats_fn=None, tracer=None, trace_out: str | None = None,
                 postmortem_dir: str | None = None):
        # loopback by default: the endpoint is unauthenticated, and the
        # in-repo client only ever connects to localhost; pass host="0.0.0.0"
        # deliberately to expose it
        #
        # ``serialize=False``: generate_fn is safe under concurrent calls
        # (a ContinuousSession routing every call into one live batch) —
        # concurrent POSTs then overlap on the chip instead of queueing on
        # the lock (vLLM api_server semantics, reference start_server.sh:17)
        import contextlib
        import inspect

        self.generate_fn = generate_fn
        self.model_id = model_id
        params = inspect.signature(generate_fn).parameters
        self._streams = "on_progress" in params
        self._deadlines = "deadline_s" in params
        self._req_ids = "request_id" in params
        self._grammars = "grammar" in params
        #: reproducibility receipts (obs/receipts.py): generate_fns that
        #: accept ``on_receipt`` get their receipt exposed as the
        #: ``X-Reval-Receipt`` header + ``receipt`` JSON field (and an
        #: SSE trailer event); session-less engines simply don't
        self._receipts = "on_receipt" in params
        self._lock = (threading.Lock() if serialize
                      else contextlib.nullcontext())
        self.ready_fn = ready_fn
        #: zero-arg -> list[EngineStats]: the registries ``/metrics`` and
        #: ``/statusz`` merge (attach_session wires it; session-less
        #: engines pass it explicitly)
        self.stats_fn = stats_fn
        #: server-side counters (HTTP-level, engine-independent)
        self._obs = MetricsRegistry()
        self.tracer = tracer
        self.trace_out = trace_out
        #: lazy fallback writer for dump_postmortem on session-less
        #: servers (sessions bring their own, with its retention window);
        #: honors the same configured directory either way
        self._postmortem_dir = postmortem_dir
        self._postmortem_writer: PostmortemWriter | None = None
        self.max_tokens_cap = max_tokens_cap
        self.max_body_bytes = int(max_body_bytes)
        self.drain_timeout_s = float(drain_timeout_s)
        self._draining = threading.Event()
        self._shutdown_lock = threading.Lock()
        self._shutdown_started = False      # guarded-by: _shutdown_lock
        self._shutdown_complete = threading.Event()
        # in-flight POST handlers + SSE worker threads, tracked so a
        # graceful drain can wait for them before tearing anything down
        self._inflight_cv = threading.Condition()
        self._inflight_http = 0             # guarded-by: _inflight_cv
        self._workers: set[threading.Thread] = set()    # guarded-by: _workers_lock
        self._workers_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code: int, payload: dict,
                      headers: dict | None = None,
                      request_id: str | None = None) -> None:
                body = json.dumps(payload).encode()
                self._send_bytes(code, body, "application/json",
                                 headers, request_id)

            def _send_bytes(self, code: int, body: bytes, ctype: str,
                            headers: dict | None = None,
                            request_id: str | None = None) -> None:
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    if request_id is not None:
                        # echoed on EVERY response (success included) so
                        # the client's retry log and the server log name
                        # the same request
                        self.send_header("X-Request-Id", request_id)
                    for key, value in (headers or {}).items():
                        self.send_header(key, value)
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    # client hung up mid-response: this handler thread is
                    # done; the engine and other requests are unaffected
                    pass

            def _send_serving_error(self, exc: ServingError,
                                    rid: str) -> None:
                headers = None
                if exc.retry_after is not None:
                    headers = {"Retry-After":
                               str(int(math.ceil(exc.retry_after)))}
                self._send(exc.status, _wire_payload(exc, rid), headers,
                           request_id=rid)

            def do_GET(self):
                path = self.path.rstrip("/")
                # echo the caller's id when one was sent (GETs don't mint:
                # probes/scrapes are anonymous by default)
                rid = (_RID_RE.sub("", self.headers.get("X-Request-Id", ""))
                       [:64] or None)
                if path == "/v1/models":
                    self._send(200, {"object": "list",
                                     "data": [{"id": outer.model_id,
                                               "object": "model"}]},
                               request_id=rid)
                elif path in ("/healthz", "/v1/healthz"):
                    # pure LIVENESS: the process answers — even while
                    # draining or wedged (orchestrators must not kill a
                    # pod for being busy shutting down cleanly)
                    self._send(200, {"status": "ok",
                                     "model": outer.model_id},
                               request_id=rid)
                elif path in ("/readyz", "/v1/readyz"):
                    if outer._draining.is_set():
                        self._send(503, {"status": "draining",
                                         "ready": False},
                                   {"Retry-After": "1"}, request_id=rid)
                        return
                    info = (outer.ready_fn() if outer.ready_fn is not None
                            else {"ready": True})
                    ready = bool(info.get("ready"))
                    # "warming" is its own not-ready state (boot replaying
                    # a warm-state snapshot — distinct from "draining"):
                    # the client handshake and the router health poller
                    # both keep polling a 503 + Retry-After
                    status = ("ready" if ready
                              else "warming" if info.get("warming")
                              else "unready")
                    self._send(200 if ready else 503,
                               {"status": status, **info},
                               None if ready else {"Retry-After": "1"},
                               request_id=rid)
                elif path in ("/metrics", "/v1/metrics"):
                    self._send_bytes(
                        200, outer.metrics_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                        request_id=rid)
                elif path in ("/statusz", "/v1/statusz"):
                    self._send(200, outer.statusz(), request_id=rid)
                elif path in ("/debugz", "/v1/debugz"):
                    # the postmortem bundle, live: what a crash dump
                    # would contain RIGHT NOW (flight records, in-flight
                    # request table, spans, recent logs) — nothing is
                    # written; scrape-safe under concurrency
                    self._send(200, outer.debug_bundle(), request_id=rid)
                else:
                    self._send(404, _err("not_found",
                                         f"unknown route {self.path}"),
                               request_id=rid)

            def do_POST(self):
                # per-request isolation: whatever one request does, the
                # worst outcome is its own error response — never a dead
                # serve loop taking the whole fleet's backend with it.
                # The id is the CLIENT's X-Request-Id when sent (so both
                # sides' logs name the same request), minted otherwise.
                rid = _request_id(self.headers)
                outer._obs.counter(obs_metrics.HTTP_REQUESTS).add(1)
                with outer._track():
                    try:
                        self._handle_post(rid)
                    except Exception as exc:  # noqa: BLE001
                        log_event("server.request_error", level="error",
                                  request_id=rid, exc=exc, where="handler",
                                  trace=traceback.format_exc())
                        self._send(500, _err(
                            "internal_error",
                            "internal error (see server log)", rid),
                            request_id=rid)

            def _handle_post(self, rid: str):
                if self.path.rstrip("/") != "/v1/completions":
                    self._send(404, _err("not_found",
                                         f"unknown route {self.path}"),
                               request_id=rid)
                    return
                if outer._draining.is_set():
                    self._send(503, _err("draining",
                                         "server is draining", rid),
                               {"Retry-After": "1"}, request_id=rid)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    self._send(400, _err("invalid_request",
                                         "bad Content-Length", rid),
                               request_id=rid)
                    return
                if length < 0:
                    # a negative length would defeat the cap below AND
                    # turn rfile.read(length) into read-until-EOF
                    self._send(400, _err("invalid_request",
                                         "bad Content-Length", rid),
                               request_id=rid)
                    return
                if length > outer.max_body_bytes:
                    self._send(413, _err(
                        "request_too_large",
                        f"body of {length} bytes exceeds the "
                        f"{outer.max_body_bytes}-byte cap", rid),
                        request_id=rid)
                    return
                try:
                    req = json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(req, dict):
                        raise ValueError("request body must be a JSON object")
                    p = _validate_request(req, outer.max_tokens_cap)
                except ValueError as exc:   # malformed request → client error
                    self._send(400, _err("invalid_request", str(exc), rid),
                               request_id=rid)
                    return
                except Exception:
                    self._send(400, _err("invalid_request",
                                         "malformed JSON body", rid),
                               request_id=rid)
                    return
                sampling = ({"top_k": p["top_k"], "top_p": p["top_p"]}
                            if (p["top_k"] > 0 or p["top_p"] < 1.0)
                            and p["temperature"] > 0 else {})
                if outer._deadlines and p["deadline_s"] is not None:
                    sampling["deadline_s"] = p["deadline_s"]
                if p["grammar"] is not None:
                    if not outer._grammars:
                        # a silently-dropped constraint would score
                        # unconstrained generations as constrained ones
                        self._send(400, _err(
                            "invalid_request",
                            "this engine does not support "
                            "grammar-constrained decoding", rid),
                            request_id=rid)
                        return
                    sampling["grammar"] = p["grammar"]
                if outer._req_ids:
                    # sessions thread the id into spans + engine logs
                    sampling["request_id"] = rid
                if p["stream"]:
                    self._stream(p["prompts"], p["max_tokens"],
                                 p["temperature"], p["stop"], rid, **sampling)
                    return
                receipt_box: list = []
                if outer._receipts:
                    # the session driver delivers the receipt BEFORE the
                    # blocking result() returns, so one element is here
                    # (or none, on engines that predate receipts) by the
                    # time generate_fn comes back
                    sampling["on_receipt"] = receipt_box.append
                try:
                    with outer._lock:
                        texts = outer.generate_fn(
                            p["prompts"], max_tokens=p["max_tokens"],
                            temperature=p["temperature"], stop=p["stop"],
                            **sampling)
                except ServingError as exc:
                    # deliberate lifecycle outcome: stable code + status,
                    # message authored by the serving layer (wire-safe)
                    self._send_serving_error(exc, rid)
                    return
                except ValueError as exc:
                    # engine-side parameter rejection (token budget larger
                    # than the sequence capacity, …): the request's fault
                    self._send(400, _err("invalid_request", str(exc), rid),
                               request_id=rid)
                    return
                except Exception as exc:  # engine/device fault → server error
                    log_event("server.request_error", level="error",
                              request_id=rid, exc=exc, where="generate",
                              trace=traceback.format_exc())
                    self._send(500, _err("internal_error",
                                         "internal error (see server log)",
                                         rid),
                               request_id=rid)
                    return
                payload = {
                    "object": "text_completion",
                    "model": outer.model_id,
                    "choices": [{"index": i, "text": t, "finish_reason": "stop"}
                                for i, t in enumerate(texts)],
                }
                headers = None
                if receipt_box:
                    from ..obs.receipts import encode_receipt

                    # both exposures carry the SAME receipt: body field
                    # for JSON consumers, header for anything that only
                    # sees response metadata (proxies, the client's
                    # verification cross-checks the two)
                    payload["receipt"] = receipt_box[0]
                    headers = {"X-Reval-Receipt":
                               encode_receipt(receipt_box[0])}
                self._send(200, payload, headers, request_id=rid)

            def _stream(self, prompts, max_tokens, temperature, stop, rid,
                        **sampling) -> None:
                """SSE streaming: one delta event per decode chunk.

                Single-writer design: the engine runs on a worker thread
                and only ever pushes (index, text, reason) into a queue —
                it NEVER touches the socket, so a client that stops
                reading stalls only this handler thread, not the engine
                or the global engine lock, and concurrent dp-replica
                callbacks cannot interleave bytes on the wire."""
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("X-Request-Id", rid)
                self.end_headers()
                import queue

                q: queue.Queue = queue.Queue()
                receipt_box: list = []

                def run() -> None:
                    try:
                        kwargs = dict(sampling)
                        if outer._streams:
                            kwargs["on_progress"] = (
                                lambda i, t: q.put((i, t, None)))
                        if outer._receipts:
                            kwargs["on_receipt"] = receipt_box.append
                        with outer._lock:
                            texts = outer.generate_fn(
                                prompts, max_tokens=max_tokens,
                                temperature=temperature, stop=stop, **kwargs)
                        for i, t in enumerate(texts):
                            q.put((i, t, "stop"))
                    except ServingError as exc:
                        q.put(("error", _wire_payload(exc, rid), None))
                    except Exception as exc:
                        log_event("server.request_error", level="error",
                                  request_id=rid, exc=exc, where="stream",
                                  trace=traceback.format_exc())
                        q.put(("error", _err("internal_error",
                                             "internal error (see server "
                                             "log)", rid), None))
                    finally:
                        q.put(None)
                        with outer._workers_lock:
                            outer._workers.discard(threading.current_thread())

                worker = threading.Thread(target=run, daemon=True,
                                          name="sse-generate")
                with outer._workers_lock:
                    outer._workers.add(worker)
                worker.start()

                sent = [""] * len(prompts)
                dead = False

                def event(payload) -> bool:
                    nonlocal dead
                    if dead:
                        return False        # client gone: drain, don't write
                    try:
                        self.wfile.write(b"data: "
                                         + json.dumps(payload).encode()
                                         + b"\n\n")
                        self.wfile.flush()
                        return True
                    except OSError:
                        dead = True
                        return False

                while True:
                    item = q.get()
                    if item is None:
                        break
                    if item[0] == "error":  # headers sent: in-band error
                        event(item[1])
                        continue
                    i, text, reason = item
                    if reason is None:
                        # never stream a tail that might be the start of a
                        # stop string: finalize_text only truncates on the
                        # COMPLETE stop, so a chunk boundary mid-stop would
                        # otherwise leak "[/ANS" and then retract it
                        text = _hold_stop_prefix(text, stop)
                    if text.startswith(sent[i]):
                        delta = text[len(sent[i]):]
                        sent[i] = text
                    elif reason is None:
                        continue            # detok wobble: wait for stability
                    else:
                        delta = ""          # terminal: always deliver finish
                    if delta or reason is not None:
                        event({"object": "text_completion",
                               "model": outer.model_id,
                               "choices": [{"index": i, "text": delta,
                                            "finish_reason": reason}]})
                if not dead:
                    try:
                        if receipt_box:
                            # the receipt TRAILER: emitted after every
                            # delta and terminal event, right before
                            # [DONE] — a mid-stream disconnect simply
                            # never sees it (the generation's receipt
                            # was still stamped engine-side)
                            self.wfile.write(
                                b"data: " + json.dumps(
                                    {"object": "reval.receipt",
                                     "model": outer.model_id,
                                     "receipt": receipt_box[0]}).encode()
                                + b"\n\n")
                        self.wfile.write(b"data: [DONE]\n\n")
                        self.wfile.flush()
                    except OSError:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]   # resolved if port=0
        self._thread: threading.Thread | None = None

    def attach_session(self, session) -> None:
        """Bind a :class:`ContinuousSession`/:class:`MultiSession`: its
        readiness backs ``/readyz``, its engine registries feed
        ``/metrics``, and ``shutdown()`` drains it in the right order
        (before the listener socket closes)."""
        self._session = session
        if self.ready_fn is None:
            self.ready_fn = session.readiness
        if self.stats_fn is None:
            self.stats_fn = session.engine_stats

    # -- observability endpoints -------------------------------------------
    def merged_registry(self) -> MetricsRegistry:
        """Every engine/session registry folded with the server's own
        counters — counters sum, histogram buckets add, gauges take last
        (the dp/MultiSession merge rule; one scrape sees the whole
        replica set)."""
        regs = [self._obs]
        if self.stats_fn is not None:
            regs.extend(s.registry for s in self.stats_fn())
        return MetricsRegistry.merged(regs)

    def metrics_text(self) -> str:
        return self.merged_registry().render_prometheus()

    def statusz(self) -> dict:
        """JSON twin of ``/metrics`` + readiness detail (one curl shows
        what a human wants; Prometheus scrapes the text twin)."""
        out = {"model": self.model_id,
               "draining": self._draining.is_set(),
               "metrics": self.merged_registry().snapshot()}
        if self.ready_fn is not None:
            try:
                out["readiness"] = self.ready_fn()
            except Exception:   # a readiness fault must not kill statusz
                out["readiness"] = {"ready": False, "error": "ready_fn failed"}
        return out

    def debug_bundle(self) -> dict:
        """The live postmortem bundle behind ``GET /debugz``: whatever a
        crash dump would contain right now, for the attached session (or
        a metrics-only bundle for session-less engines), plus the
        server's own identity/drain state."""
        session = getattr(self, "_session", None)
        try:
            if session is not None and hasattr(session, "postmortem_bundle"):
                bundle = session.postmortem_bundle("debugz")
            else:
                # session-less engines (static/pp/sp): metrics + any
                # flight records, no per-request lifecycle table
                fr = getattr(getattr(self, "_engine", None),
                             "flightrec", None)
                bundle = build_bundle(
                    "debugz", metrics=self.merged_registry().snapshot(),
                    flight=fr.snapshot() if fr is not None else None)
        except Exception as exc:    # a debug scrape must never 500
            bundle = build_bundle("debugz", error=repr(exc))
        bundle["model"] = self.model_id
        bundle["draining"] = self._draining.is_set()
        return bundle

    def dump_postmortem(self, reason: str) -> str | None:
        """Write the current bundle to disk (SIGUSR1 / SIGTERM-drain
        triggers — the CLI wires the signals).  Uses the session's
        writer (its retention window) when one is attached."""
        session = getattr(self, "_session", None)
        writer = getattr(session, "_postmortem", None)
        if writer is None:
            writer = self._postmortem_writer
            if writer is None:
                writer = self._postmortem_writer = PostmortemWriter(
                    self._postmortem_dir)
        bundle = self.debug_bundle()
        bundle["reason"] = reason
        try:
            return writer.dump(bundle)
        except Exception as exc:
            log_event("session.postmortem", level="error", exc=exc,
                      reason=reason)
            return None

    def _track(self):
        import contextlib

        @contextlib.contextmanager
        def tracked():
            with self._inflight_cv:
                self._inflight_http += 1
            try:
                yield
            finally:
                with self._inflight_cv:
                    self._inflight_http -= 1
                    self._inflight_cv.notify_all()
        return tracked()

    def start(self) -> "EngineServer":
        """Serve on a daemon thread (tests, co-located runs)."""
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Blocking serve (the CLI entry point)."""
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        """Graceful drain, idempotent.  Order matters and is the point:

        1. flip ``_draining`` — new POSTs get 503 + Retry-After and
           ``/readyz`` goes unready, so load balancers/clients move on;
        2. wait (bounded by ``drain_timeout_s``) for in-flight request
           handlers, then join SSE worker threads;
        3. close the session — the driver finishes whatever the handlers
           left in flight and releases the engine;
        4. only THEN stop the accept loop and close the listener socket;
        5. record ``drain_seconds`` and flush a counters summary to the
           log (the process is about to exit — this is the last trace).
        """
        with self._shutdown_lock:
            started, self._shutdown_started = self._shutdown_started, True
        if started:
            # concurrent/second call: wait for the first drain to finish
            # rather than return mid-drain (a caller exiting the process
            # on return would kill the draining thread under it)
            self._shutdown_complete.wait()
            return
        try:
            self._drain()
        finally:
            # an exception mid-drain must not strand every other
            # shutdown() caller on the wait above forever
            self._shutdown_complete.set()

    def _drain(self) -> None:
        t0 = time.monotonic()
        self._draining.set()
        deadline = t0 + self.drain_timeout_s
        with self._inflight_cv:
            while (self._inflight_http
                   and time.monotonic() < deadline):
                self._inflight_cv.wait(
                    timeout=max(0.01, min(1.0, deadline - time.monotonic())))
            leftover = self._inflight_http
        with self._workers_lock:
            workers = list(self._workers)
        for worker in workers:
            worker.join(timeout=max(0.1, deadline - time.monotonic()))
        session = getattr(self, "_session", None)
        if session is not None:
            session.close()
        if self.tracer is not None and self.trace_out:
            # after session.close(): every in-flight request has resolved,
            # so its span tree is recorded — the file is complete
            try:
                n = self.tracer.save(self.trace_out)
                log_event("server.trace_written", path=self.trace_out,
                          events=n)
            except OSError as exc:
                log_event("server.trace_error", level="error",
                          path=self.trace_out, exc=exc)
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()
        drain = time.monotonic() - t0
        counters: dict = {}
        if session is not None:
            all_stats = session.engine_stats()
            if all_stats:
                # ONE wall-clock drain happened: record it once (the dp
                # stats aggregator SUMS drain_seconds over replicas, so
                # adding it to each would report the drain dp-fold)
                all_stats[0].drain_seconds += drain
            for stats in all_stats:
                for key, value in stats.serving_counters().items():
                    counters[key] = round(counters.get(key, 0) + value, 3)
                counters["prompts"] = counters.get("prompts", 0) + stats.prompts
        log_event("server.drained", drain_seconds=round(drain, 3),
                  leftover_requests=leftover, counters=counters or None,
                  level="warning" if leftover else "info")


def _engine_generate_fn(engine):
    import inspect

    params = inspect.signature(engine.generate).parameters
    streams = "on_progress" in params
    grammars = "grammar" in params

    if grammars:
        def generate(prompts, *, max_tokens, temperature, stop,
                     top_k=0, top_p=1.0, on_progress=None, grammar=None):
            kwargs = {"grammar": grammar} if grammar is not None else {}
            if on_progress is not None and streams:
                kwargs["on_progress"] = on_progress
            if top_k > 0 or top_p < 1.0:
                kwargs.update(top_k=top_k, top_p=top_p)
            return engine.generate(prompts, max_new_tokens=max_tokens,
                                   temperature=temperature, stop=stop,
                                   **kwargs)
        return generate

    def generate(prompts, *, max_tokens, temperature, stop,
                 top_k=0, top_p=1.0, on_progress=None):
        # no grammar kwarg on purpose: the server then 400s grammar=
        # requests instead of silently decoding unconstrained
        kwargs = {}
        if on_progress is not None and streams:
            # engines without the hook (static) fall back to a buffered
            # result, still delivered over the SSE framing
            kwargs["on_progress"] = on_progress
        if top_k > 0 or top_p < 1.0:
            kwargs.update(top_k=top_k, top_p=top_p)
        return engine.generate(prompts, max_new_tokens=max_tokens,
                               temperature=temperature, stop=stop, **kwargs)
    return generate


def _max_tokens_cap(engine) -> int | None:
    """The largest per-request token budget ``encode_clipped`` accepts
    (one prompt token + the clip margin must survive)."""
    max_len = getattr(engine, "max_seq_len", None)
    if max_len is None:
        pages = getattr(engine, "max_pages_per_seq", None)
        if pages:
            max_len = pages * getattr(engine, "page_size", 128)
    return max_len - 2 if max_len else None


def warmup_engine(engine) -> float:
    """Compile the hot programs before the server takes traffic.

    The first request otherwise pays the jit cost (20-40 s per shape on a
    real chip — SURVEY §7 hard part 4's bucketing bounds the shape count,
    but the first hit per bucket still compiles).  One short and one long
    prompt cover the smallest and a large prefill bucket plus the decode
    chunk programs (the budget spans a full chunk, so the steady-state
    chunk compiles, not just the short first-chunk variant).  Returns the
    wall seconds spent (logged by the CLI).
    """
    import time

    t0 = time.perf_counter()
    for prompt in ("pass", "pass\n" * 300):
        engine.generate([prompt], max_new_tokens=40, temperature=0.0,
                        stop=["[/ANSWER]"])
    # the top-k/top-p filter is a DISTINCT jitted chunk program (static
    # flag): compile it too, or the first nucleus request stalls the
    # live batch for the full jit cost despite this warmup.  Detect
    # filter support by signature (not try/except TypeError, which would
    # also swallow real plumbing bugs inside a supporting engine).
    import inspect

    if "top_p" in inspect.signature(engine.generate).parameters:
        engine.generate(["pass"], max_new_tokens=40, temperature=0.8,
                        top_p=0.95, stop=["[/ANSWER]"])
    return time.perf_counter() - t0


def serve_config(cfg: dict, *, port: int | None = None,
                 warmup: bool = False, step_chaos=None) -> EngineServer:
    """Build the TPU engine from a run config (same keys the ``tpu``
    backend takes) and return an unstarted server bound to ``port``
    (default: config ``port`` or 3000).  ``warmup`` pre-compiles the hot
    generation programs before binding.

    A single paged engine is served through a :class:`ContinuousSession`
    and a dp replica set through a :class:`MultiSession` (one session per
    replica, least-loaded routing): concurrent POSTs join live decode
    batches (vLLM api_server semantics).  Other engines (static/pp/sp)
    keep the serialised per-request path.

    ``cfg["mock"]`` serves a host-only
    :class:`~reval_tpu.serving.mock_engine.MockStepEngine` through the
    SAME session/server stack — the zero-TPU lifecycle smoke target.
    Lifecycle knobs ride the config: ``max_queued_tokens`` (admission
    watermark), ``watchdog_s`` (no-progress threshold).  ``step_chaos``
    injects engine-step faults into the session driver (hardening/tests).
    """
    from .session import ContinuousSession

    model_id = cfg.get("model_id", "reval-tpu-model")
    bind = port if port is not None else cfg.get("port", 3000)
    trace_out = cfg.get("trace_out")
    tracer = None
    if trace_out:
        from ..obs.trace import Tracer

        tracer = Tracer()
    lifecycle = {"max_queued_tokens": cfg.get("max_queued_tokens"),
                 "watchdog_s": cfg.get("watchdog_s"), "tracer": tracer,
                 "postmortem_dir": cfg.get("postmortem_dir"),
                 # warm restarts: drain writes the snapshot here, boot
                 # replays it (default env REVAL_TPU_SNAPSHOT_PATH);
                 # the fallback is a SIBLING's snapshot an autoscaler
                 # scale-up inherits (read-only)
                 "snapshot_path": cfg.get("snapshot_path"),
                 "snapshot_fallback": cfg.get("snapshot_fallback")}
    # KV-tier fault injection (inference/tpu/kv_tiers.py): deterministic
    # corrupt/stall/fail faults on tier promotions — every one must
    # degrade to a recompute, never a wrong token (hardening drills)
    tier_chaos = None
    if cfg.get("tier_chaos"):
        from ..resilience import TierChaos

        modes = cfg.get("tier_chaos_modes")
        mode_kw = ({"modes": tuple(m for m in str(modes).split(",") if m)}
                   if modes else {})
        tier_chaos = TierChaos(
            rate=float(cfg["tier_chaos"]),
            seed=int(cfg.get("tier_chaos_seed", 0)),
            stall_s=float(cfg.get("tier_chaos_stall_s", 0.05)), **mode_kw)
    body_cap = int(cfg.get("max_body_bytes", MAX_BODY_BYTES))
    obs_kw = {"tracer": tracer, "trace_out": trace_out,
              "postmortem_dir": cfg.get("postmortem_dir")}
    if cfg.get("mock"):
        if tier_chaos is not None:
            # no KV pool to tier — a drill that silently tests nothing
            # is worse than a loud error (same rule as step chaos on
            # sessionless engines)
            raise ValueError("tier_chaos requires a paged TPU engine — "
                             "the mock engine has no KV pool to tier")
        from .mock_engine import MockStepEngine

        engine = MockStepEngine(
            response=cfg.get("mock_response", "mock_model_gen"),
            step_s=float(cfg.get("mock_step_s", 0.0)),
            echo=bool(cfg.get("mock_echo", False)),
            rewarm_s=float(cfg.get("mock_rewarm_s", 0.0)))
        session = ContinuousSession(engine, step_chaos=step_chaos,
                                    **lifecycle)
        server = EngineServer(session.generate_fn(), model_id=model_id,
                              port=bind, serialize=False,
                              max_body_bytes=body_cap,
                              max_tokens_cap=_max_tokens_cap(engine),
                              **obs_kw)
        server.attach_session(session)
        return server

    from ..inference.tpu.backend import TPUBackend
    from ..inference.tpu.dp_paged import DataParallelPagedEngine
    from ..inference.tpu.paged_engine import PagedTPUEngine

    backend = TPUBackend(tier_chaos=tier_chaos,
                         **{k: v for k, v in cfg.items()
                            if k not in ("task", "backend", "port", "mock",
                                         "max_queued_tokens", "watchdog_s",
                                         "max_body_bytes", "trace_out",
                                         "postmortem_dir", "mock_response",
                                         "mock_step_s", "mock_echo",
                                         "mock_rewarm_s", "snapshot_path",
                                         "snapshot_fallback", "tier_chaos",
                                         "tier_chaos_seed",
                                         "tier_chaos_modes",
                                         "tier_chaos_stall_s")})
    if warmup:
        secs = warmup_engine(backend.engine)
        print(f"warmup: generation programs compiled in {secs:.1f}s")

    session = None
    if isinstance(backend.engine, PagedTPUEngine):
        session = ContinuousSession(backend.engine, step_chaos=step_chaos,
                                    **lifecycle)
        cap = _max_tokens_cap(backend.engine)
    elif isinstance(backend.engine, DataParallelPagedEngine):
        # dp replica set: one session per replica + least-loaded routing
        from .session import MultiSession

        session = MultiSession(backend.engine.replicas,
                               step_chaos=step_chaos, **lifecycle)
        cap = _max_tokens_cap(backend.engine.replicas[0])
    if session is None and step_chaos is not None:
        # static/pp/sp engines have no session drive loop to inject into —
        # failing loudly beats a hardening drill that silently tests nothing
        raise ValueError("engine-step chaos requires a session-driven "
                         "engine (paged, dp replicas, or --mock)")
    if session is not None:
        server = EngineServer(session.generate_fn(), model_id=model_id,
                              port=bind, serialize=False, max_tokens_cap=cap,
                              max_body_bytes=body_cap, **obs_kw)
        server.attach_session(session)   # readiness + ordered drain
        return server
    # session-less engines (static/pp/sp) still expose /metrics: no
    # per-request spans (the session records those), but every engine
    # counter and engine-side histogram is there
    server = EngineServer(_engine_generate_fn(backend.engine),
                          model_id=model_id, port=bind,
                          max_body_bytes=body_cap,
                          max_tokens_cap=_max_tokens_cap(backend.engine),
                          stats_fn=lambda eng=backend.engine: [eng.stats],
                          **obs_kw)
    server._engine = backend.engine     # /debugz: flight records, no session
    return server
