"""OpenAI-compatible completions server over the resident TPU engine.

Protocol surface (exactly what the client backend + reference harness use;
reference inference.py:110-131, start_server.sh):

- ``GET /v1/models``           → ``{"data": [{"id": <model_id>}]}``
- ``POST /v1/completions``     → prompt (string or list), ``max_tokens``,
  ``temperature``, ``stop`` → ``{"choices": [{"index", "text"}]}``;
  with ``"stream": true`` → Server-Sent Events, one
  ``data: {"choices": [{"index", "text": <delta>}]}`` event per decode
  chunk and a final ``data: [DONE]`` — the protocol the reference's
  clients speak to vLLM's server (reference inference.py:115-131 sets
  ``stream=True`` and accumulates deltas).

Implementation notes:
- stdlib ``ThreadingHTTPServer``; each request handles its own socket but
  engine calls are serialised with a lock — the engine owns device state
  (KV cache, scheduler) and is single-owner by design.  Batching comes
  from *list prompts in one request* (the client backend sends whole
  task batches), which the engine schedules together; concurrent separate
  requests queue on the lock.
- streaming rides the engine's ``on_progress`` hook (decode-chunk
  granularity, ~32 tokens).  BPE detokenisation is not strictly
  prefix-stable at chunk edges, so a delta is emitted only when the new
  text extends what was already sent; a non-extending revision is held
  back until it stabilises (the common case is plain extension).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["EngineServer", "serve_config"]


def _hold_stop_prefix(text: str, stop: list[str]) -> str:
    """Trim a trailing substring that is a proper prefix of any stop
    string — it might complete into the stop next chunk, in which case
    the final text would retract it (append-only streams cannot)."""
    if not stop:
        return text
    max_hold = max(len(s) for s in stop) - 1
    for k in range(min(max_hold, len(text)), 0, -1):
        tail = text[-k:]
        if any(s.startswith(tail) for s in stop):
            return text[:-k]
    return text


class EngineServer:
    """Serve ``generate_fn(prompts, max_tokens, temperature, stop) ->
    list[str]`` over the OpenAI completions protocol.  A ``generate_fn``
    that also accepts ``on_progress`` gets chunk-granular SSE streaming;
    otherwise ``"stream": true`` requests receive the buffered result in
    SSE framing."""

    def __init__(self, generate_fn, model_id: str, port: int = 3000,
                 host: str = "127.0.0.1", serialize: bool = True):
        # loopback by default: the endpoint is unauthenticated, and the
        # in-repo client only ever connects to localhost; pass host="0.0.0.0"
        # deliberately to expose it
        #
        # ``serialize=False``: generate_fn is safe under concurrent calls
        # (a ContinuousSession routing every call into one live batch) —
        # concurrent POSTs then overlap on the chip instead of queueing on
        # the lock (vLLM api_server semantics, reference start_server.sh:17)
        import contextlib
        import inspect

        self.generate_fn = generate_fn
        self.model_id = model_id
        self._streams = ("on_progress"
                         in inspect.signature(generate_fn).parameters)
        self._lock = (threading.Lock() if serialize
                      else contextlib.nullcontext())
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code: int, payload: dict) -> None:
                try:
                    body = json.dumps(payload).encode()
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    # client hung up mid-response: this handler thread is
                    # done; the engine and other requests are unaffected
                    pass

            def do_GET(self):
                path = self.path.rstrip("/")
                if path == "/v1/models":
                    self._send(200, {"object": "list",
                                     "data": [{"id": outer.model_id,
                                               "object": "model"}]})
                elif path in ("/healthz", "/v1/healthz"):
                    # the client handshake polls this until the engine is
                    # loaded; answering at all is the signal
                    self._send(200, {"status": "ok",
                                     "model": outer.model_id})
                else:
                    self._send(404, {"error": f"unknown route {self.path}"})

            def do_POST(self):
                # per-request isolation: whatever one request does, the
                # worst outcome is its own error response — never a dead
                # serve loop taking the whole fleet's backend with it
                try:
                    self._handle_post()
                except Exception as exc:  # noqa: BLE001
                    self._send(500, {"error": f"internal error: {exc}"})

            def _handle_post(self):
                if self.path.rstrip("/") != "/v1/completions":
                    self._send(404, {"error": f"unknown route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    prompts = req.get("prompt", "")
                    single = isinstance(prompts, str)
                    if single:
                        prompts = [prompts]
                    stop = req.get("stop") or []
                    if isinstance(stop, str):
                        stop = [stop]
                    max_tokens = int(req.get("max_tokens", 256))
                    temperature = float(req.get("temperature", 0.0))
                    top_k = int(req.get("top_k", 0))        # 0 = off
                    top_p = float(req.get("top_p", 1.0))    # 1 = off
                    stream = bool(req.get("stream", False))
                except Exception as exc:        # malformed request → client error
                    self._send(400, {"error": str(exc)})
                    return
                sampling = ({"top_k": top_k, "top_p": top_p}
                            if (top_k > 0 or top_p < 1.0)
                            and temperature > 0 else {})
                if stream:
                    self._stream(prompts, max_tokens, temperature, stop,
                                 **sampling)
                    return
                try:
                    with outer._lock:
                        texts = outer.generate_fn(
                            prompts, max_tokens=max_tokens,
                            temperature=temperature, stop=stop, **sampling)
                except Exception as exc:        # engine/device fault → server error
                    self._send(500, {"error": str(exc)})
                    return
                self._send(200, {
                    "object": "text_completion",
                    "model": outer.model_id,
                    "choices": [{"index": i, "text": t, "finish_reason": "stop"}
                                for i, t in enumerate(texts)],
                })

            def _stream(self, prompts, max_tokens, temperature, stop,
                        **sampling) -> None:
                """SSE streaming: one delta event per decode chunk.

                Single-writer design: the engine runs on a worker thread
                and only ever pushes (index, text, reason) into a queue —
                it NEVER touches the socket, so a client that stops
                reading stalls only this handler thread, not the engine
                or the global engine lock, and concurrent dp-replica
                callbacks cannot interleave bytes on the wire."""
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                import queue

                q: queue.Queue = queue.Queue()

                def run() -> None:
                    try:
                        kwargs = dict(sampling)
                        if outer._streams:
                            kwargs["on_progress"] = (
                                lambda i, t: q.put((i, t, None)))
                        with outer._lock:
                            texts = outer.generate_fn(
                                prompts, max_tokens=max_tokens,
                                temperature=temperature, stop=stop, **kwargs)
                        for i, t in enumerate(texts):
                            q.put((i, t, "stop"))
                    except Exception as exc:
                        q.put(("error", str(exc), None))
                    q.put(None)

                threading.Thread(target=run, daemon=True,
                                 name="sse-generate").start()

                sent = [""] * len(prompts)
                dead = False

                def event(payload) -> bool:
                    nonlocal dead
                    if dead:
                        return False        # client gone: drain, don't write
                    try:
                        self.wfile.write(b"data: "
                                         + json.dumps(payload).encode()
                                         + b"\n\n")
                        self.wfile.flush()
                        return True
                    except OSError:
                        dead = True
                        return False

                while True:
                    item = q.get()
                    if item is None:
                        break
                    if item[0] == "error":  # headers sent: in-band error
                        event({"error": item[1]})
                        continue
                    i, text, reason = item
                    if reason is None:
                        # never stream a tail that might be the start of a
                        # stop string: finalize_text only truncates on the
                        # COMPLETE stop, so a chunk boundary mid-stop would
                        # otherwise leak "[/ANS" and then retract it
                        text = _hold_stop_prefix(text, stop)
                    if text.startswith(sent[i]):
                        delta = text[len(sent[i]):]
                        sent[i] = text
                    elif reason is None:
                        continue            # detok wobble: wait for stability
                    else:
                        delta = ""          # terminal: always deliver finish
                    if delta or reason is not None:
                        event({"object": "text_completion",
                               "model": outer.model_id,
                               "choices": [{"index": i, "text": delta,
                                            "finish_reason": reason}]})
                if not dead:
                    try:
                        self.wfile.write(b"data: [DONE]\n\n")
                        self.wfile.flush()
                    except OSError:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]   # resolved if port=0
        self._thread: threading.Thread | None = None

    def start(self) -> "EngineServer":
        """Serve on a daemon thread (tests, co-located runs)."""
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Blocking serve (the CLI entry point)."""
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd.server_close()
        session = getattr(self, "_session", None)
        if session is not None:
            session.close()


def _engine_generate_fn(engine):
    import inspect

    streams = "on_progress" in inspect.signature(engine.generate).parameters

    def generate(prompts, *, max_tokens, temperature, stop,
                 top_k=0, top_p=1.0, on_progress=None):
        kwargs = {}
        if on_progress is not None and streams:
            # engines without the hook (static) fall back to a buffered
            # result, still delivered over the SSE framing
            kwargs["on_progress"] = on_progress
        if top_k > 0 or top_p < 1.0:
            kwargs.update(top_k=top_k, top_p=top_p)
        return engine.generate(prompts, max_new_tokens=max_tokens,
                               temperature=temperature, stop=stop, **kwargs)
    return generate


def warmup_engine(engine) -> float:
    """Compile the hot programs before the server takes traffic.

    The first request otherwise pays the jit cost (20-40 s per shape on a
    real chip — SURVEY §7 hard part 4's bucketing bounds the shape count,
    but the first hit per bucket still compiles).  One short and one long
    prompt cover the smallest and a large prefill bucket plus the decode
    chunk programs (the budget spans a full chunk, so the steady-state
    chunk compiles, not just the short first-chunk variant).  Returns the
    wall seconds spent (logged by the CLI).
    """
    import time

    t0 = time.perf_counter()
    for prompt in ("pass", "pass\n" * 300):
        engine.generate([prompt], max_new_tokens=40, temperature=0.0,
                        stop=["[/ANSWER]"])
    # the top-k/top-p filter is a DISTINCT jitted chunk program (static
    # flag): compile it too, or the first nucleus request stalls the
    # live batch for the full jit cost despite this warmup.  Detect
    # filter support by signature (not try/except TypeError, which would
    # also swallow real plumbing bugs inside a supporting engine).
    import inspect

    if "top_p" in inspect.signature(engine.generate).parameters:
        engine.generate(["pass"], max_new_tokens=40, temperature=0.8,
                        top_p=0.95, stop=["[/ANSWER]"])
    return time.perf_counter() - t0


def serve_config(cfg: dict, *, port: int | None = None,
                 warmup: bool = False) -> EngineServer:
    """Build the TPU engine from a run config (same keys the ``tpu``
    backend takes) and return an unstarted server bound to ``port``
    (default: config ``port`` or 3000).  ``warmup`` pre-compiles the hot
    generation programs before binding.

    A single paged engine is served through a :class:`ContinuousSession`
    and a dp replica set through a :class:`MultiSession` (one session per
    replica, least-loaded routing): concurrent POSTs join live decode
    batches (vLLM api_server semantics).  Other engines (static/pp/sp)
    keep the serialised per-request path."""
    from ..inference.tpu.backend import TPUBackend
    from ..inference.tpu.paged_engine import PagedTPUEngine

    backend = TPUBackend(**{k: v for k, v in cfg.items()
                            if k not in ("task", "backend", "port", "mock")})
    if warmup:
        secs = warmup_engine(backend.engine)
        print(f"warmup: generation programs compiled in {secs:.1f}s")
    from ..inference.tpu.dp_paged import DataParallelPagedEngine

    model_id = cfg.get("model_id", "reval-tpu-model")
    bind = port if port is not None else cfg.get("port", 3000)
    session = None
    if isinstance(backend.engine, PagedTPUEngine):
        from .session import ContinuousSession

        session = ContinuousSession(backend.engine)
    elif isinstance(backend.engine, DataParallelPagedEngine):
        # dp replica set: one session per replica + least-loaded routing
        from .session import MultiSession

        session = MultiSession(backend.engine.replicas)
    if session is not None:
        server = EngineServer(session.generate_fn(), model_id=model_id,
                              port=bind, serialize=False)
        server._session = session       # keep the driver threads reachable
        return server
    return EngineServer(_engine_generate_fn(backend.engine),
                        model_id=model_id, port=bind)
