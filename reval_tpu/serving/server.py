"""OpenAI-compatible completions server over the resident TPU engine.

Protocol surface (exactly what the client backend + reference harness use;
reference inference.py:110-131, start_server.sh):

- ``GET /v1/models``           → ``{"data": [{"id": <model_id>}]}``
- ``POST /v1/completions``     → prompt (string or list), ``max_tokens``,
  ``temperature``, ``stop`` → ``{"choices": [{"index", "text"}]}``

Implementation notes:
- stdlib ``ThreadingHTTPServer``; each request handles its own socket but
  engine calls are serialised with a lock — the engine owns device state
  (KV cache, scheduler) and is single-owner by design.  Batching comes
  from *list prompts in one request* (the client backend sends whole
  task batches), which the engine schedules together; concurrent separate
  requests queue on the lock.
- no streaming: the reference's client accumulates the stream and returns
  only the final string (reference inference.py:115-131), so a buffered
  response is observationally identical through that client.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["EngineServer", "serve_config"]


class EngineServer:
    """Serve ``generate_fn(prompts, max_tokens, temperature, stop) ->
    list[str]`` over the OpenAI completions protocol."""

    def __init__(self, generate_fn, model_id: str, port: int = 3000,
                 host: str = "127.0.0.1"):
        # loopback by default: the endpoint is unauthenticated, and the
        # in-repo client only ever connects to localhost; pass host="0.0.0.0"
        # deliberately to expose it
        self.generate_fn = generate_fn
        self.model_id = model_id
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.rstrip("/") == "/v1/models":
                    self._send(200, {"object": "list",
                                     "data": [{"id": outer.model_id,
                                               "object": "model"}]})
                else:
                    self._send(404, {"error": f"unknown route {self.path}"})

            def do_POST(self):
                if self.path.rstrip("/") != "/v1/completions":
                    self._send(404, {"error": f"unknown route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                    prompts = req.get("prompt", "")
                    single = isinstance(prompts, str)
                    if single:
                        prompts = [prompts]
                    stop = req.get("stop") or []
                    if isinstance(stop, str):
                        stop = [stop]
                    max_tokens = int(req.get("max_tokens", 256))
                    temperature = float(req.get("temperature", 0.0))
                except Exception as exc:        # malformed request → client error
                    self._send(400, {"error": str(exc)})
                    return
                try:
                    with outer._lock:
                        texts = outer.generate_fn(
                            prompts, max_tokens=max_tokens,
                            temperature=temperature, stop=stop)
                except Exception as exc:        # engine/device fault → server error
                    self._send(500, {"error": str(exc)})
                    return
                self._send(200, {
                    "object": "text_completion",
                    "model": outer.model_id,
                    "choices": [{"index": i, "text": t, "finish_reason": "stop"}
                                for i, t in enumerate(texts)],
                })

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]   # resolved if port=0
        self._thread: threading.Thread | None = None

    def start(self) -> "EngineServer":
        """Serve on a daemon thread (tests, co-located runs)."""
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Blocking serve (the CLI entry point)."""
        self._httpd.serve_forever()

    def shutdown(self) -> None:
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd.server_close()


def _engine_generate_fn(engine):
    def generate(prompts, *, max_tokens, temperature, stop):
        return engine.generate(prompts, max_new_tokens=max_tokens,
                               temperature=temperature, stop=stop)
    return generate


def serve_config(cfg: dict, *, port: int | None = None) -> EngineServer:
    """Build the TPU engine from a run config (same keys the ``tpu``
    backend takes) and return an unstarted server bound to ``port``
    (default: config ``port`` or 3000)."""
    from ..inference.tpu.backend import TPUBackend

    backend = TPUBackend(**{k: v for k, v in cfg.items()
                            if k not in ("task", "backend", "port", "mock")})
    server = EngineServer(_engine_generate_fn(backend.engine),
                          model_id=cfg.get("model_id", "reval-tpu-model"),
                          port=port if port is not None else cfg.get("port", 3000))
    return server
