"""SLO-driven autoscaler: close the loop from router metrics to fleet size.

The serving shell can *survive* overload (admission sheds, the router
fails over) but until now it could not *resize*: a diurnal peak against
a fixed fleet just sheds for hours.  This control loop watches the
router's federated ``GET /metrics`` — p99 TTFT over the last interval,
the shed-rate delta, the queued-tokens gauge — and resizes the fleet
through surfaces that already exist:

- **scale-up** spawns a replica through a :class:`~.supervisor.
  ReplicaPool` (crash-loop supervision, sticky-failed, postmortems all
  retained) and joins it via ``POST /admin/add_replica``.  The new
  replica boots through the PR-10 warm path — AOT executable cache +
  warm-state snapshot replay — surfacing the ``warming`` readiness
  state until it serves;
- **scale-down** takes the graceful path end to end: ``POST
  /admin/drain`` (in-flight forwards finish), wait for the replica's
  in-flight count to reach zero, ``POST /admin/remove_replica``, then
  a supervised terminate (the child's exit-0 drain writes its
  snapshot).

**Flap suppression** is structural, not incidental: an action needs
``up_consecutive``/``down_consecutive`` CONSECUTIVE breach/idle
observations (one boundary-oscillating signal resets the streak every
other tick), every action arms a ``cooldown_s`` window during which
further actions are suppressed and counted (``reval_autoscale_blocked_
total``), and the replica bounds are hard.  The clock is injectable —
the whole policy is unit-testable without sleeping
(:class:`ScalingPolicy` is the pure state machine).

Sticky-failed replicas are never re-targeted: the pool never reuses a
sticky slot, and the reconcile step removes a sticky-failed member from
the router ring (``reason="sticky_failed"``) instead of waiting for
strikes.

Every action is visible three ways: ``autoscale.*`` structured events,
``reval_autoscale_*`` counters in the loop's own registry, and the
router's admin action log (each admin call carries a ``reason`` naming
this autoscaler) — which is what the ``reval_tpu watch`` fleet view
renders.

:class:`LocalReplicaProcess` is the host-only child the mock fleet
drills use: an in-process ``serve --mock`` server wearing a subprocess
costume (``wait``/``poll``/``terminate``/``kill``), so the tier-1
chaos drill exercises the identical supervisor/pool/autoscaler code a
real fleet runs.
"""

from __future__ import annotations

import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass

from ..env import env_float, env_int
from ..obs import metrics as obs_metrics
from ..obs.logging import log_event
from ..obs.metrics import (MetricsRegistry, parse_prometheus,
                           scrape_delta_histogram, snapshot_percentile)

__all__ = ["Autoscaler", "ScalingPolicy", "Signals", "LocalReplicaProcess",
           "mock_replica_factory", "p99_from_scrapes"]


def p99_from_scrapes(samples: dict, prev: dict | None, name: str,
                     q: float = 0.99) -> float:
    """The q-quantile of ``name`` over the observations BETWEEN two
    scrapes — :func:`~reval_tpu.obs.metrics.scrape_delta_histogram`
    (THE cumulative→delta assembly) + the shared percentile estimator.
    0.0 when nothing was observed in the interval (an idle fleet
    breaches no latency SLO)."""
    hist = scrape_delta_histogram(samples, prev, name)
    if hist is None or hist["count"] <= 0:
        return 0.0
    return snapshot_percentile(hist, q)


@dataclass
class Signals:
    """One observation interval's view of the fleet, scraped from the
    router's federated ``/metrics``."""

    ttft_p99_s: float
    shed_delta: float
    queued_tokens: float
    replicas_ready: float
    requests_delta: float


class ScalingPolicy:
    """The pure anti-flap state machine: consecutive-observation
    hysteresis + a post-action cooldown, injectable clock.

    Feed it one ``observe(breach, idle)`` per interval; it returns
    ``(action, indicated, reason)`` — ``action`` is ``"up"``/``"down"``
    when the caller should act NOW, ``indicated`` names an action the
    streaks justify but the cooldown suppressed (the caller counts it
    blocked), and ``reason`` is the human-readable story either way.
    Call :meth:`acted` after executing an action: it arms the cooldown
    and resets both streaks.  Single-owner (the autoscaler loop)."""

    def __init__(self, *, up_consecutive: int = 2, down_consecutive: int = 5,
                 cooldown_s: float | None = None, clock=time.monotonic):
        self.up_consecutive = max(1, int(up_consecutive))
        self.down_consecutive = max(1, int(down_consecutive))
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else env_float("REVAL_TPU_AUTOSCALE_COOLDOWN_S",
                                          15.0))
        self._clock = clock
        self._up_streak = 0
        self._down_streak = 0
        self._last_action: float | None = None

    def observe(self, breach: bool, idle: bool
                ) -> tuple[str | None, str | None, str]:
        if breach:
            self._up_streak += 1
            self._down_streak = 0
        elif idle:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # neither breached nor comfortably idle: the hysteresis
            # deadband — streaks reset, nothing accumulates
            self._up_streak = 0
            self._down_streak = 0
        if self._up_streak >= self.up_consecutive:
            indicated = "up"
            reason = (f"breach sustained {self._up_streak} observations")
        elif self._down_streak >= self.down_consecutive:
            indicated = "down"
            reason = (f"idle sustained {self._down_streak} observations")
        else:
            return None, None, "steady"
        if (self._last_action is not None
                and self._clock() - self._last_action < self.cooldown_s):
            remain = self.cooldown_s - (self._clock() - self._last_action)
            return None, indicated, f"cooldown holds {indicated} " \
                                    f"({remain:.1f}s left)"
        return indicated, indicated, reason

    def acted(self) -> None:
        self._last_action = self._clock()
        self._up_streak = 0
        self._down_streak = 0


class Autoscaler:
    """The control loop (see module docstring).  ``router`` is the
    router's ``host:port``; ``pool`` a :class:`~.supervisor.
    ReplicaPool`.  Scaling signals come ONLY from the router's
    federated ``/metrics`` (``/statusz`` is consulted for membership
    and drain progress — control-plane state, not load).  Single-owner:
    one thread calls :meth:`step` (or :meth:`start` runs it on one)."""

    def __init__(self, router: str, pool, *,
                 ttft_p99_s: float | None = None,
                 queue_high_tokens: float | None = None,
                 shed_tolerance: float = 0.0,
                 interval_s: float | None = None,
                 cooldown_s: float | None = None,
                 min_replicas: int | None = None,
                 max_replicas: int | None = None,
                 up_consecutive: int = 2, down_consecutive: int = 5,
                 down_frac: float = 0.5, drain_wait_s: float = 10.0,
                 clock=time.monotonic, sleep=time.sleep):
        self.router = router if ":" in str(router) else f"127.0.0.1:{router}"
        self.base_url = f"http://{self.router}"
        self.pool = pool
        self.ttft_p99_s = (ttft_p99_s if ttft_p99_s is not None
                           else env_float("REVAL_TPU_AUTOSCALE_TTFT_P99_S",
                                          0.5))
        self.queue_high_tokens = queue_high_tokens
        self.shed_tolerance = float(shed_tolerance)
        self.interval_s = (interval_s if interval_s is not None
                           else env_float("REVAL_TPU_AUTOSCALE_INTERVAL_S",
                                          2.0))
        self.min_replicas = (min_replicas if min_replicas is not None
                             else env_int("REVAL_TPU_AUTOSCALE_MIN_REPLICAS",
                                          1))
        self.max_replicas = (max_replicas if max_replicas is not None
                             else env_int("REVAL_TPU_AUTOSCALE_MAX_REPLICAS",
                                          4))
        self.down_frac = float(down_frac)
        self.drain_wait_s = float(drain_wait_s)
        self.policy = ScalingPolicy(up_consecutive=up_consecutive,
                                    down_consecutive=down_consecutive,
                                    cooldown_s=cooldown_s, clock=clock)
        self._obs = MetricsRegistry()
        self._sleep = sleep
        self._prev_samples: dict | None = None  # unguarded: loop-thread only
        #: chronological action ledger (the drill's assertion surface;
        #: the watch view reads the router admin log instead)
        self.actions: deque = deque(maxlen=128)  # unguarded: loop-thread only
        self._removed_sticky: set = set()   # unguarded: loop-thread only
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- observation --------------------------------------------------------
    def _get_json(self, path: str) -> dict:
        import json

        with urllib.request.urlopen(self.base_url + path, timeout=10) as r:
            return json.loads(r.read())

    def _admin(self, path: str, replica: str, reason: str) -> dict:
        import json

        req = urllib.request.Request(
            self.base_url + path,
            data=json.dumps({"replica": replica,
                             "reason": f"autoscaler: {reason}"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    def observe(self) -> Signals | None:
        """One federated ``/metrics`` scrape folded into interval
        signals; None when the router is unreachable (the step skips —
        a blind interval must not trigger scaling) and on the FIRST
        scrape (lifetime counter totals are history, not load — an
        autoscaler attached to a long-running router must warm up one
        interval before it may act)."""
        try:
            with urllib.request.urlopen(self.base_url + "/metrics",
                                        timeout=10) as r:
                samples = parse_prometheus(r.read().decode())
        except Exception:   # noqa: BLE001 — unreachable router = no signal
            return None
        prev = self._prev_samples
        self._prev_samples = samples
        if prev is None:
            return None

        def delta(name: str) -> float:
            return max(0.0, samples.get(name, 0.0)
                       - (prev or {}).get(name, 0.0))

        return Signals(
            ttft_p99_s=p99_from_scrapes(samples, prev, obs_metrics.TTFT),
            shed_delta=(delta(obs_metrics.ROUTER_SHEDS)
                        + delta("reval_serving_sheds_total")),
            queued_tokens=samples.get(obs_metrics.QUEUED_TOKENS, 0.0),
            replicas_ready=samples.get(obs_metrics.ROUTER_REPLICAS_READY,
                                       0.0),
            requests_delta=delta(obs_metrics.ROUTER_REQUESTS))

    def _members(self) -> list[str]:
        try:
            return list(self._get_json("/statusz")
                        .get("ring", {}).get("members") or [])
        except Exception:   # noqa: BLE001 — unreachable router
            return []

    # -- the loop body ------------------------------------------------------
    def counters(self) -> dict:
        snap = self._obs.snapshot()["counters"]
        return {"up": int(snap.get(obs_metrics.AUTOSCALE_UP, 0)),
                "down": int(snap.get(obs_metrics.AUTOSCALE_DOWN, 0)),
                "blocked": int(snap.get(obs_metrics.AUTOSCALE_BLOCKED, 0))}

    def registry(self) -> MetricsRegistry:
        return self._obs

    def _record(self, action: str, **fields) -> None:
        self.actions.append({"ts": round(time.time(), 3),
                             "action": action, **fields})

    def _blocked(self, indicated: str, why: str) -> None:
        self._obs.counter(obs_metrics.AUTOSCALE_BLOCKED).add(1)
        log_event("autoscale.blocked", indicated=indicated, reason=why)
        self._record("blocked", indicated=indicated, reason=why)

    def _reconcile_sticky(self, members: list[str]) -> None:
        """A sticky-failed pool replica must leave the ring NOW (its
        supervisor stopped respawning; waiting for forward strikes just
        smears errors over live traffic) and is never re-targeted —
        the pool never reuses its slot."""
        for endpoint in self.pool.sticky_failed():
            if endpoint in self._removed_sticky or endpoint not in members:
                self._removed_sticky.add(endpoint)
                continue
            try:
                self._admin("/admin/remove_replica", endpoint,
                            "sticky_failed")
                self._removed_sticky.add(endpoint)
                self._record("remove_sticky", replica=endpoint)
                log_event("autoscale.down", replica=endpoint,
                          reason="sticky_failed", members=len(members) - 1)
            except Exception:   # noqa: BLE001 — e.g. last member: leave it
                pass            # ejected; retried next step

    def step(self) -> str | None:
        """One observe → decide → act round; returns the action taken
        (``"up"``/``"down"``) or None."""
        members = self._members()
        if not members:
            # a blind /statusz interval (router unreachable, transient
            # fault) must not scale OR mark sticky members reconciled —
            # skip the whole round and look again next tick
            return None
        self._reconcile_sticky(members)
        signals = self.observe()
        if signals is None:
            return None
        self._obs.gauge(obs_metrics.AUTOSCALE_REPLICAS).set(len(members))
        breach = (signals.ttft_p99_s > self.ttft_p99_s
                  or signals.shed_delta > self.shed_tolerance
                  or (self.queue_high_tokens is not None
                      and signals.queued_tokens > self.queue_high_tokens))
        idle = (signals.ttft_p99_s <= self.down_frac * self.ttft_p99_s
                and signals.shed_delta == 0
                and (self.queue_high_tokens is None
                     or signals.queued_tokens
                     <= self.down_frac * self.queue_high_tokens))
        action, indicated, reason = self.policy.observe(breach, idle)
        if action is None:
            if indicated is not None:
                self._blocked(indicated, reason)
            return None
        if action == "up":
            if len(members) >= self.max_replicas:
                self._blocked("up", f"at max_replicas={self.max_replicas}")
                return None
            return self._scale_up(signals, reason)
        if len(members) <= self.min_replicas:
            self._blocked("down", f"at min_replicas={self.min_replicas}")
            return None
        return self._scale_down(members, reason)

    def _scale_up(self, signals: Signals, reason: str) -> str | None:
        try:
            endpoint = self.pool.spawn()
        except Exception as exc:    # noqa: BLE001 — a failed spawn must
            # not kill the loop; the breach re-indicates next steps
            self._blocked("up", f"spawn failed: {exc!r}")
            return None
        try:
            self._admin("/admin/add_replica", endpoint, reason)
        except Exception as exc:    # noqa: BLE001 — the join failed: the
            # spawned replica would otherwise serve nothing forever (it
            # is outside the ring, so _pick_victim never sees it) and
            # every later breach would leak another one — stop it NOW
            try:
                self.pool.stop(endpoint)
            except Exception:   # noqa: BLE001 — best-effort teardown
                pass
            self._blocked("up", f"join failed (replica stopped): {exc!r}")
            return None
        self.policy.acted()
        self._obs.counter(obs_metrics.AUTOSCALE_UP).add(1)
        self._record("up", replica=endpoint, reason=reason,
                     ttft_p99_s=round(signals.ttft_p99_s, 4),
                     shed_delta=signals.shed_delta)
        log_event("autoscale.up", replica=endpoint, reason=reason,
                  ttft_p99_s=round(signals.ttft_p99_s, 4),
                  shed_delta=signals.shed_delta,
                  queued_tokens=signals.queued_tokens)
        return "up"

    def _pick_victim(self, members: list[str]) -> str | None:
        """Newest pool-owned member: the autoscaler only stops replicas
        it owns (a seed replica someone else launched is not its to
        kill), and last-in-first-out keeps the longest-warm caches."""
        owned = [ep for ep in self.pool.endpoints() if ep in members]
        return owned[-1] if owned else None

    def _scale_down(self, members: list[str], reason: str) -> str | None:
        victim = self._pick_victim(members)
        if victim is None:
            self._blocked("down", "no pool-owned replica to stop")
            return None
        try:
            self._admin("/admin/drain", victim, reason)
            self._wait_drained(victim)
            self._admin("/admin/remove_replica", victim, reason)
        except Exception as exc:    # noqa: BLE001 — a half-done drain is
            # safe (a draining member takes no forwards); retried later
            self._blocked("down", f"drain/remove failed: {exc!r}")
            return None
        self.pool.stop(victim)
        self.policy.acted()
        self._obs.counter(obs_metrics.AUTOSCALE_DOWN).add(1)
        self._record("down", replica=victim, reason=reason)
        log_event("autoscale.down", replica=victim, reason=reason,
                  members=len(members) - 1)
        return "down"

    def _wait_drained(self, endpoint: str) -> None:
        """Wait (bounded) for the drained replica's in-flight forwards
        to reach zero before it leaves the ring — the graceful-path
        guarantee that a scale-down loses nothing."""
        deadline = time.monotonic() + self.drain_wait_s
        while time.monotonic() < deadline:
            try:
                reps = self._get_json("/statusz").get("replicas") or []
            except Exception:   # noqa: BLE001 — transient statusz fault
                reps = []
            row = next((r for r in reps if r.get("id") == endpoint), None)
            if row is None or not row.get("inflight"):
                return
            self._sleep(0.05)

    # -- lifecycle ----------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as exc:    # noqa: BLE001 — the loop survives
                # any single step fault (unreachable router, pool race)
                log_event("autoscale.blocked", level="warning",
                          indicated=None, reason=f"step failed: {exc!r}")

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="autoscaler")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, 2 * self.interval_s))
            self._thread = None


# -- the host-only mock child ------------------------------------------------

class LocalReplicaProcess:
    """An in-process ``serve --mock`` replica wearing a subprocess
    costume — the :class:`~.supervisor.ReplicaPool` child for host-only
    fleets.  ``terminate()`` is the graceful drain (exit 0: the
    supervisor stays stopped, the session lands its warm-state
    snapshot); ``kill()`` is the chaos hard-kill (exit 1: the listener
    dies under its in-flight sockets, the session driver is orphaned
    like a real ``kill -9`` — the supervisor respawns)."""

    def __init__(self, cfg: dict, port: int = 0):
        from .server import serve_config

        # unguarded: built once here, read-only thereafter
        self.cfg = dict(cfg)
        self.server = serve_config(self.cfg, port=port).start()
        self.endpoint = f"127.0.0.1:{self.server.port}"
        self._exit = threading.Event()
        self._exit_lock = threading.Lock()
        self.returncode: int | None = None  # guarded-by: _exit_lock (writes)

    def wait(self) -> int:
        self._exit.wait()
        return self.returncode      # type: ignore[return-value]

    def poll(self) -> int | None:
        return self.returncode if self._exit.is_set() else None

    def _claim(self, rc: int) -> bool:
        """First caller wins the exit; the port teardown then happens
        BEFORE ``_exit`` publishes (the supervisor respawns the moment
        ``wait()`` returns — the new child must find the port free)."""
        with self._exit_lock:
            if self.returncode is not None:
                return False
            self.returncode = rc
            return True

    def terminate(self) -> None:
        if self._claim(0):
            self.server.shutdown()
            self._exit.set()

    def kill(self) -> None:
        if self._claim(1):
            # a crash, not a drain: the listener dies under its sockets;
            # the session driver thread is left running (daemon), exactly
            # like a kill -9 leaves no one to clean up
            self.server._httpd.shutdown()
            self.server._httpd.server_close()
            self._exit.set()


def mock_replica_factory(base_cfg: dict | None = None,
                         per_slot: dict | None = None):
    """A :class:`~.supervisor.ReplicaPool` factory over
    :class:`LocalReplicaProcess`: ``base_cfg`` overlays the mock serve
    config, ``per_slot[slot]`` overlays per pool slot (the drill gives
    slot 1 its snapshot path), and a respawn re-binds the previous
    endpoint's port so the ring membership stays stable."""
    def factory(slot: int, endpoint_hint: str | None) -> LocalReplicaProcess:
        cfg = {"mock": True, "mock_echo": True}
        cfg.update(base_cfg or {})
        cfg.update((per_slot or {}).get(slot, {}))
        port = (int(endpoint_hint.rsplit(":", 1)[1]) if endpoint_hint
                else 0)
        return LocalReplicaProcess(cfg, port=port)
    return factory
