"""Cross-request continuous batching for the paged engine.

The reference's server rides vLLM's AsyncLLMEngine: concurrent HTTP
clients (reference batch_run.py:20-28 launches four at once) are admitted
into ONE live decode batch, so a new request starts prefilling while
earlier ones are mid-decode.  Round-2's server serialised `generate()`
calls instead — each POST batched only with itself (VERDICT round 2,
missing item 2).  This module closes that gap.

Design: the engine stays single-owner.  A dedicated driver thread owns
the `PagedTPUEngine` and repeatedly runs `_drive_tick` — one admission +
prefill + decode-chunk round.  HTTP handler threads never touch the
engine; `submit()` tokenises in the caller, enqueues the request, and
blocks on a `_Pending` handle.  Between any two decode chunks the driver
drains the inbox and hands new sequences to the C++ scheduler
(runtime/native/runtime.cpp FCFS queue), which admits them as slots free
up — exactly vLLM's engine-step loop, with the scheduler already built
for incremental admission.

Per-request sampling state (temperature, stop strings, token budget)
lives on the request (`_Request.temp` / `.scanner` / `.max_new`), so one
decode chunk can mix greedy and sampled requests: `sample_token` takes a
per-slot temperature vector.

Prefix reuse composes across HTTP requests: submissions enter the engine
through `submit_request`, which consults the engine's PERSISTENT radix
prefix cache (inference/tpu/prefix_cache.py) — the cache outlives any
one request, so a client re-sending the same few-shot template (the
DREval serve shape) prefills only its suffix even with one prompt per
POST.  Cached pages are refcounted pool pages; eviction under load is
LRU over rider-free nodes, so a busy session cannot be starved by its
own cache.
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass, field

__all__ = ["ContinuousSession", "MultiSession"]


class _Pending:
    """Caller-side handle for one submitted prompt batch."""

    def __init__(self, n: int):
        self.texts: list[str | None] = [None] * n
        self._remaining = n
        self._event = threading.Event()
        self._error: str | None = None
        self._cb_lock = threading.Lock()
        self._callbacks: list = []
        self._fired = False

    def _fire(self) -> None:
        """Resolve the handle (success or error) exactly once.  Done-
        callbacks run BEFORE the event wakes waiters, so anything a
        waiter observes after ``result()`` (e.g. MultiSession's load
        counters) already reflects the release."""
        with self._cb_lock:
            if self._fired:
                return
            self._fired = True
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb()
        self._event.set()

    def _add_done_callback(self, cb) -> None:
        with self._cb_lock:
            if not self._fired:
                self._callbacks.append(cb)
                return
        cb()

    def result(self, timeout: float | None = None) -> list[str]:
        """Block until every prompt in the submission finished."""
        if not self._event.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self._error is not None:
            raise RuntimeError(self._error)
        return self.texts  # type: ignore[return-value]

    def done(self) -> bool:
        return self._event.is_set()


def _generate_fn_for(submitter):
    """EngineServer ``generate_fn`` over any ``submit(...) -> _Pending``
    owner (single session or replica set) — pass ``serialize=False``."""
    def generate(prompts, *, max_tokens, temperature, stop,
                 top_k=0, top_p=1.0, on_progress=None):
        return submitter.submit(prompts, max_new_tokens=max_tokens,
                                temperature=temperature, stop=stop,
                                top_k=top_k, top_p=top_p,
                                on_progress=on_progress).result()
    return generate


@dataclass
class _Submission:
    prompts: list[str]
    max_new: int
    temperature: float
    stop: list[str]
    on_progress: object
    top_k: int = 0
    top_p: float = 1.0
    pending: _Pending = field(init=False)

    def __post_init__(self):
        self.pending = _Pending(len(self.prompts))


class ContinuousSession:
    """Drive a ``PagedTPUEngine`` from a background thread, admitting
    concurrently submitted requests into the live decode batch.

    While a session is attached the engine is owned by the driver thread —
    do not call ``engine.generate()`` alongside it.

    ``autostart=False`` lets tests enqueue several submissions first and
    then start the driver, making the fused-admission path deterministic.
    """

    def __init__(self, engine, autostart: bool = True):
        self.engine = engine
        self._inbox: queue.Queue = queue.Queue()
        self._closed = threading.Event()
        # serialises the closed-check against the inbox put: without it a
        # submit() could check "open", lose the CPU, and land its put after
        # close()'s sentinel let the driver exit — a handle nobody ever
        # resolves (and a server handler blocked forever on result())
        self._submit_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        if autostart:
            self.start()

    # -- caller side -------------------------------------------------------
    def submit(self, prompts: list[str], *, max_new_tokens: int = 256,
               temperature: float = 0.0, stop: list[str] | None = None,
               top_k: int = 0, top_p: float = 1.0,
               on_progress=None) -> _Pending:
        """Enqueue a prompt batch; returns a handle whose ``result()``
        blocks until all its prompts finish.  ``on_progress(index, text)``
        streams finalised-so-far text at decode-chunk granularity (same
        contract as ``PagedTPUEngine.generate``)."""
        sub = _Submission(list(prompts), max_new_tokens, float(temperature),
                          list(stop or []), on_progress,
                          top_k=int(top_k), top_p=float(top_p))
        if not sub.prompts:
            sub.pending._fire()
            return sub.pending
        with self._submit_lock:
            if self._closed.is_set():
                raise RuntimeError("session is closed")
            self._inbox.put(sub)
        return sub.pending

    def generate_fn(self):
        """A ``generate_fn`` for :class:`EngineServer` — blocking per
        call, but concurrent calls share the live batch, so the server
        must NOT serialise them (pass ``serialize=False``)."""
        return _generate_fn_for(self)

    # -- driver side -------------------------------------------------------
    def start(self) -> "ContinuousSession":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="paged-session-driver")
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop accepting work, finish in-flight requests, join the
        driver."""
        with self._submit_lock:
            self._closed.set()
            self._inbox.put(None)       # wake a blocked driver
        if self._thread is not None:
            self._thread.join(timeout=120)
            if self._thread.is_alive():
                # A wedged device dispatch (or a very long healthy drain)
                # can outlive the join timeout.  The driver still owns
                # the engine, so keep the thread reference — nulling it
                # would let callers tear down/reuse the engine while the
                # driver is live.  No raise: close() runs from __exit__
                # and MultiSession.close(), where an exception would mask
                # in-flight errors or strand sibling replicas un-closed.
                # logging, not warnings.warn: the default warning filter
                # dedups per call site, which would hide a second wedged
                # replica in the same process.
                logging.getLogger(__name__).warning(
                    "ContinuousSession %#x driver did not exit within "
                    "120s; engine is still owned by the driver thread "
                    "(call close() again to re-join)", id(self))
                return
            self._thread = None

    def __enter__(self) -> "ContinuousSession":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _run(self) -> None:
        eng = self.engine
        reqs: dict[int, object] = {}
        # seq_id -> (submission, position of this prompt in it)
        origin: dict[int, tuple[_Submission, int]] = {}
        st = eng.new_drive_state()

        def drain(block: bool) -> None:
            while True:
                try:
                    sub = self._inbox.get(timeout=0.2 if block else 0)
                except queue.Empty:
                    return
                if sub is None:
                    return
                try:
                    self._enqueue(sub, reqs, origin)
                except Exception as exc:   # oversized request etc.
                    # roll back any of THIS submission's already-queued
                    # sequences so they don't decode into a dead handle
                    self._fail(sub, str(exc), reqs, origin)
                    sub.pending._error = str(exc)
                    sub.pending._fire()
                if block:
                    return                  # got work; go run a tick

        while True:
            if not reqs:
                if self._closed.is_set() and self._inbox.empty():
                    return
                drain(block=True)
                continue
            drain(block=False)
            try:
                eng._drive_tick(reqs, st)
            except RuntimeError as exc:
                if "deadlock" in str(exc):
                    # nothing running + nothing admissible: the FCFS head
                    # cannot ever fit (e.g. needs more pages than the
                    # pool).  Fail ONLY its submission — the requests
                    # behind it are admissible once it leaves the queue.
                    head = min((s for s, r in reqs.items() if not r.done),
                               default=None)
                    if head is not None:
                        self._fail(origin[head][0], str(exc), reqs, origin)
                        st.dirty = True
                        continue
                self._fail(None, str(exc), reqs, origin)
                st = eng.new_drive_state()
                continue
            except Exception as exc:
                # device fault: fail every in-flight submission, release
                # their sequences, start clean
                self._fail(None, str(exc), reqs, origin)
                st = eng.new_drive_state()
                continue
            for seq_id in [s for s, r in reqs.items() if r.done]:
                req = reqs.pop(seq_id)
                sub, pos = origin.pop(seq_id)
                from ..inference.tpu.engine import finalize_text

                sub.pending.texts[pos] = finalize_text(
                    eng.tokenizer, req.generated, sub.stop)
                sub.pending._remaining -= 1
                eng.stats.prompts += 1
                if sub.pending._remaining == 0:
                    sub.pending._fire()

    def _fail(self, target: _Submission | None, msg: str, reqs: dict,
              origin: dict) -> None:
        """Error ``target``'s pending handle (or every submission when
        ``target`` is None), releasing its scheduler sequences."""
        eng = self.engine
        for seq_id in list(reqs):
            sub, _ = origin[seq_id]
            if target is not None and sub is not target:
                continue
            req = reqs.pop(seq_id)
            origin.pop(seq_id)
            if not req.done:
                try:
                    eng.release_request(seq_id, req)
                except Exception:
                    pass
            if not sub.pending.done():
                sub.pending._error = msg
                sub.pending._fire()

    def _enqueue(self, sub: _Submission, reqs: dict,
                 origin: dict) -> None:
        """Tokenise + hand a submission's prompts to the native scheduler
        (driver thread only — the runtime is single-owner)."""
        from ..inference.tpu.engine import StopScanner, finalize_text
        from ..inference.tpu.paged_engine import _Request

        eng = self.engine
        keys = eng.request_keys(len(sub.prompts))
        for pos, prompt in enumerate(sub.prompts):
            ids = eng.encode_clipped(prompt, sub.max_new)
            notify = None
            if sub.on_progress is not None:
                def notify(req, _sub=sub, _pos=pos):
                    _sub.on_progress(_pos, finalize_text(
                        eng.tokenizer, req.generated, _sub.stop))
            # ride the engine's persistent prefix cache: a template seen
            # on ANY earlier request (this submission, a previous POST, a
            # fleet call before the session attached) prefills only once
            seq_id, node = eng.submit_request(ids, sub.max_new)
            reqs[seq_id] = _Request(
                index=pos, ids=ids, max_new=sub.max_new,
                scanner=StopScanner(eng.tokenizer, sub.stop),
                temp=sub.temperature, top_k=sub.top_k, top_p=sub.top_p,
                notify=notify, key=keys[pos], node=node)
            origin[seq_id] = (sub, pos)


class MultiSession:
    """Cross-request continuous batching over a replica set
    (``DataParallelPagedEngine``): one :class:`ContinuousSession` per
    replica, each with its own driver thread on its own device group, and
    least-loaded routing of incoming submissions — the serve-mode
    topology for the v5e-8 flagship shape (dp=2 × tp=4), where a single
    session would leave half the chips idle.

    Load feedback is by outstanding prompt count; a submission's weight
    releases when its handle resolves (the ``_Pending`` done-callback),
    so a replica stuck on long generations stops receiving work — the
    serve-side analog of the in-process work-stealing queue
    (inference/tpu/dp_paged.py)."""

    def __init__(self, engines, autostart: bool = True):
        self.sessions = [ContinuousSession(e, autostart=autostart)
                         for e in engines]
        self._load = [0] * len(self.sessions)
        self._lock = threading.Lock()

    def start(self) -> "MultiSession":
        for s in self.sessions:
            s.start()
        return self

    def submit(self, prompts: list[str], *, max_new_tokens: int = 256,
               temperature: float = 0.0, stop: list[str] | None = None,
               top_k: int = 0, top_p: float = 1.0,
               on_progress=None) -> _Pending:
        n = len(prompts)
        with self._lock:
            i = min(range(len(self.sessions)), key=self._load.__getitem__)
            self._load[i] += n

        def release() -> None:
            with self._lock:
                self._load[i] -= n

        try:
            pending = self.sessions[i].submit(
                prompts, max_new_tokens=max_new_tokens,
                temperature=temperature, stop=stop, top_k=top_k, top_p=top_p,
                on_progress=on_progress)
        except Exception:
            release()                   # closed session etc.: no leak
            raise
        pending._add_done_callback(release)
        return pending

    def generate_fn(self):
        """See :meth:`ContinuousSession.generate_fn` — pass
        ``serialize=False`` to the server."""
        return _generate_fn_for(self)

    def close(self) -> None:
        for s in self.sessions:
            s.close()

    def __enter__(self) -> "MultiSession":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
