"""Cross-request continuous batching for the paged engine.

The reference's server rides vLLM's AsyncLLMEngine: concurrent HTTP
clients (reference batch_run.py:20-28 launches four at once) are admitted
into ONE live decode batch, so a new request starts prefilling while
earlier ones are mid-decode.  Round-2's server serialised `generate()`
calls instead — each POST batched only with itself (VERDICT round 2,
missing item 2).  This module closes that gap.

Design: the engine stays single-owner.  A dedicated driver thread owns
the `PagedTPUEngine` and repeatedly runs `_drive_tick` — one admission +
prefill + decode-chunk round.  HTTP handler threads never touch the
engine; `submit()` tokenises in the caller, enqueues the request, and
blocks on a `_Pending` handle.  Between any two decode chunks the driver
drains the inbox and hands new sequences to the C++ scheduler
(runtime/native/runtime.cpp FCFS queue), which admits them as slots free
up — exactly vLLM's engine-step loop, with the scheduler already built
for incremental admission.

Per-request sampling state (temperature, stop strings, token budget)
lives on the request (`_Request.temp` / `.scanner` / `.max_new`), so one
decode chunk can mix greedy and sampled requests: `sample_token` takes a
per-slot temperature vector.

Prefix reuse composes across HTTP requests: submissions enter the engine
through `submit_request`, which consults the engine's PERSISTENT radix
prefix cache (inference/tpu/prefix_cache.py) — the cache outlives any
one request, so a client re-sending the same few-shot template (the
DREval serve shape) prefills only its suffix even with one prompt per
POST.  Cached pages are refcounted pool pages; eviction under load is
LRU over rider-free nodes, so a busy session cannot be starved by its
own cache.

Lifecycle hardening (on top of the batching):

- **Admission control.** The pending queue is bounded in *prompt tokens*
  (`max_queued_tokens`); a submission that would push it past the
  watermark is shed with a typed :class:`~.errors.Overloaded` (HTTP 429 +
  Retry-After at the server) instead of growing an unbounded backlog.  A
  submission arriving at an EMPTY queue always admits — a single batch
  larger than the watermark must make progress, not 429 forever.
- **Per-request deadlines.** `submit(..., deadline_s=...)` carries the
  client's remaining budget; the driver cancels expired submissions
  between steps via the engine's `release_request` lifecycle (pages and
  prefix pins freed — the slot goes to a live request) and fails the
  handle with :class:`~.errors.DeadlineExceeded`.
- **No-progress watchdog.** The driver (and the engine's own decode loop)
  stamp a heartbeat every step; a watchdog thread detects a stamp older
  than `watchdog_s` while work is in flight, flips readiness, and fails
  every pending handle with :class:`~.errors.EngineWedged` — a wedged
  device never strands callers in `result()`.  Wedged is sticky: the
  fleet's retry/bisection/resume machinery (resilience/) takes over and a
  fresh process replaces this one.
- **Readiness.** :meth:`ContinuousSession.readiness` condenses all of the
  above (driver alive, heartbeat fresh, queue below watermark, not
  draining/wedged) for the server's `/readyz`; `MultiSession` routes
  around unready replicas.
- **Chaos hook.** `step_chaos` (a
  :class:`~reval_tpu.resilience.EngineStepChaos`) injects a stalled step
  or mid-batch exception between decode steps, so every path above is
  testable in the fast tier without a TPU.
- **Postmortems.** Watchdog trips, driver faults, and deadline storms
  dump a crash bundle (flight-record runway, metrics snapshot, in-flight
  request table, span tail, recent logs — obs/flightrec.py) to
  ``postmortem_dir``; ``GET /debugz`` serves the same document live.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field

from ..env import env_float, env_int, env_str
from ..obs.flightrec import PostmortemWriter, build_bundle
from ..obs.logging import log_event
from .snapshot import read_snapshot, write_snapshot
from .errors import (DeadlineExceeded, Draining, EngineFailure, EngineWedged,
                     Overloaded, ServingError)

__all__ = ["ContinuousSession", "MultiSession"]

#: deadline expiries in ONE driver sweep that count as a "storm" and
#: trigger a postmortem bundle (env ``REVAL_TPU_DEADLINE_STORM``) — one
#: slow request missing its budget is business as usual; a whole batch
#: expiring together means the engine, not the request, is the story
DEADLINE_STORM_N = env_int("REVAL_TPU_DEADLINE_STORM", 3)


class _Pending:
    """Caller-side handle for one submitted prompt batch."""

    def __init__(self, n: int):
        # unguarded: single writer (the driver) fills slots; readers wait
        # on the event, which publishes the writes (happens-before)
        self.texts: list[str | None] = [None] * n
        self._remaining = n
        self._event = threading.Event()
        self._error: str | None = None
        self._exc: ServingError | None = None
        self._cb_lock = threading.Lock()
        self._callbacks: list = []      # guarded-by: _cb_lock
        self._fired = False             # guarded-by: _cb_lock

    def _fire(self) -> None:
        """Resolve the handle (success or error) exactly once.  Done-
        callbacks run BEFORE the event wakes waiters, so anything a
        waiter observes after ``result()`` (e.g. MultiSession's load
        counters) already reflects the release."""
        with self._cb_lock:
            if self._fired:
                return
            self._fired = True
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb()
        self._event.set()

    def _add_done_callback(self, cb) -> None:
        with self._cb_lock:
            if not self._fired:
                self._callbacks.append(cb)
                return
        cb()

    def result(self, timeout: float | None = None) -> list[str]:
        """Block until every prompt in the submission finished."""
        if not self._event.wait(timeout):
            raise TimeoutError("generation did not finish in time")
        if self._exc is not None:
            raise self._exc
        if self._error is not None:
            # typed wrapper for an UNTYPED engine/driver fault: still a
            # RuntimeError for old callers, but the HTTP boundary sees a
            # taxonomy member whose message it knows is NOT wire-safe
            raise EngineFailure(self._error)
        return self.texts  # type: ignore[return-value]

    def done(self) -> bool:
        return self._event.is_set()


def _generate_fn_for(submitter):
    """EngineServer ``generate_fn`` over any ``submit(...) -> _Pending``
    owner (single session or replica set) — pass ``serialize=False``."""
    def generate(prompts, *, max_tokens, temperature, stop,
                 top_k=0, top_p=1.0, on_progress=None, deadline_s=None,
                 request_id=None, grammar=None, on_receipt=None):
        return submitter.submit(prompts, max_new_tokens=max_tokens,
                                temperature=temperature, stop=stop,
                                top_k=top_k, top_p=top_p,
                                on_progress=on_progress,
                                deadline_s=deadline_s,
                                request_id=request_id,
                                grammar=grammar,
                                on_receipt=on_receipt).result()
    return generate


@dataclass(eq=False)           # identity hash: submissions live in sets
class _Submission:
    prompts: list[str]
    max_new: int
    temperature: float
    stop: list[str]
    on_progress: object
    top_k: int = 0
    top_p: float = 1.0
    #: grammar name constraining every prompt of this submission (the
    #: wire ``grammar`` field; None = unconstrained)
    grammar: str | None = None
    #: the wire request id (``X-Request-Id``) this submission serves —
    #: span tracing and server/client logs name requests by it
    request_id: str | None = None
    #: ``on_receipt(receipt)`` fires once, from the driver, when the
    #: LAST prompt retires — the reproducibility receipt
    #: (obs/receipts.py) covering every prompt of this submission
    on_receipt: object = None
    pending: _Pending = field(init=False)
    #: per-prompt raw-id-stream digests, filled at retire in prompt
    #: order (obs/receipts.py token_digest) — single writer: the driver
    digests: list = field(init=False, default_factory=list)
    #: raw emitted tokens across the submission (receipt ``n_tokens``)
    gen_tokens: int = field(init=False, default=0)
    #: token ids per prompt, encoded in the SUBMITTING thread (admission
    #: control needs the counts before the driver ever sees this)
    encoded: list = field(init=False, default_factory=list)
    tokens: int = field(init=False, default=0)
    #: monotonic-clock expiry (None = no deadline)
    deadline: float | None = field(init=False, default=None)
    #: perf_counter stamp at submit: latency histograms and spans count
    #: inbox wait from HERE, not from driver pickup
    t_submit: float = field(init=False, default=0.0)

    def __post_init__(self):
        self.pending = _Pending(len(self.prompts))
        self.digests = [None] * len(self.prompts)
        self.t_submit = time.perf_counter()


class ContinuousSession:
    """Drive a ``PagedTPUEngine`` from a background thread, admitting
    concurrently submitted requests into the live decode batch.

    While a session is attached the engine is owned by the driver thread —
    do not call ``engine.generate()`` alongside it.

    ``autostart=False`` lets tests enqueue several submissions first and
    then start the driver, making the fused-admission path deterministic.

    ``max_queued_tokens``: admission-control watermark in pending prompt
    tokens (default ``REVAL_TPU_MAX_QUEUED_TOKENS`` or 4 × slots ×
    max_seq_len).  ``watchdog_s``: no-progress threshold (default
    ``REVAL_TPU_WATCHDOG_S`` or 120 s — generously above a worst-case
    first-request jit compile; 0 disables).  ``step_chaos``: an
    :class:`~reval_tpu.resilience.EngineStepChaos` fault injector run
    before every engine step.
    """

    def __init__(self, engine, autostart: bool = True, *,
                 max_queued_tokens: int | None = None,
                 watchdog_s: float | None = None, step_chaos=None,
                 tracer=None, postmortem_dir: str | None = None,
                 snapshot_path: str | None = None,
                 snapshot_fallback: str | None = None):
        self.engine = engine
        # -- reproducibility receipts (obs/receipts.py) ----------------------
        #: the engine-level config fingerprint every response's receipt
        #: carries (None when the engine predates receipt_context);
        #: snapshotted once — the engine's context is build-time stable
        self.receipt_fingerprint: str | None = None
        #: this serving engine's provenance id (router failover makes
        #: "which replica actually answered" a real question)
        self.engine_id: str | None = None
        ctx_fn = getattr(engine, "receipt_context", None)
        if callable(ctx_fn):
            import uuid

            from ..obs import receipts

            self.receipt_fingerprint = receipts.config_fingerprint(ctx_fn())
            self.engine_id = f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        # -- warm restarts (serving/snapshot.py) -----------------------------
        #: where the graceful drain lands its warm-state snapshot and
        #: boot looks for the previous process's (default env
        #: REVAL_TPU_SNAPSHOT_PATH; empty disables the whole feature)
        self.snapshot_path = (snapshot_path
                              if snapshot_path is not None
                              else (env_str("REVAL_TPU_SNAPSHOT_PATH", "")
                                    or None))
        #: autoscaler warm scale-ups: a replica with no snapshot of its
        #: own boots from a SIBLING's (token tree + v2 disk-tier pages)
        #: — read-only, never written to
        self.snapshot_fallback = snapshot_fallback or None
        self._t_boot = time.perf_counter()
        self._snapshot_once = threading.Event()     # drain writes ONE snapshot
        #: boot is replaying a warm-state snapshot through prefill:
        #: /readyz answers 503 "warming" (+ Retry-After, distinct from
        #: draining) until the driver finishes the restore
        self._warming = threading.Event()
        if hasattr(engine, "rewarm") and (
                (self.snapshot_path and os.path.exists(self.snapshot_path))
                or (self.snapshot_fallback
                    and os.path.exists(self.snapshot_fallback))):
            self._warming.set()
        #: crash-dump sink: watchdog trips, driver faults, and deadline
        #: storms dump a bundle here (obs/flightrec.py; default
        #: REVAL_TPU_POSTMORTEM_DIR or tpu_watch/)
        self._postmortem = PostmortemWriter(postmortem_dir)
        #: the driver's live request/origin tables, published by _run so
        #: a postmortem (or /debugz) can read the in-flight lifecycle
        #: stamps — read-only, racy by design (diagnostics, not control)
        self._driver_reqs: dict = {}        # unguarded: racy diagnostics reads by design
        self._driver_origin: dict = {}      # unguarded: racy diagnostics reads by design
        #: optional :class:`~reval_tpu.obs.trace.Tracer` — one span tree
        #: per (request id, prompt) at completion; None = zero cost
        self._tracer = tracer
        self._inbox: queue.Queue = queue.Queue()
        self._closed = threading.Event()
        self._wedged = threading.Event()
        # serialises the closed-check against the inbox put: without it a
        # submit() could check "open", lose the CPU, and land its put after
        # close()'s sentinel let the driver exit — a handle nobody ever
        # resolves (and a server handler blocked forever on result())
        self._submit_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._step_chaos = step_chaos
        # -- admission control ---------------------------------------------
        max_seq = (getattr(engine, "max_pages_per_seq", 64)
                   * getattr(engine, "page_size", 128))
        if max_queued_tokens is None:
            max_queued_tokens = (
                env_int("REVAL_TPU_MAX_QUEUED_TOKENS", 0)
                or 4 * getattr(engine, "max_slots", 8) * max_seq)
        self.max_queued_tokens = int(max_queued_tokens)
        self._acct_lock = threading.Lock()
        self._queued_tokens = 0             # guarded-by: _acct_lock
        #: submissions whose handle has not resolved yet — what the
        #: watchdog fails on a trip (the driver's reqs/origin are locals)
        self._inflight: set[_Submission] = set()    # guarded-by: _acct_lock
        # -- watchdog -------------------------------------------------------
        if watchdog_s is None:
            watchdog_s = env_float("REVAL_TPU_WATCHDOG_S", 120.0)
        self.watchdog_s = max(0.0, float(watchdog_s))
        # unguarded: one writer (the driver) stamps a monotonic float;
        # the watchdog's read tolerates any stale-but-well-formed value
        self._heartbeat = time.monotonic()
        self._watch_stop = threading.Event()
        self._watch_thread: threading.Thread | None = None
        if autostart:
            self.start()

    # -- caller side -------------------------------------------------------
    def submit(self, prompts: list[str], *, max_new_tokens: int = 256,
               temperature: float = 0.0, stop: list[str] | None = None,
               top_k: int = 0, top_p: float = 1.0,
               on_progress=None, deadline_s: float | None = None,
               request_id: str | None = None,
               grammar: str | None = None, on_receipt=None) -> _Pending:
        """Enqueue a prompt batch; returns a handle whose ``result()``
        blocks until all its prompts finish.  ``on_progress(index, text)``
        streams finalised-so-far text at decode-chunk granularity (same
        contract as ``PagedTPUEngine.generate``).  ``deadline_s`` is the
        caller's remaining budget: past it the driver cancels the
        submission engine-side and the handle raises
        :class:`DeadlineExceeded`.  ``request_id`` is the wire id the
        server received (``X-Request-Id``): spans and logs carry it.
        ``grammar`` constrains every prompt of the submission to the
        named answer shape (reval_tpu/decoding/).

        Raises :class:`Overloaded` when the pending-token queue is above
        the watermark, :class:`Draining` after :meth:`close`,
        :class:`EngineWedged` after a watchdog trip, and ``ValueError``
        for a token budget no prompt could ever fit OR an unknown
        grammar name (client errors — the server maps both to 400)."""
        if grammar:
            from ..decoding import validate_grammar

            # fail unknown names HERE, in the caller's thread (a 400),
            # never in the driver loop (which would fail the handle as a
            # 500-shaped engine fault)
            validate_grammar(grammar)
        sub = _Submission(list(prompts), max_new_tokens, float(temperature),
                          list(stop or []), on_progress,
                          top_k=int(top_k), top_p=float(top_p),
                          grammar=grammar, request_id=request_id,
                          on_receipt=on_receipt)
        if not sub.prompts:
            sub.pending._fire()
            return sub.pending
        if self._wedged.is_set():
            raise EngineWedged("engine watchdog tripped; session is not serving")
        # tokenise in the caller's thread: token-denominated admission
        # control needs the counts before the driver sees the submission,
        # and it keeps tokenisation off the driver's critical path
        sub.encoded = [self.engine.encode_clipped(p, max_new_tokens)
                       for p in sub.prompts]
        sub.tokens = sum(len(ids) for ids in sub.encoded)
        if deadline_s is not None:
            sub.deadline = time.monotonic() + float(deadline_s)
        with self._acct_lock:
            # shed only when a backlog exists: a lone submission bigger
            # than the watermark must run (bounded per-sequence anyway),
            # not bounce forever
            if (self._queued_tokens
                    and self._queued_tokens + sub.tokens > self.max_queued_tokens):
                self.engine.stats.sheds += 1
                raise Overloaded(
                    f"pending queue full: {self._queued_tokens} prompt tokens "
                    f"queued (watermark {self.max_queued_tokens})",
                    retry_after=self._retry_after_locked())
            self._queued_tokens += sub.tokens
            self._inflight.add(sub)
            self._set_queue_gauge()
        sub.pending._add_done_callback(lambda: self._release_acct(sub))
        with self._submit_lock:
            if self._closed.is_set():
                self._release_acct(sub)
                raise Draining("session is closed")
            if self._wedged.is_set():
                self._release_acct(sub)
                raise EngineWedged(
                    "engine watchdog tripped; session is not serving")
            self._inbox.put(sub)
        return sub.pending

    def _retry_after_locked(self) -> float:   # lock-held: _acct_lock
        """Retry-After hint under ``_acct_lock``: ~0.5 s per 2k queued
        tokens — rough, but it scales the fleet's backoff with the
        backlog instead of hammering a saturated server."""
        return round(min(30.0, max(0.5, self._queued_tokens / 4096.0)), 2)

    def _release_acct(self, sub: _Submission) -> None:
        with self._acct_lock:
            if sub in self._inflight:
                self._inflight.discard(sub)
                self._queued_tokens -= sub.tokens
                self._set_queue_gauge()

    def _set_queue_gauge(self) -> None:       # lock-held: _acct_lock
        """Mirror the admission backlog into the obs registry (called
        under ``_acct_lock``) so ``/metrics`` and ``/statusz`` expose
        the same number ``/readyz`` decides on."""
        from ..obs import metrics as obs_metrics

        self.engine.stats.registry.gauge(
            obs_metrics.QUEUED_TOKENS).set(self._queued_tokens)

    def generate_fn(self):
        """A ``generate_fn`` for :class:`EngineServer` — blocking per
        call, but concurrent calls share the live batch, so the server
        must NOT serialise them (pass ``serialize=False``)."""
        return _generate_fn_for(self)

    # -- readiness ---------------------------------------------------------
    def _accepting(self) -> bool:
        return not (self._wedged.is_set() or self._closed.is_set())

    def readiness(self) -> dict:
        """Readiness snapshot for ``/readyz``: engine loaded (a session
        implies it), driver alive, heartbeat fresh, queue below the
        watermark, not warming from a snapshot, not draining or
        wedged.  ``warming`` is a DISTINCT not-ready state (the boot
        replaying a warm-state snapshot through prefill): the server
        answers 503 ``warming`` + Retry-After, which the client
        handshake and the router health poller both keep polling
        through — alive, just not serving yet."""
        hb = max(self._heartbeat, getattr(self.engine, "heartbeat", 0.0))
        hb_age = time.monotonic() - hb
        alive = self._thread is not None and self._thread.is_alive()
        with self._acct_lock:
            queued = self._queued_tokens
            busy = bool(self._inflight)
        stale = bool(busy and self.watchdog_s and hb_age > self.watchdog_s)
        warming = self._warming.is_set()
        ready = (alive and self._accepting() and not stale and not warming
                 and queued < self.max_queued_tokens)
        return {"ready": ready, "driver_alive": alive,
                "wedged": self._wedged.is_set(),
                "warming": warming,
                "draining": self._closed.is_set(),
                "heartbeat_age_s": round(hb_age, 3),
                "queued_tokens": queued,
                "max_queued_tokens": self.max_queued_tokens,
                # receipt provenance rides readiness so it reaches the
                # router's health poll (and /statusz) with zero extra
                # endpoints — fingerprint-pinned placement keys on it
                "fingerprint": self.receipt_fingerprint,
                "engine_id": self.engine_id}

    def engine_stats(self) -> list:
        return [self.engine.stats]

    # -- postmortems -------------------------------------------------------
    def postmortem_bundle(self, reason: str, error: str | None = None,
                          *, envelope: bool = True) -> dict:
        """One self-contained crash-dump document: the flight-record
        runway, the metrics snapshot, readiness, the in-flight request
        table with lifecycle stamps, and the span-tree tail.  Served
        live by ``GET /debugz`` and written to disk on watchdog trips,
        driver faults, deadline storms, SIGUSR1, and SIGTERM drains.

        Reads racy driver state by design (diagnostics, not control);
        every section is assembled defensively so a bundle can always be
        produced, even mid-fault."""
        eng = self.engine
        sections: dict = {"error": error}
        now = time.perf_counter()
        mono = time.monotonic()
        try:
            fr = getattr(eng, "flightrec", None)
            if fr is not None:
                sections["flight"] = fr.snapshot()
                sections["flight_dropped"] = max(0, fr.total - fr.capacity)
        except Exception:
            sections["flight"] = None
        try:
            sections["metrics"] = eng.stats.registry.snapshot()
        except Exception:
            sections["metrics"] = None
        try:
            sections["readiness"] = self.readiness()
        except Exception:
            sections["readiness"] = None
        try:
            with self._acct_lock:
                inflight = list(self._inflight)
            sections["inflight"] = [
                {"request_id": sub.request_id, "prompts": len(sub.prompts),
                 "tokens": sub.tokens,
                 "age_s": round(now - sub.t_submit, 3),
                 "deadline_in_s": (round(sub.deadline - mono, 3)
                                   if sub.deadline is not None else None),
                 "resolved": sub.pending.done()}
                for sub in inflight]
        except Exception:
            sections["inflight"] = None
        try:
            origin = dict(self._driver_origin)
            rows = []
            for seq_id, req in list(self._driver_reqs.items()):
                sub = origin.get(seq_id)
                rows.append(
                    {"seq_id": seq_id, "index": req.index,
                     "request_id": sub[0].request_id if sub else None,
                     "prompt_tokens": len(req.ids),
                     "generated_tokens": len(req.generated),
                     "done": req.done,
                     "t_submit": req.t_submit, "t_admit": req.t_admit,
                     "t_first": req.t_first, "t_done": req.t_done,
                     "age_s": round(now - req.t_submit, 3)})
            sections["requests"] = rows
        except Exception:
            sections["requests"] = None
        try:
            if self._tracer is not None:
                events = self._tracer.events()
                sections["spans"] = {"events": events[-256:],
                                     "total": len(events),
                                     "dropped": self._tracer.dropped}
        except Exception:
            sections["spans"] = None
        return build_bundle(reason, envelope=envelope, **sections)

    def _dump_postmortem(self, bundle: dict) -> str | None:
        """Write a prebuilt bundle; diagnostics never raise into the
        serving path."""
        try:
            return self._postmortem.dump(bundle)
        except Exception as exc:   # never let a dump take serving down
            log_event("session.postmortem", level="error", exc=exc,
                      reason=bundle.get("reason"))
            return None

    # -- watchdog ----------------------------------------------------------
    def _watch(self) -> None:
        interval = max(0.02, min(1.0, (self.watchdog_s or 1.0) / 4))
        while not self._watch_stop.wait(interval):
            with self._acct_lock:
                busy = bool(self._inflight)
            if not busy:
                continue
            hb = max(self._heartbeat, getattr(self.engine, "heartbeat", 0.0))
            if time.monotonic() - hb > self.watchdog_s:
                self.trip_watchdog()

    def trip_watchdog(self) -> None:
        """Declare the engine wedged: flip readiness, fail every pending
        handle with a typed error (no caller is ever left hanging), and
        stop accepting submissions.  Sticky — recovery is a new process;
        the driver releases engine-side sequences if/when it unsticks."""
        with self._acct_lock:
            if self._wedged.is_set():
                return
            self._wedged.set()
            pending = list(self._inflight)
        self.engine.stats.watchdog_trips += 1
        log_event("session.watchdog_trip", level="error",
                  watchdog_s=self.watchdog_s, pending=len(pending),
                  session=f"{id(self):#x}")
        exc = EngineWedged(
            f"engine made no progress for >{self.watchdog_s:.1f}s "
            f"(watchdog tripped)")
        # the whole point of the flight recorder: the trip ships the
        # runway that led to it — snapshot BEFORE failing the handles
        # (resolution empties the in-flight table the bundle records)
        bundle = self.postmortem_bundle("watchdog_trip", error=str(exc))
        for sub in pending:
            self._resolve_error(sub, exc)
        self._dump_postmortem(bundle)

    # -- driver side -------------------------------------------------------
    def start(self) -> "ContinuousSession":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="paged-session-driver")
            self._thread.start()
        if (self._watch_thread is None and self.watchdog_s
                and not self._watch_stop.is_set()):
            self._watch_thread = threading.Thread(
                target=self._watch, daemon=True, name="paged-session-watchdog")
            self._watch_thread.start()
        return self

    def close(self) -> None:
        """Stop accepting work, finish in-flight requests, join the
        driver."""
        with self._submit_lock:
            self._closed.set()
            self._inbox.put(None)       # wake a blocked driver
        joined = True
        # a session whose driver never ran has nothing worth snapshotting
        # — its engine is cold (the rewarm happens in _run), and writing
        # would clobber the previous process's good snapshot with an
        # empty one (_snapshot_once keeps the double-drain idempotence
        # for sessions that DID run and already snapshotted)
        started = self._thread is not None
        if self._thread is not None:
            self._thread.join(timeout=120)
            if self._thread.is_alive():
                # A wedged device dispatch (or a very long healthy drain)
                # can outlive the join timeout.  The driver still owns
                # the engine, so keep the thread reference — nulling it
                # would let callers tear down/reuse the engine while the
                # driver is live.  No raise: close() runs from __exit__
                # and MultiSession.close(), where an exception would mask
                # in-flight errors or strand sibling replicas un-closed.
                # structured event, not warnings.warn: the default warning
                # filter dedups per call site, which would hide a second
                # wedged replica in the same process.
                log_event("session.drain_stuck", level="warning",
                          timeout_s=120, session=f"{id(self):#x}")
                joined = False
            else:
                self._thread = None
        if joined and self._watch_thread is not None:
            self._watch_stop.set()
            self._watch_thread.join(timeout=5)
            self._watch_thread = None
        if started and joined and not self._wedged.is_set():
            # the driver exited cleanly: the engine is quiescent and
            # single-owner safe to snapshot (a wedged engine's state is
            # exactly what NOT to rewarm the next process with)
            self._write_snapshot()

    def __enter__(self) -> "ContinuousSession":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _restore_warm(self) -> None:
        """Replay the previous process's warm-state snapshot through the
        engine (driver thread — it owns the engine) and flip ``warming``
        off; every failure shape boots cold with a warning event.  The
        restore interval lands in ``reval_restart_to_ready_seconds`` —
        the restart SLO this whole subsystem exists to shrink."""
        from ..obs import metrics as obs_metrics

        try:
            src = self.snapshot_path
            doc = read_snapshot(src) if src else None
            if doc is None and self.snapshot_fallback:
                # warm scale-up: no snapshot of our own — inherit a
                # sibling's (its .pages sidecar rides along below)
                src = self.snapshot_fallback
                doc = read_snapshot(src)
            if doc is not None:
                refs = doc.get("kv_pages")
                if refs and hasattr(self.engine, "attach_tier_refs"):
                    # BEFORE rewarm: the replayed chains then promote
                    # real disk-tier KV bytes instead of re-running
                    # prefill (kv_tiers.py; garbage refs degrade to the
                    # v1 replay path inside the engine)
                    self.engine.attach_tier_refs(refs, f"{src}.pages")
                warmed = self.engine.rewarm(doc.get("engine") or {})
                reg = self.engine.stats.registry
                if warmed:
                    reg.counter(
                        obs_metrics.RESTART_WARM_PREFIXES).add(warmed)
                reg.histogram(obs_metrics.RESTART_TO_READY).observe(
                    time.perf_counter() - self._t_boot)
                log_event("session.snapshot_restored",
                          path=src, prefix_chains=warmed,
                          unfinished=len(doc.get("unfinished_request_ids")
                                         or []),
                          restore_s=round(
                              time.perf_counter() - self._t_boot, 3))
        except Exception as exc:   # noqa: BLE001 — a failed restore is
            # a cold boot, never a wedged one
            log_event("session.snapshot_error", level="warning",
                      path=self.snapshot_path, where="restore", exc=exc)
        finally:
            self._warming.clear()

    def _write_snapshot(self) -> None:
        """The drain-side half: land ONE warm-state snapshot (idempotent
        across double drains), carrying the engine's warm state plus the
        request ids the drain left unfinished (journal refs — ``fleet
        --resume`` re-runs those chunks)."""
        if (not self.snapshot_path or self._snapshot_once.is_set()
                or not hasattr(self.engine, "warm_state")):
            return
        self._snapshot_once.set()
        try:
            state = self.engine.warm_state()
        except Exception as exc:   # noqa: BLE001 — a drain must finish
            # whether or not its snapshot lands
            log_event("session.snapshot_error", level="warning",
                      path=self.snapshot_path, where="warm_state", exc=exc)
            return
        kv_pages = None
        if hasattr(self.engine, "dump_tier_pages"):
            try:
                # v2 disk tier: warm pages land in the sidecar dir, their
                # refs in the snapshot doc (kv_tiers.py); a failed dump
                # still writes the v1-equivalent token-tree snapshot
                kv_pages = self.engine.dump_tier_pages(
                    f"{self.snapshot_path}.pages") or None
            except Exception as exc:   # noqa: BLE001
                log_event("kvtier.disk_error", level="warning",
                          where="drain", path=self.snapshot_path, exc=exc)
        with self._acct_lock:
            unfinished = [sub.request_id for sub in self._inflight
                          if not sub.pending.done()]
        write_snapshot(self.snapshot_path, state,
                       unfinished_request_ids=unfinished,
                       kv_pages=kv_pages)

    def _run(self) -> None:
        eng = self.engine
        if self._warming.is_set():
            # rewarm BEFORE the drive loop: the driver owns the engine,
            # and /readyz stays 503 "warming" until this returns (early
            # submissions just wait in the inbox)
            self._restore_warm()
        reqs: dict[int, object] = {}
        # seq_id -> (submission, position of this prompt in it)
        origin: dict[int, tuple[_Submission, int]] = {}
        # publish the live tables for postmortem/debugz snapshots
        self._driver_reqs = reqs
        self._driver_origin = origin
        st = eng.new_drive_state()

        def drain(block: bool) -> None:
            while True:
                try:
                    sub = self._inbox.get(timeout=0.2 if block else 0)
                except queue.Empty:
                    return
                if sub is None:
                    return
                if self._wedged.is_set():
                    # enqueued before the trip flag landed: reject, never
                    # hand work to a wedged engine
                    self._resolve_error(sub, EngineWedged(
                        "engine watchdog tripped; session is not serving"))
                    continue
                try:
                    self._enqueue(sub, reqs, origin)
                except Exception as exc:   # oversized request etc.
                    # roll back any of THIS submission's already-queued
                    # sequences so they don't decode into a dead handle
                    self._fail(sub, exc, reqs, origin, st)
                    self._resolve_error(sub, exc)
                if block:
                    return                  # got work; go run a tick

        while True:
            # heartbeat: one stamp per loop iteration — every decode step
            # and every idle poll.  The watchdog reads the max of this and
            # the engine's own in-tick stamp.
            self._heartbeat = time.monotonic()
            if self._wedged.is_set():
                # watchdog tripped while we were stuck: the handles are
                # already failed; release engine-side sequences so pages
                # and prefix pins free, then only drain-and-reject
                if reqs:
                    self._fail(None, EngineWedged(
                        "engine watchdog tripped"), reqs, origin, st)
                if self._closed.is_set() and self._inbox.empty():
                    return
                drain(block=True)
                continue
            if not reqs:
                if self._closed.is_set() and self._inbox.empty():
                    return
                drain(block=True)
                continue
            drain(block=False)
            self._expire_deadlines(reqs, origin, st)
            if not reqs:
                continue
            try:
                if self._step_chaos is not None:
                    self._step_chaos.tick()
                eng._drive_tick(reqs, st)
            except RuntimeError as exc:
                if "deadlock" in str(exc):
                    # nothing running + nothing admissible: the FCFS head
                    # cannot ever fit (e.g. needs more pages than the
                    # pool).  Fail ONLY its submission — the requests
                    # behind it are admissible once it leaves the queue.
                    head = min((s for s, r in reqs.items() if not r.done),
                               default=None)
                    if head is not None:
                        self._fail(origin[head][0], exc, reqs, origin, st)
                        st.dirty = True
                        continue
                log_event("session.driver_error", level="error", exc=exc)
                bundle = self.postmortem_bundle("driver_exception",
                                                error=repr(exc))
                self._fail(None, exc, reqs, origin)
                self._dump_postmortem(bundle)
                st = eng.new_drive_state()
                continue
            except Exception as exc:
                # device fault (or injected engine-step chaos): fail every
                # in-flight submission, release their sequences, start clean
                log_event("session.driver_error", level="error", exc=exc)
                bundle = self.postmortem_bundle("driver_exception",
                                                error=repr(exc))
                self._fail(None, exc, reqs, origin)
                self._dump_postmortem(bundle)
                st = eng.new_drive_state()
                continue
            for seq_id in [s for s, r in reqs.items() if r.done]:
                req = reqs.pop(seq_id)
                sub, pos = origin.pop(seq_id)
                from ..inference.tpu.engine import finalize_text

                sub.pending.texts[pos] = finalize_text(
                    eng.tokenizer, req.generated, sub.stop)
                sub.pending._remaining -= 1
                eng.stats.prompts += 1
                if self.receipt_fingerprint is not None:
                    # receipt stamp point: req.generated is the RAW
                    # emitted id stream (EOS included) — digest it here,
                    # before finalisation can cut anything
                    from ..obs import receipts

                    sub.digests[pos] = receipts.token_digest(req.generated)
                    sub.gen_tokens += len(req.generated)
                if self._tracer is not None:
                    self._trace_req(sub, pos, req)
                if sub.pending._remaining == 0:
                    self._stamp_receipt(sub)
                    sub.pending._fire()

    def _stamp_receipt(self, sub: _Submission) -> None:
        """Build the submission's reproducibility receipt and deliver it
        via ``on_receipt`` — BEFORE ``_fire()``, so a blocked ``result()``
        caller observes it.  Only full successes get one (an errored or
        partially-cancelled submission resolves through ``_fail``, which
        never reaches here); a misbehaving callback must not take the
        driver down."""
        if (self.receipt_fingerprint is None or sub.on_receipt is None
                or any(d is None for d in sub.digests)):
            return
        from ..obs import receipts

        receipt = receipts.build_receipt(
            self.receipt_fingerprint, self.engine_id,
            sub.digests, sub.gen_tokens, grammar=sub.grammar,
            sampling={"max_tokens": sub.max_new,
                      "temperature": sub.temperature,
                      "top_k": sub.top_k, "top_p": sub.top_p})
        try:
            sub.on_receipt(receipt)
        except Exception as exc:   # noqa: BLE001 — observability must
            # never fail the generation it describes
            log_event("session.receipt_error", level="warning", exc=exc,
                      request_id=sub.request_id)

    def _trace_req(self, sub: _Submission, pos: int, req,
                   error: str | None = None) -> None:
        """Emit one finished prompt's span tree from the stamps the
        engine kept on its request object."""
        t_done = req.t_done if req.t_done is not None else time.perf_counter()
        self._tracer.record_request(
            sub.request_id, pos, t_submit=req.t_submit, t_admit=req.t_admit,
            t_first=req.t_first, t_done=t_done,
            n_tokens=len(req.generated), error=error)

    def _expire_deadlines(self, reqs: dict, origin: dict, st) -> None:
        """Cancel submissions whose deadline passed: release their
        scheduler sequences (pages + prefix pins free for live work) and
        fail the handle with :class:`DeadlineExceeded`."""
        now = time.monotonic()
        expired = {sub for sub, _ in origin.values()
                   if sub.deadline is not None and now >= sub.deadline}
        if not expired:
            return
        # a storm (a whole batch expiring in one sweep) means the engine
        # is the story, not the requests: ship the runway before the
        # cancellations rewrite the in-flight table
        storm = (self.postmortem_bundle(
                     "deadline_storm", error=f"{len(expired)} submissions "
                     f"expired in one sweep")
                 if len(expired) >= DEADLINE_STORM_N else None)
        # land any in-flight pipelined chunk's writes BEFORE releasing
        # pages it may still target
        flush = getattr(self.engine, "_process_pending", None)
        if flush is not None:
            flush(reqs, st)
        for sub in expired:
            self.engine.stats.deadline_expired += 1
            log_event("session.deadline_expired", level="warning",
                      request_id=sub.request_id, prompts=len(sub.prompts))
            self._fail(sub, DeadlineExceeded(
                "request deadline exceeded before generation finished"),
                reqs, origin, st)
        if storm is not None:
            log_event("session.deadline_storm", level="error",
                      expired=len(expired), threshold=DEADLINE_STORM_N)
            self._dump_postmortem(storm)

    @staticmethod
    def _resolve_error(sub: _Submission, exc: BaseException) -> None:
        if sub.pending.done():
            return
        if isinstance(exc, ServingError):
            sub.pending._exc = exc
        sub.pending._error = str(exc)
        sub.pending._fire()

    def _fail(self, target: _Submission | None, exc: BaseException,
              reqs: dict, origin: dict, st=None) -> None:
        """Error ``target``'s pending handle (or every submission when
        ``target`` is None), releasing its scheduler sequences.  With
        ``st`` given, the released sequences are also dropped from the
        drive state's active slots (a deadline can expire a RUNNING
        request; the engine must not keep decoding into a freed slot)."""
        eng = self.engine
        for seq_id in list(reqs):
            sub, pos = origin[seq_id]
            if target is not None and sub is not target:
                continue
            req = reqs.pop(seq_id)
            origin.pop(seq_id)
            if self._tracer is not None:
                self._trace_req(sub, pos, req, error=str(exc))
            if not req.done:
                try:
                    eng.release_request(seq_id, req)
                except Exception:
                    pass
            if st is not None:
                active = getattr(st, "active", None) or {}
                for slot, sid in list(active.items()):
                    if sid == seq_id:
                        active.pop(slot)
                        st.dirty = True
            self._resolve_error(sub, exc)

    def _enqueue(self, sub: _Submission, reqs: dict,
                 origin: dict) -> None:
        """Hand a submission's (already tokenised) prompts to the native
        scheduler (driver thread only — the runtime is single-owner)."""
        from ..inference.tpu.engine import StopScanner, finalize_text
        from ..inference.tpu.paged_engine import _Request

        eng = self.engine
        keys = eng.request_keys(len(sub.prompts))
        for pos, ids in enumerate(sub.encoded):
            notify = None
            if sub.on_progress is not None:
                def notify(req, _sub=sub, _pos=pos):
                    _sub.on_progress(_pos, finalize_text(
                        eng.tokenizer, req.generated, _sub.stop))
            # ride the engine's persistent prefix cache: a template seen
            # on ANY earlier request (this submission, a previous POST, a
            # fleet call before the session attached) prefills only once
            seq_id, node = eng.submit_request(ids, sub.max_new,
                                              grammar=sub.grammar)
            reqs[seq_id] = _Request(
                index=pos, ids=ids, max_new=sub.max_new,
                scanner=StopScanner(eng.tokenizer, sub.stop),
                temp=sub.temperature, top_k=sub.top_k, top_p=sub.top_p,
                notify=notify, key=keys[pos], node=node,
                grammar=sub.grammar,
                gstate=(eng.grammar_state(sub.grammar)
                        if sub.grammar else 0),
                # latency counts from the HTTP submit, inbox wait included
                t_submit=sub.t_submit)
            origin[seq_id] = (sub, pos)


class MultiSession:
    """Cross-request continuous batching over a replica set
    (``DataParallelPagedEngine``): one :class:`ContinuousSession` per
    replica, each with its own driver thread on its own device group, and
    least-loaded routing of incoming submissions — the serve-mode
    topology for the v5e-8 flagship shape (dp=2 × tp=4), where a single
    session would leave half the chips idle.

    Load feedback is by outstanding prompt count; a submission's weight
    releases when its handle resolves (the ``_Pending`` done-callback),
    so a replica stuck on long generations stops receiving work — the
    serve-side analog of the in-process work-stealing queue
    (inference/tpu/dp_paged.py).

    Routing skips replicas that stopped accepting (wedged watchdog,
    draining) outright, and prefers READY replicas (queue below
    watermark, fresh heartbeat, live driver) over merely-accepting ones —
    a replica drowning in queued tokens must not shed a request a
    sibling had room for.  One bad replica degrades capacity, not
    availability.  When NO replica accepts, the typed error reflects why
    (wedged beats draining), so the server returns the right status.

    ``step_chaos`` is shared across the replica drivers (the step ordinal
    is then process-global, so cross-replica fault placement depends on
    scheduling — single-session runs keep the fully deterministic
    schedule)."""

    def __init__(self, engines, autostart: bool = True, *,
                 max_queued_tokens: int | None = None,
                 watchdog_s: float | None = None, step_chaos=None,
                 tracer=None, postmortem_dir: str | None = None,
                 snapshot_path: str | None = None,
                 snapshot_fallback: str | None = None):
        if snapshot_path is None:
            # resolve the env default HERE so replicas get distinct
            # files — each falling back independently would collide on
            # one path ("" disables explicitly)
            snapshot_path = env_str("REVAL_TPU_SNAPSHOT_PATH", "") or None
        # one shared tracer: replica placement is an `args` detail, the
        # span tree is per request id either way
        # unguarded: built once here, read-only thereafter
        self.sessions = [ContinuousSession(e, autostart=autostart,
                                           max_queued_tokens=max_queued_tokens,
                                           watchdog_s=watchdog_s,
                                           step_chaos=step_chaos,
                                           tracer=tracer,
                                           postmortem_dir=postmortem_dir,
                                           # one snapshot file per replica:
                                           # each driver owns its own
                                           # engine's warm state
                                           snapshot_path=(
                                               f"{snapshot_path}.r{i}"
                                               if snapshot_path else ""),
                                           # every replica may inherit
                                           # the same sibling snapshot
                                           # (scale-up warm boot)
                                           snapshot_fallback=snapshot_fallback)
                         for i, e in enumerate(engines)]
        #: the server's SIGUSR1/SIGTERM dumps use this writer, so a dp
        #: set honors the configured directory exactly like a single
        #: session (replica-level trips use each session's own writer —
        #: same directory, separate per-reason rate windows)
        self._postmortem = PostmortemWriter(postmortem_dir)
        self._load = [0] * len(self.sessions)   # guarded-by: _lock
        self._lock = threading.Lock()

    def start(self) -> "MultiSession":
        for s in self.sessions:
            s.start()
        return self

    def submit(self, prompts: list[str], *, max_new_tokens: int = 256,
               temperature: float = 0.0, stop: list[str] | None = None,
               top_k: int = 0, top_p: float = 1.0,
               on_progress=None, deadline_s: float | None = None,
               request_id: str | None = None,
               grammar: str | None = None, on_receipt=None) -> _Pending:
        n = len(prompts)
        with self._lock:
            accepting = [i for i, s in enumerate(self.sessions)
                         if s._accepting()]
            if not accepting:
                if any(s._wedged.is_set() for s in self.sessions):
                    raise EngineWedged("no replica is serving (watchdog tripped)")
                raise Draining("all replicas are draining/closed")
            # prefer READY replicas (queue below watermark, heartbeat
            # fresh, driver alive): an overloaded/stale replica must not
            # shed or stall a request a sibling has room for.  Fall back
            # to merely-accepting replicas so the typed shed/wedge error
            # still comes from a real submit when everyone is saturated.
            ready = [i for i in accepting
                     if self.sessions[i].readiness()["ready"]]
            pool = ready or accepting
            i = min(pool, key=self._load.__getitem__)
            self._load[i] += n

        def release() -> None:
            with self._lock:
                self._load[i] -= n

        try:
            pending = self.sessions[i].submit(
                prompts, max_new_tokens=max_new_tokens,
                temperature=temperature, stop=stop, top_k=top_k, top_p=top_p,
                on_progress=on_progress, deadline_s=deadline_s,
                request_id=request_id, grammar=grammar,
                on_receipt=on_receipt)
        except Exception:
            release()                   # closed/shedding session etc.: no leak
            raise
        pending._add_done_callback(release)
        return pending

    def generate_fn(self):
        """See :meth:`ContinuousSession.generate_fn` — pass
        ``serialize=False`` to the server."""
        return _generate_fn_for(self)

    def readiness(self) -> dict:
        """Per-replica readiness; the set is ready while ANY replica is
        (degraded capacity still serves)."""
        reps = [s.readiness() for s in self.sessions]
        fps = sorted({r.get("fingerprint") for r in reps} - {None})
        return {"ready": any(r["ready"] for r in reps),
                "warming": any(r.get("warming") for r in reps),
                # unanimous receipt fingerprint, or None when the dp
                # replicas disagree (never true in-process — one config
                # builds them — but the router's skew detector treats
                # None as "unknown", the safe reading either way)
                "fingerprint": fps[0] if len(fps) == 1 else None,
                "fingerprints": fps,
                "replicas": reps}

    def engine_stats(self) -> list:
        return [s.engine.stats for s in self.sessions]

    def postmortem_bundle(self, reason: str, error: str | None = None) -> dict:
        """One bundle per replica under ONE shared envelope (``/debugz``
        and SIGUSR1 for a dp replica set): the fingerprint and log ring
        are process-global, so only the outer bundle carries them."""
        return build_bundle(
            reason, error=error,
            replicas=[s.postmortem_bundle(reason, envelope=False)
                      for s in self.sessions])

    def close(self) -> None:
        for s in self.sessions:
            s.close()

    def __enter__(self) -> "MultiSession":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
