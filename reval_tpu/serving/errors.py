"""Typed serving-lifecycle errors.

Every failure the serving layer produces on purpose is one of these, so
the HTTP boundary can map it to a *stable* status + error code (and the
fleet's retry/bisection machinery can classify it) instead of leaking
``str(exc)`` of whatever the engine raised.  The messages are authored
here — safe to put on the wire; anything else is an internal error and
only its request id leaves the server.

- :class:`Overloaded` — admission control shed the request (HTTP 429 +
  ``Retry-After``); the queue of pending prompt tokens is above the
  session's watermark.  Transient by construction: back off and retry.
- :class:`Draining` — the server/session is in graceful shutdown (503):
  no new work, in-flight requests finish.
- :class:`EngineWedged` — the no-progress watchdog tripped (503): the
  engine stopped stepping, every pending submission is failed with this
  error (never left hanging), and readiness stays down until the process
  is replaced.
- :class:`DeadlineExceeded` — the request's client-supplied budget ran
  out mid-service (504); the engine-side sequence was cancelled and its
  pages/prefix pins freed.
- :class:`EngineFailure` — an engine/driver fault surfaced through a
  pending handle (500).  The message is the UNDERLYING exception's text
  (engine internals, device paths) and therefore ``wire_safe = False``:
  the HTTP boundary logs it and puts only the stable code + request id
  on the wire, exactly like any other unexpected 500.
- :class:`FleetUnavailable` — the fleet router exhausted its replica
  candidates (every replica ejected, draining, or dead in transport)
  (503 + ``Retry-After``).  Distinct from :class:`Overloaded`, which the
  router raises when replicas are alive but all shedding.

All subclass ``RuntimeError`` so pre-existing callers that caught the
untyped failures keep working.  The typed-error lint pass
(``reval_tpu/analysis/errboundary.py``) enforces that the serving layer
raises nothing outside this taxonomy (plus client-error ``ValueError``
and waiter ``TimeoutError``).
"""

from __future__ import annotations

__all__ = ["ServingError", "Overloaded", "Draining", "EngineWedged",
           "DeadlineExceeded", "EngineFailure", "FleetUnavailable"]


class ServingError(RuntimeError):
    """Base: a deliberate serving-layer failure with a stable wire code."""

    status: int = 500
    code: str = "serving_error"
    #: True = the message was authored by the serving layer and may go on
    #: the wire verbatim; False = it carries engine internals, so the
    #: HTTP boundary must log it and send a sanitized body instead
    wire_safe: bool = True

    def __init__(self, message: str, *, retry_after: float | None = None):
        super().__init__(message)
        #: seconds the client should wait before retrying (None = no hint)
        self.retry_after = retry_after


class Overloaded(ServingError):
    status = 429
    code = "overloaded"

    def __init__(self, message: str, *, retry_after: float | None = 1.0):
        super().__init__(message, retry_after=retry_after)


class Draining(ServingError):
    status = 503
    code = "draining"

    def __init__(self, message: str, *, retry_after: float | None = 1.0):
        super().__init__(message, retry_after=retry_after)


class EngineWedged(ServingError):
    status = 503
    code = "engine_wedged"


class DeadlineExceeded(ServingError):
    status = 504
    code = "deadline_exceeded"


class FleetUnavailable(ServingError):
    """Router: no replica could take the request — every candidate was
    ejected, draining, or died in transport.  Transient by construction
    (ejection cooldowns are bounded and half-open probes rejoin
    recovered replicas), so clients back off and retry."""

    status = 503
    code = "fleet_unavailable"

    def __init__(self, message: str, *, retry_after: float | None = 2.0):
        super().__init__(message, retry_after=retry_after)


class EngineFailure(ServingError):
    """Typed wrapper for an untyped engine/driver fault: the serving
    path never re-raises a bare ``RuntimeError``, but the original
    message (NOT wire-safe — it is whatever the engine raised) is
    preserved for in-process callers like the fleet's retry loop."""

    status = 500
    code = "internal_error"
    wire_safe = False
