"""Warm-state snapshots: what a graceful drain leaves for the next boot.

The restart story has two halves.  The AOT executable cache
(``inference/tpu/aot_cache.py``) makes the next process skip XLA
compilation; this module makes it skip the COLD CACHE: at drain the
session writes one atomic JSON snapshot — the radix prefix-cache token
tree (every cached chain as its full token list), the per-template
affinity stats the fleet router's placement view keys on, and the
request ids of submissions that were still unfinished when the drain
cut them off (journal refs: ``fleet --resume`` re-runs those chunks) —
and at boot the engine replays the token tree through real prefill
before ``/readyz`` flips, surfacing the interval as the distinct
``warming`` readiness state.  (The template stats are keyed in TOKEN
space — crc32 of the first prompt page's ids, the engine-side analog
of the router's char-window affinity key, not the same hash.)

Format **v2** additionally carries disk-tier KV page refs
(``kv_pages``: key/file/sha256 per page, files in a ``<path>.pages``
sidecar directory — inference/tpu/kv_tiers.py): the next boot promotes
the actual KV bytes instead of replaying prefill per chain.  v1
documents stay readable — they simply have no pages to promote, so
rewarm falls back to the v1 prefill-replay path.

Degradation contract (mirrors the AOT cache): a truncated, garbage, or
wrong-format snapshot file boots a COLD engine with one
``session.snapshot_error`` warning event — never a wedged startup; a
directory the drain cannot write gets the same event and the drain
completes anyway.  Writes are tmp+rename atomic with a sticky
once-guard in the session, so a double drain writes exactly one
snapshot.
"""

from __future__ import annotations

import json
import os
import time

from ..obs.logging import log_event

__all__ = ["read_snapshot", "write_snapshot", "FORMAT", "ACCEPTED_FORMATS"]

FORMAT = "reval-warm-snapshot-v2"

#: formats read_snapshot admits: v1 docs (pre-KV-tiering) rewarm the
#: token tree exactly as before, just without disk-tier pages
ACCEPTED_FORMATS = ("reval-warm-snapshot-v1", FORMAT)


def write_snapshot(path: str, engine_state: dict,
                   unfinished_request_ids: list | None = None,
                   kv_pages: list | None = None) -> bool:
    """Atomically land one warm-state snapshot; True on success.  Every
    failure shape (unwritable dir, full disk) degrades to a
    ``session.snapshot_error`` warning — a drain must finish whether or
    not its snapshot lands.  ``kv_pages``: disk-tier page refs from
    :meth:`TieredPageStore.write_disk` (absent = no disk tier)."""
    doc = {"format": FORMAT,
           "created_ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "pid": os.getpid(),
           "engine": engine_state or {},
           "unfinished_request_ids": list(unfinished_request_ids or [])}
    if kv_pages:
        doc["kv_pages"] = list(kv_pages)
    tmp = f"{path}.tmp"
    try:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError as exc:
        log_event("session.snapshot_error", level="warning", path=path,
                  where="write", exc=exc)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    chains = len((engine_state or {}).get("prefix_chains") or [])
    log_event("session.snapshot_written", path=path, prefix_chains=chains,
              kv_pages=len(doc.get("kv_pages") or []),
              unfinished=len(doc["unfinished_request_ids"]))
    return True


def read_snapshot(path: str) -> dict | None:
    """The snapshot document, or None: absent is a silent cold boot,
    while corrupt/truncated/wrong-format warns (``session.snapshot_error``)
    and STILL boots cold — a bad snapshot must never wedge startup."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or doc.get("format") not in ACCEPTED_FORMATS:
            raise ValueError(f"not a {FORMAT} document")
        if not isinstance(doc.get("engine"), dict):
            raise ValueError("snapshot carries no engine state object")
    except Exception as exc:    # noqa: BLE001 — every unreadable shape
        # is the same verdict: boot cold, say why
        log_event("session.snapshot_error", level="warning", path=path,
                  where="read", exc=exc)
        return None
    return doc
