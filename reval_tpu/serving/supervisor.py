"""Crash-loop supervisor: fast rebirth without flapping the router.

``reval_tpu serve --supervise`` wraps the server process in this loop:
spawn the child, wait for it to die, land a postmortem bundle naming
the death, back off (the existing :class:`~reval_tpu.resilience.
RetryPolicy` exponential schedule — base ``REVAL_TPU_SUPERVISE_BACKOFF_S``,
doubling per rapid death, jittered, capped), and respawn.  Combined
with the AOT executable cache and the warm-state snapshot, the respawn
is seconds-to-ready instead of a full compile — which is what makes
supervised respawn a *good* policy: a replica that takes minutes to
come back should stay dead and let the router re-balance instead.

**Sticky-failed beats flapping.**  Deaths inside the rapid-death window
(``REVAL_TPU_SUPERVISE_WINDOW_S``) accumulate; at
``REVAL_TPU_SUPERVISE_MAX_DEATHS`` the supervisor STOPS respawning and
exits nonzero (``supervisor.sticky_failed``).  A crash-looping replica
that kept respawning would oscillate the router's health state machine
(eject → half-open probe → accept → die → eject …) and smear failures
over live traffic; sticky-failed leaves it cleanly ejected until an
operator (or orchestrator) intervenes.  Deaths older than the window
age out, so a long-lived server that dies once a day respawns forever.

A child exiting 0 is a GRACEFUL shutdown (SIGTERM drain, operator
stop): the supervisor exits 0 without respawning — a deliberate stop
must stay stopped.

Everything process-shaped is injectable (``spawn`` returns any object
with ``wait() -> returncode``; clock/sleep likewise), so the whole
state machine is unit-testable without real subprocesses.
"""

from __future__ import annotations

import time
from collections import deque

from ..env import env_float, env_int
from ..obs import metrics as obs_metrics
from ..obs.flightrec import PostmortemWriter, build_bundle
from ..obs.logging import log_event
from ..obs.metrics import MetricsRegistry
from ..resilience import RetryPolicy

__all__ = ["Supervisor"]


class Supervisor:
    """Respawn loop around one child server (see module docstring).

    ``spawn``: zero-arg callable returning a child handle —
    ``subprocess.Popen`` or any object with ``wait() -> returncode``
    (and optionally ``pid``).  Constructor knobs default to the
    ``REVAL_TPU_SUPERVISE_*`` env vars.  Single-owner: one thread runs
    :meth:`run`; :meth:`stop` (any thread) makes the loop exit after
    the current child dies instead of respawning."""

    def __init__(self, spawn, *, max_deaths: int | None = None,
                 window_s: float | None = None,
                 base_backoff_s: float | None = None,
                 max_backoff_s: float = 30.0,
                 postmortem_dir: str | None = None,
                 clock=time.monotonic, sleep=time.sleep, rng=None):
        self.spawn = spawn
        self.max_deaths = (max_deaths if max_deaths is not None
                           else env_int("REVAL_TPU_SUPERVISE_MAX_DEATHS", 5))
        self.window_s = (window_s if window_s is not None
                         else env_float("REVAL_TPU_SUPERVISE_WINDOW_S", 60.0))
        base = (base_backoff_s if base_backoff_s is not None
                else env_float("REVAL_TPU_SUPERVISE_BACKOFF_S", 0.5))
        #: the one backoff schedule in the tree — delay_for(n) doubles
        #: per rapid death, jitters, and caps at max_backoff_s
        self._retry = RetryPolicy(base_delay=base, max_delay=max_backoff_s,
                                  rng=rng)
        self._clock = clock
        self._sleep = sleep
        self._deaths: deque = deque()       # unguarded: run()-thread only
        self._stopping = False              # unguarded: latch read by run()
        self._obs = MetricsRegistry()
        self._postmortem = PostmortemWriter(postmortem_dir,
                                            min_interval_s=0.0)
        #: "idle" → "running" → "stopped" | "sticky_failed"
        self.state = "idle"
        self.child = None
        self.respawns = 0

    def counters(self) -> dict:
        return {"deaths": len(self._deaths), "respawns": self.respawns,
                "state": self.state}

    def stop(self) -> None:
        """Make :meth:`run` exit once the current child dies (callers
        kill the child themselves — the supervisor never owns signal
        delivery, so tests and the CLI can each do it their way)."""
        self._stopping = True

    def _note_death(self, rc) -> int:
        """Fold one child death into the rapid-death window; returns the
        deaths currently inside it."""
        now = self._clock()
        self._deaths.append(now)
        while self._deaths and now - self._deaths[0] > self.window_s:
            self._deaths.popleft()
        self._obs.counter(obs_metrics.RESTART_DEATHS).add(1)
        log_event("supervisor.death", level="warning", exit_code=rc,
                  rapid_deaths=len(self._deaths),
                  window_s=self.window_s)
        self._postmortem.dump(build_bundle(
            "supervisor_child_death", exit_code=rc,
            rapid_deaths=len(self._deaths), window_s=self.window_s,
            respawns=self.respawns, metrics=self._obs.snapshot()))
        return len(self._deaths)

    def run(self) -> int:
        """Supervise until the child exits gracefully (0), :meth:`stop`
        is called (0), or the rapid-death budget is spent (1)."""
        self.state = "running"
        while True:
            self.child = self.spawn()
            self.respawns += 1
            self._obs.counter(obs_metrics.RESTART_RESPAWNS).add(1)
            log_event("supervisor.spawn",
                      pid=getattr(self.child, "pid", None),
                      respawns=self.respawns)
            rc = self.child.wait()
            if self._stopping or rc == 0:
                # graceful: a deliberate stop must stay stopped
                self.state = "stopped"
                return 0
            rapid = self._note_death(rc)
            if rapid >= self.max_deaths:
                self.state = "sticky_failed"
                log_event("supervisor.sticky_failed", level="error",
                          rapid_deaths=rapid, window_s=self.window_s,
                          max_deaths=self.max_deaths)
                return 1
            self._sleep(self._retry.delay_for(rapid - 1))
