"""Crash-loop supervisor: fast rebirth without flapping the router.

``reval_tpu serve --supervise`` wraps the server process in this loop:
spawn the child, wait for it to die, land a postmortem bundle naming
the death, back off (the existing :class:`~reval_tpu.resilience.
RetryPolicy` exponential schedule — base ``REVAL_TPU_SUPERVISE_BACKOFF_S``,
doubling per rapid death, jittered, capped), and respawn.  Combined
with the AOT executable cache and the warm-state snapshot, the respawn
is seconds-to-ready instead of a full compile — which is what makes
supervised respawn a *good* policy: a replica that takes minutes to
come back should stay dead and let the router re-balance instead.

**Sticky-failed beats flapping.**  Deaths inside the rapid-death window
(``REVAL_TPU_SUPERVISE_WINDOW_S``) accumulate; at
``REVAL_TPU_SUPERVISE_MAX_DEATHS`` the supervisor STOPS respawning and
exits nonzero (``supervisor.sticky_failed``).  A crash-looping replica
that kept respawning would oscillate the router's health state machine
(eject → half-open probe → accept → die → eject …) and smear failures
over live traffic; sticky-failed leaves it cleanly ejected until an
operator (or orchestrator) intervenes.  Deaths older than the window
age out, so a long-lived server that dies once a day respawns forever.

A child exiting 0 is a GRACEFUL shutdown (SIGTERM drain, operator
stop): the supervisor exits 0 without respawning — a deliberate stop
must stay stopped.

Everything process-shaped is injectable (``spawn`` returns any object
with ``wait() -> returncode``; clock/sleep likewise), so the whole
state machine is unit-testable without real subprocesses.

**Programmatic lifecycles.**  ``serve --supervise`` is the CLI-loop
shape; the SLO-driven autoscaler needs to OWN replica lifecycles
instead.  :class:`SupervisedReplica` runs one supervisor loop on a
background thread (same sticky-failed/backoff semantics, same
postmortem-per-death), and :class:`ReplicaPool` manages N of them
behind a ``spawn() -> endpoint`` / ``stop(endpoint)`` API — each pool
slot keeps its endpoint stable across respawns (the router's ring
membership must not churn when a child crashes), and a sticky-failed
slot is never reused: the next ``spawn()`` opens a FRESH slot, so a
poisoned config/port cannot be re-targeted.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..env import env_float, env_int
from ..obs import metrics as obs_metrics
from ..obs.flightrec import PostmortemWriter, build_bundle
from ..obs.logging import log_event
from ..obs.metrics import MetricsRegistry
from ..resilience import RetryPolicy

__all__ = ["Supervisor", "SupervisedReplica", "ReplicaPool"]


class Supervisor:
    """Respawn loop around one child server (see module docstring).

    ``spawn``: zero-arg callable returning a child handle —
    ``subprocess.Popen`` or any object with ``wait() -> returncode``
    (and optionally ``pid``).  Constructor knobs default to the
    ``REVAL_TPU_SUPERVISE_*`` env vars.  Single-owner: one thread runs
    :meth:`run`; :meth:`stop` (any thread) makes the loop exit after
    the current child dies instead of respawning."""

    def __init__(self, spawn, *, max_deaths: int | None = None,
                 window_s: float | None = None,
                 base_backoff_s: float | None = None,
                 max_backoff_s: float = 30.0,
                 postmortem_dir: str | None = None,
                 clock=time.monotonic, sleep=time.sleep, rng=None):
        self.spawn = spawn
        self.max_deaths = (max_deaths if max_deaths is not None
                           else env_int("REVAL_TPU_SUPERVISE_MAX_DEATHS", 5))
        self.window_s = (window_s if window_s is not None
                         else env_float("REVAL_TPU_SUPERVISE_WINDOW_S", 60.0))
        base = (base_backoff_s if base_backoff_s is not None
                else env_float("REVAL_TPU_SUPERVISE_BACKOFF_S", 0.5))
        #: the one backoff schedule in the tree — delay_for(n) doubles
        #: per rapid death, jitters, and caps at max_backoff_s
        self._retry = RetryPolicy(base_delay=base, max_delay=max_backoff_s,
                                  rng=rng)
        self._clock = clock
        self._sleep = sleep
        self._deaths: deque = deque()       # unguarded: run()-thread only
        self._stopping = False              # unguarded: latch read by run()
        self._obs = MetricsRegistry()
        self._postmortem = PostmortemWriter(postmortem_dir,
                                            min_interval_s=0.0)
        #: "idle" → "running" → "stopped" | "sticky_failed"
        self.state = "idle"
        self.child = None
        self.respawns = 0

    def counters(self) -> dict:
        return {"deaths": len(self._deaths), "respawns": self.respawns,
                "state": self.state}

    def stop(self) -> None:
        """Make :meth:`run` exit once the current child dies (callers
        kill the child themselves — the supervisor never owns signal
        delivery, so tests and the CLI can each do it their way)."""
        self._stopping = True

    def _note_death(self, rc) -> int:
        """Fold one child death into the rapid-death window; returns the
        deaths currently inside it."""
        now = self._clock()
        self._deaths.append(now)
        while self._deaths and now - self._deaths[0] > self.window_s:
            self._deaths.popleft()
        self._obs.counter(obs_metrics.RESTART_DEATHS).add(1)
        log_event("supervisor.death", level="warning", exit_code=rc,
                  rapid_deaths=len(self._deaths),
                  window_s=self.window_s)
        self._postmortem.dump(build_bundle(
            "supervisor_child_death", exit_code=rc,
            rapid_deaths=len(self._deaths), window_s=self.window_s,
            respawns=self.respawns, metrics=self._obs.snapshot()))
        return len(self._deaths)

    def run(self) -> int:
        """Supervise until the child exits gracefully (0), :meth:`stop`
        is called (0), or the rapid-death budget is spent (1)."""
        self.state = "running"
        while True:
            self.child = self.spawn()
            self.respawns += 1
            self._obs.counter(obs_metrics.RESTART_RESPAWNS).add(1)
            log_event("supervisor.spawn",
                      pid=getattr(self.child, "pid", None),
                      respawns=self.respawns)
            rc = self.child.wait()
            if self._stopping or rc == 0:
                # graceful: a deliberate stop must stay stopped
                self.state = "stopped"
                return 0
            rapid = self._note_death(rc)
            if rapid >= self.max_deaths:
                self.state = "sticky_failed"
                log_event("supervisor.sticky_failed", level="error",
                          rapid_deaths=rapid, window_s=self.window_s,
                          max_deaths=self.max_deaths)
                return 1
            self._sleep(self._retry.delay_for(rapid - 1))


class SupervisedReplica:
    """One :class:`Supervisor` loop on a background thread — the
    programmatic sibling of ``serve --supervise``.

    ``factory(endpoint_hint)`` returns a child handle (``wait() ->
    returncode``, ``terminate()``, ideally ``poll()``; an ``endpoint``
    attribute names where it serves).  The hint is the PREVIOUS spawn's
    resolved endpoint, so a respawned child can re-bind the same port —
    the router's ring membership stays stable across crashes.  All
    sticky-failed/backoff/postmortem semantics are the supervisor's,
    unchanged."""

    def __init__(self, factory, *, name: str = "replica", **supervisor_kw):
        self.name = name
        # unguarded: written only inside the supervisor thread's spawn
        # wrapper; stable after the first spawn (readers wait on _spawned)
        self.endpoint: str | None = None
        self._spawned = threading.Event()

        def spawn():
            child = factory(self.endpoint)
            ep = getattr(child, "endpoint", None)
            if ep:
                self.endpoint = str(ep)
            self._spawned.set()
            return child

        self.supervisor = Supervisor(spawn, **supervisor_kw)
        self._thread: threading.Thread | None = None
        self.rc: int | None = None

    def start(self, timeout_s: float = 30.0) -> "SupervisedReplica":
        """Run the supervisor loop on a daemon thread and block until
        the FIRST child spawned (its endpoint is then known).  Raises
        ``TimeoutError`` when the factory never produces a child."""
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=f"supervise-{self.name}")
            self._thread.start()
        if not self._spawned.wait(timeout_s):
            raise TimeoutError(
                f"{self.name}: first spawn did not complete in "
                f"{timeout_s:.0f}s")
        return self

    def _run(self) -> None:
        self.rc = self.supervisor.run()

    @property
    def state(self) -> str:
        return self.supervisor.state

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self, timeout_s: float = 30.0) -> int | None:
        """Graceful stop: flag the supervisor, terminate the live child
        (its exit-0 drain IS the stop), and join the loop.  Loops the
        terminate because a stop can land inside a respawn-backoff
        window — the freshly respawned child must be terminated too."""
        self.supervisor.stop()
        deadline = time.monotonic() + timeout_s
        while self.alive() and time.monotonic() < deadline:
            child = self.supervisor.child
            if child is not None:
                poll = getattr(child, "poll", None)
                if poll is None or poll() is None:
                    try:
                        child.terminate()
                    except OSError:
                        pass        # already gone
            self._thread.join(timeout=0.05)
        if self._thread is not None and not self._thread.is_alive():
            self._thread = None
        return self.rc


class ReplicaPool:
    """N supervised replicas behind ``spawn() -> endpoint`` /
    ``stop(endpoint)`` — the autoscaler's replica-lifecycle API.

    ``factory(slot, endpoint_hint)`` builds one child for pool slot
    ``slot`` (see :class:`SupervisedReplica` for the hint contract).
    Slots are never reused: a sticky-failed replica keeps its slot (and
    its postmortem trail) and the next ``spawn()`` opens a fresh one,
    so a poisoned port/config is never re-targeted."""

    def __init__(self, factory, *, postmortem_dir: str | None = None,
                 max_deaths: int | None = None, window_s: float | None = None,
                 base_backoff_s: float | None = None,
                 max_backoff_s: float = 30.0, rng=None):
        self.factory = factory
        # unguarded: built once here, read-only thereafter
        self._supervisor_kw = {
            "postmortem_dir": postmortem_dir, "max_deaths": max_deaths,
            "window_s": window_s, "base_backoff_s": base_backoff_s,
            "max_backoff_s": max_backoff_s, "rng": rng}
        self._lock = threading.Lock()
        self._slots: dict = {}      # guarded-by: _lock — slot -> replica
        self._next_slot = 0         # guarded-by: _lock

    def spawn(self, timeout_s: float = 30.0) -> str:
        """Open a fresh slot, supervise a child in it, return the
        child's endpoint once it resolved."""
        with self._lock:
            slot = self._next_slot
            self._next_slot += 1
        rep = SupervisedReplica(
            lambda hint, _slot=slot: self.factory(_slot, hint),
            name=f"replica-{slot}", **self._supervisor_kw)
        try:
            rep.start(timeout_s)
        except TimeoutError:
            # the factory overran the budget, but its supervisor thread
            # is LIVE and will finish the spawn eventually — stop it
            # before raising, or the replica it births is invisible to
            # endpoints()/close() forever
            rep.stop(timeout_s)
            raise
        if rep.endpoint is None:
            # an endpoint-less child is unreachable through every
            # endpoint-keyed API here — stop it instead of leaving a
            # supervisor thread respawning an unaddressable replica
            rep.stop(timeout_s)
            raise ValueError(
                f"replica-{slot}: factory child exposes no endpoint")
        with self._lock:
            self._slots[slot] = rep
        return rep.endpoint

    def _by_endpoint(self, endpoint: str):
        with self._lock:
            for rep in self._slots.values():
                if rep.endpoint == endpoint:
                    return rep
        return None

    def replica(self, endpoint: str) -> SupervisedReplica | None:
        """The supervised replica at ``endpoint`` (tests and drills
        reach through it to the child)."""
        return self._by_endpoint(endpoint)

    def stop(self, endpoint: str, timeout_s: float = 30.0) -> None:
        """Gracefully stop the replica at ``endpoint`` (drain-shaped:
        terminate → exit 0 → the supervisor stays stopped)."""
        rep = self._by_endpoint(endpoint)
        if rep is None:
            raise ValueError(f"no pool replica at {endpoint!r}")
        rep.stop(timeout_s)

    def endpoints(self) -> list[str]:
        """Live (supervised, not sticky-failed, not stopped) endpoints."""
        with self._lock:
            reps = list(self._slots.values())
        return [r.endpoint for r in reps
                if r.endpoint and r.alive() and r.state == "running"]

    def sticky_failed(self) -> list[str]:
        with self._lock:
            reps = list(self._slots.values())
        return [r.endpoint for r in reps
                if r.endpoint and r.state == "sticky_failed"]

    def states(self) -> dict:
        with self._lock:
            reps = list(self._slots.values())
        return {r.endpoint: r.state for r in reps if r.endpoint}

    def close(self, timeout_s: float = 30.0) -> None:
        with self._lock:
            reps = list(self._slots.values())
        for rep in reps:
            if rep.alive():
                rep.stop(timeout_s)
