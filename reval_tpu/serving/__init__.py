"""In-tree model server: the reference's vLLM-server topology, TPU-native.

The reference starts a separate GPU server process
(``python -m vllm.entrypoints.openai.api_server``, reference
start_server.sh:1-19) so one resident model can serve many sequential task
runs over the OpenAI completions protocol (reference inference.py:106-131).
Here the same topology is one in-tree module: :class:`EngineServer` holds
the resident (sharded) TPU engine and speaks the same protocol to
:class:`~reval_tpu.inference.client.HTTPClientBackend`.
"""

from .server import EngineServer, serve_config, warmup_engine
from .session import ContinuousSession, MultiSession

__all__ = ["EngineServer", "serve_config", "warmup_engine",
           "ContinuousSession", "MultiSession"]
