"""In-tree model server: the reference's vLLM-server topology, TPU-native.

The reference starts a separate GPU server process
(``python -m vllm.entrypoints.openai.api_server``, reference
start_server.sh:1-19) so one resident model can serve many sequential task
runs over the OpenAI completions protocol (reference inference.py:106-131).
Here the same topology is one in-tree module: :class:`EngineServer` holds
the resident (sharded) TPU engine and speaks the same protocol to
:class:`~reval_tpu.inference.client.HTTPClientBackend`.

Lifecycle hardening lives alongside: typed serving errors (429/503/504
with stable codes), token-denominated admission control, per-request
deadlines, a no-progress watchdog, a readiness (``/readyz``) vs liveness
(``/healthz``) split, and graceful drain — see ``session.py`` and
``server.py`` docstrings, and :class:`~.mock_engine.MockStepEngine` for
the zero-TPU smoke target behind ``serve --mock``.
"""

from .autoscaler import Autoscaler, LocalReplicaProcess, ScalingPolicy
from .errors import (
    DeadlineExceeded,
    Draining,
    EngineWedged,
    FleetUnavailable,
    Overloaded,
    ServingError,
)
from .mock_engine import MockStepEngine
from .router import FleetRouter
from .server import EngineServer, serve_config, warmup_engine
from .session import ContinuousSession, MultiSession
from .supervisor import ReplicaPool, SupervisedReplica, Supervisor

__all__ = ["EngineServer", "serve_config", "warmup_engine",
           "ContinuousSession", "MultiSession", "MockStepEngine",
           "FleetRouter", "Supervisor", "SupervisedReplica", "ReplicaPool",
           "Autoscaler", "ScalingPolicy", "LocalReplicaProcess",
           "ServingError", "Overloaded",
           "Draining", "EngineWedged", "DeadlineExceeded",
           "FleetUnavailable"]
