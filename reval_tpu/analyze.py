"""Valid-test-case statistics (reference analyze_testcases.py:1-48).

Summarises a ``*.valid_test_cases.*.json`` artifact written by a
trace-of-thoughts run: how many benchmark tasks survived validation, how
many inputs per task, how many probe samples per task and per
(task, input).  Entries are the probe keys — 3-tuples
``(task, input, line)`` for coverage/path, 4-tuples
``(task, input, var, line)`` for state.
"""

from __future__ import annotations

import json
from collections import defaultdict

__all__ = ["analyze_valid_test_cases"]


def analyze_valid_test_cases(path: str) -> dict:
    with open(path) as f:
        entries = [tuple(e) for e in json.load(f)]
    per_task: dict = defaultdict(lambda: {"inputs": set(), "samples": set()})
    per_pair: dict = defaultdict(set)
    for entry in entries:
        task_idx, input_idx, *probe = entry
        per_task[task_idx]["inputs"].add(input_idx)
        per_task[task_idx]["samples"].add((input_idx, *probe))
        per_pair[(task_idx, input_idx)].add(tuple(probe))
    num_tasks = len(per_task)
    total_samples = sum(len(v["samples"]) for v in per_task.values())
    return {
        "num_tasks": num_tasks,
        "avg_input_idxs_per_task":
            sum(len(v["inputs"]) for v in per_task.values()) / num_tasks if num_tasks else 0.0,
        "avg_sample_per_task": total_samples / num_tasks if num_tasks else 0.0,
        "avg_sample_per_task_idx":
            sum(len(s) for s in per_pair.values()) / len(per_pair) if per_pair else 0.0,
        "total_samples": total_samples,
    }
