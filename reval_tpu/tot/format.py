"""Trace-of-thoughts dump format: layout, reader, writer.

One dump file per (dataset, task_idx, input_idx):

    <base_dir>/<run_name>/<dataset>/task_<task_idx>_input_<input_idx>.trace.jsonl

JSONL records, in order:

- header   ``{"kind": "header", "code_sha256": …, "invocation": …}`` —
  identifies the exact program+input the trace claims to simulate; the
  parser's validation phase checks it against the benchmark row.
- step     ``{"kind": "step", "step": n, "lineno": L, "values": {var: "repr; type"}}``
  — the model's simulated visit to 1-indexed line ``L`` with its belief
  about variable values *on arrival* (same pre-line semantics as the
  ground-truth tracer).  A labeled dump adds ``"label": {"lineno": …,
  "values": …}`` carrying the ground truth for the same step.
- end      ``{"kind": "end", "return": "repr; type" | null}``.

Values are rendered ``"repr; typename"`` — the state task's answer grammar
— so state answers lift straight out of the dump.

:func:`write_trace_dump` can build a dump from a ground-truth
:class:`~reval_tpu.dynamics.ExecutionTrace` (labels == steps), which both
documents the format and gives tests a perfect-oracle fixture; real model
dumps come from an external tracing harness writing the same schema.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

__all__ = ["trace_dump_path", "write_trace_dump", "read_dump", "format_value", "code_digest"]


def code_digest(code: str) -> str:
    return hashlib.sha256(code.encode()).hexdigest()[:16]


def format_value(value) -> str:
    """Render one runtime value in the state-answer grammar ``repr; type``."""
    return f"{value!r}; {type(value).__name__}"


def trace_dump_path(base_dir: str | Path, run_name: str, dataset: str,
                    task_idx: int, input_idx: int) -> Path:
    return (Path(base_dir) / run_name / dataset /
            f"task_{task_idx}_input_{input_idx}.trace.jsonl")


def write_trace_dump(
    base_dir: str | Path,
    run_name: str,
    dataset: str,
    task_idx: int,
    input_idx: int,
    *,
    code: str,
    invocation: str,
    trace=None,
    steps: list[dict] | None = None,
    with_labels: bool = True,
    end_return: object = ...,
) -> Path:
    """Write one dump.  ``trace`` (an ExecutionTrace) supplies ground-truth
    steps/labels; ``steps`` overrides the model-side steps (tests use this
    to simulate an imperfect model while keeping truthful labels);
    ``end_return`` overrides the end record's return value (model dumps
    record the MODEL's claimed return, not the truth's) — default keeps
    the trace-derived value."""
    path = trace_dump_path(base_dir, run_name, dataset, task_idx, input_idx)
    path.parent.mkdir(parents=True, exist_ok=True)

    truth_steps: list[dict] = []
    ret_value = None
    if trace is not None:
        for n, state in enumerate(trace):
            values = {}
            for name, value in state.locals.items():
                try:
                    values[name] = format_value(value)
                except Exception:
                    continue  # unrepr-able values stay out of the dump
                # flatten object attributes so `self.attr` probes resolve
                if name == "self":
                    for attr, attr_value in getattr(value, "__dict__", {}).items():
                        try:
                            values[f"self.{attr}"] = format_value(attr_value)
                        except Exception:
                            continue
            truth_steps.append({"step": n, "lineno": state.lineno + 1, "values": values})
        from ..dynamics import Nil

        for state in trace:
            if state.return_value is not Nil:
                try:
                    ret_value = format_value(state.return_value)
                except Exception:
                    ret_value = None
    model_steps = steps if steps is not None else truth_steps
    if end_return is not ...:
        ret_value = end_return

    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": "header",
            "code_sha256": code_digest(code),
            "invocation": invocation.strip(),
        }) + "\n")
        for n, step in enumerate(model_steps):
            rec = {"kind": "step", "step": n,
                   "lineno": step["lineno"], "values": step.get("values", {})}
            if with_labels and n < len(truth_steps):
                rec["label"] = {"lineno": truth_steps[n]["lineno"],
                                "values": truth_steps[n]["values"]}
            f.write(json.dumps(rec) + "\n")
        f.write(json.dumps({"kind": "end", "return": ret_value}) + "\n")
    return path


def read_dump(path: str | Path) -> tuple[dict, list[dict], dict | None]:
    """Parse a dump into (header, steps, end) with schema checks."""
    header = None
    steps: list[dict] = []
    end = None
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            kind = rec.get("kind")
            if kind == "header":
                header = rec
            elif kind == "step":
                if not isinstance(rec.get("lineno"), int):
                    raise ValueError(f"step record without integer lineno: {rec}")
                steps.append(rec)
            elif kind == "end":
                end = rec
            else:
                raise ValueError(f"unknown record kind {kind!r} in {path}")
    if header is None:
        raise ValueError(f"dump {path} has no header record")
    return header, steps, end
