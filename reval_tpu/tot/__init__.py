"""Trace-of-thoughts (ToT) evaluation mode.

Instead of asking the model a question *about* one probe, ToT mode has the
model (or an external tracing harness) produce a full simulated execution
trace — its "trace of thoughts" — per (task, input).  Answers for the
coverage/path/state tasks are then *extracted* from that one dump and
scored against the tracer ground truth.

The reference gates this mode on an external package that is absent from
its snapshot (``trace_of_thoughts_parser``, imported at reference
evaluation.py:26, expected from a separate checkout per
cmdlines/evaluation_sbatch.sh:10-11) — only the driver side survives
(reference evaluation.py:303-351,455-504,772-828).  This package supplies
the missing half in-tree: a documented dump format (:mod:`.format`), the
parser with the reference's error taxonomy (:mod:`.parser`), and the
two-phase validate-then-answer protocol driven by the task engine
(tasks/base.py: ``TaskRunner.run_tot``).
"""

from .format import (
    format_value,
    read_dump,
    trace_dump_path,
    write_trace_dump,
)
from .generate import (
    build_trace_prompt,
    generate_trace_dumps,
    parse_trace_generation,
)
from .oracle import capture_pairs, write_oracle_dumps
from .parser import (
    EmptyAnswerError,
    TraceOfThoughtsParser,
    ValidationError,
)

__all__ = [
    "EmptyAnswerError",
    "TraceOfThoughtsParser",
    "ValidationError",
    "build_trace_prompt",
    "capture_pairs",
    "format_value",
    "generate_trace_dumps",
    "parse_trace_generation",
    "read_dump",
    "trace_dump_path",
    "write_oracle_dumps",
    "write_trace_dump",
]
