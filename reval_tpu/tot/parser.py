"""TraceOfThoughtsParser: extract task answers from trace dumps.

In-tree replacement for the reference's absent external parser (import at
reference evaluation.py:26).  The driver-side protocol it must serve
(reference evaluation.py:303-351,455-504,772-828):

- ``validate_task(...)`` — raise :class:`ValidationError` unless the dump
  exists, parses, and matches the benchmark program + invocation;
- ``process_task(..., use_labels)`` — return ``(answer, rendered_trace)``;
  with ``use_labels=True`` answers come from the ground-truth label
  channel (the validation pass: a correct parse over labels must
  reproduce the known ground truth, or the test case is discarded);
  with ``use_labels=False`` answers come from the model's own steps;
- raise :class:`EmptyAnswerError` when the dump holds no usable answer —
  the driver maps the taxonomy to VALIDATION_ERROR / EMPTY_ANSWER_ERROR /
  GENERAL_ERROR records (reference evaluation.py:333-350).

Answer spaces: coverage → bool; path → 1-indexed successor line or -1
(trace end); state → ``"repr; type"`` string for the probed variable
*after* the line (last visit wins, pre-line semantics ⇒ read from the
following step, falling back to the final step for a trace-ending line).
"""

from __future__ import annotations

import ast
from pathlib import Path

from .format import code_digest, format_value, read_dump, trace_dump_path

__all__ = ["TraceOfThoughtsParser", "ValidationError", "EmptyAnswerError"]


class ValidationError(Exception):
    """Dump missing/malformed or inconsistent with the benchmark row."""


class EmptyAnswerError(Exception):
    """Dump parsed fine but contains no answer for this probe."""


class TraceOfThoughtsParser:
    def __init__(self, base_dir: str | Path, dataset: str, run_name: str):
        self.base_dir = Path(base_dir)
        self.dataset = dataset
        self.run_name = run_name
        self._cache: dict[tuple[int, int], tuple[dict, list[dict], dict | None]] = {}
        self._render_cache: dict[tuple[int, int, bool], str] = {}

    # -- dump access -------------------------------------------------------
    def dump_path(self, task_idx: int, input_idx: int) -> Path:
        return trace_dump_path(self.base_dir, self.run_name, self.dataset,
                               task_idx, input_idx)

    def _load(self, task_idx: int, input_idx: int):
        key = (task_idx, input_idx)
        if key not in self._cache:
            path = self.dump_path(task_idx, input_idx)
            if not path.exists():
                raise ValidationError(f"trace dump not found: {path}")
            try:
                self._cache[key] = read_dump(path)
            except (ValueError, OSError) as e:
                raise ValidationError(f"malformed trace dump {path}: {e}") from e
        return self._cache[key]

    # -- protocol ----------------------------------------------------------
    def validate_task(self, task_idx: int, input_idx: int, *, code: str,
                      invocation: str) -> None:
        header, steps, _ = self._load(task_idx, input_idx)
        if header.get("code_sha256") != code_digest(code):
            raise ValidationError(
                f"dump {task_idx}:{input_idx} was produced for different code "
                f"(digest {header.get('code_sha256')!r})")
        if header.get("invocation", "").strip() != invocation.strip():
            raise ValidationError(
                f"dump {task_idx}:{input_idx} invocation mismatch: "
                f"{header.get('invocation')!r} != {invocation!r}")

    def process_task(self, task_idx: int, input_idx: int, task_name: str,
                     *, lineno: int, var: str | None = None,
                     use_labels: bool) -> tuple[object, str]:
        """Extract the ``task_name`` answer for probe line ``lineno``
        (1-indexed) — and ``var`` for state — from the dump."""
        _, steps, end = self._load(task_idx, input_idx)
        seq = self._line_sequence(steps, use_labels)
        if not seq:
            raise EmptyAnswerError(f"dump {task_idx}:{input_idx} has no steps")
        rendered = self.render(task_idx, input_idx, use_labels)
        if task_name == "coverage":
            return lineno in seq, rendered
        if task_name == "path":
            return self._next_line(seq, lineno), rendered
        if task_name == "state":
            assert var is not None, "state probes carry a variable"
            return self._state_answer(steps, lineno, var, use_labels), rendered
        raise ValueError(f"trace-of-thoughts does not cover task {task_name!r}")

    # -- extraction --------------------------------------------------------
    @staticmethod
    def _channel(step: dict, use_labels: bool) -> dict | None:
        if use_labels:
            return step.get("label")
        return step

    def _line_sequence(self, steps: list[dict], use_labels: bool) -> list[int]:
        seq = []
        for step in steps:
            chan = self._channel(step, use_labels)
            if chan is not None and isinstance(chan.get("lineno"), int):
                seq.append(chan["lineno"])
        return seq

    @staticmethod
    def _next_line(seq: list[int], lineno: int) -> int:
        """First successor of ``lineno`` in the simulated trace, -1 when the
        trace ends there (or the line never executes — the uncovered
        convention, reference dynamics.py:322-323)."""
        for i, line in enumerate(seq):
            if line == lineno:
                return seq[i + 1] if i + 1 < len(seq) else -1
        return -1

    def _state_answer(self, steps: list[dict], lineno: int, var: str,
                      use_labels: bool) -> str:
        """``repr; type`` of ``var`` after the last visit to ``lineno``.

        ``var`` may be a compound probe expression — ``self.attr`` (dumps
        carry flattened dotted keys), ``(i, j)``, ``arr[k]`` — evaluated
        over the step's recorded values (same expression space as the
        ground-truth VarInterpreter, reference dynamics.py:164-223)."""
        answer = None
        chans = [c for c in (self._channel(s, use_labels) for s in steps) if c is not None]
        for i, chan in enumerate(chans):
            if chan.get("lineno") != lineno:
                continue
            after = chans[i + 1] if i + 1 < len(chans) else chan
            value = self._lookup_var(after.get("values", {}), var)
            if value is not None:
                answer = value
        if answer is None:
            raise EmptyAnswerError(f"variable {var!r} never recorded after line {lineno}")
        return answer

    @staticmethod
    def _lookup_var(values: dict[str, str], var: str) -> str | None:
        """Resolve a probe expression against one step's value map."""
        if var in values:          # plain name or flattened self.attr
            return values[var]
        try:
            node = ast.parse(var, mode="eval").body
        except SyntaxError:
            return None

        def ev(n):
            if isinstance(n, ast.Constant):
                return n.value
            if isinstance(n, (ast.Name, ast.Attribute)):
                key = ast.unparse(n)
                if key not in values:
                    raise KeyError(key)
                return ast.literal_eval(values[key].rsplit(";", 1)[0].strip())
            if isinstance(n, ast.Tuple):
                return tuple(ev(e) for e in n.elts)
            if isinstance(n, ast.Subscript):
                return ev(n.value)[ev(n.slice)]
            if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.USub):
                return -ev(n.operand)
            raise KeyError(ast.dump(n))

        try:
            return format_value(ev(node))
        except Exception:
            return None

    def render(self, task_idx: int, input_idx: int, use_labels: bool = False) -> str:
        """Human-readable form of the simulated trace (stored as the
        ``generated`` field of result records).  Cached per dump+channel —
        the two-phase protocol renders each dump many times."""
        cache_key = (task_idx, input_idx, use_labels)
        if cache_key in self._render_cache:
            return self._render_cache[cache_key]
        _, steps, end = self._load(task_idx, input_idx)
        lines = []
        for step in steps:
            chan = self._channel(step, use_labels)
            if chan is None:
                continue
            vals = ", ".join(f"{k}={v}" for k, v in chan.get("values", {}).items())
            lines.append(f"[{step['step']}] line {chan.get('lineno')}: {vals}")
        if end is not None and end.get("return") is not None:
            lines.append(f"return {end['return']}")
        rendered = "\n".join(lines)
        self._render_cache[cache_key] = rendered
        return rendered
