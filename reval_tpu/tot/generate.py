"""Model-driven trace dumps: prompt → generation → parsed dump → scoring.

Closes the loop the reference never shipped.  Its trace-of-thoughts mode
expects dumps from an external tracing harness (a `custom-trepan` checkout
on PYTHONPATH, reference cmdlines/evaluation_sbatch.sh:10-11, with the
parser module absent from the snapshot — SURVEY §2.25).  Here the model
ITSELF produces the trace: a constrained prompt asks it to simulate
execution step by step in a line grammar, the generation is parsed into
the dump schema (tot/format.py), ground-truth labels are attached from the
tracer, and the standard two-phase tot scoring (``TaskRunner.run_tot``)
consumes the dumps — engine output to tot metrics with no oracle anywhere.

The grammar (one line per executed source line, `` || ``-separated values
so reprs may contain commas):

    step <n>: line <L> || <var> = <repr>; <type> || ...
    ...
    return <repr>; <type>
    [/TRACE]

Values follow pre-line semantics — the variable bindings on ARRIVAL at
the line — matching the ground-truth tracer (reference dynamics.py:94-135).
"""

from __future__ import annotations

import re

from .format import write_trace_dump
from .oracle import capture_pairs

__all__ = ["build_trace_prompt", "parse_trace_generation",
           "generate_trace_dumps", "render_trace_text"]

TRACE_STOP = "[/TRACE]"

_INSTRUCTIONS = """\
You are an expert at Python programming. Simulate the execution of the \
program below on the given invocation, step by step. Emit one line per \
executed source line, IN EXECUTION ORDER, using exactly this format:

step <n>: line <lineno> || <name> = <repr>; <type> || ...

where <lineno> is the 1-indexed source line about to execute and the \
value list shows every local variable ON ARRIVAL at that line (before it \
runs). Render values as Python reprs followed by `; ` and the type name. \
After the last step, emit `return <repr>; <type>` with the function's \
return value, then `[/TRACE]`.

Example:
[PYTHON]
1\tdef add_one(x):
2\t    y = x + 1
3\t    return y
[/PYTHON]
The invocation: add_one(4)
[TRACE]
step 0: line 2 || x = 4; int
step 1: line 3 || x = 4; int || y = 5; int
return 5; int
[/TRACE]

Now simulate this program:
[PYTHON]
{code}[/PYTHON]
The invocation: {invocation}
[TRACE]
"""

_STEP_RE = re.compile(r"step\s+(\d+)\s*:\s*line\s+(\d+)\s*(.*)")


def build_trace_prompt(code: str, invocation: str) -> str:
    numbered = "".join(f"{i + 1}\t{line}\n"
                       for i, line in enumerate(code.split("\n")))
    return _INSTRUCTIONS.format(code=numbered, invocation=invocation)


def parse_trace_generation(text: str) -> tuple[list[dict], str | None]:
    """Generation text → (steps, return value) in the dump step schema.

    Tolerant by design: unparseable lines are skipped (a malformed trace
    becomes a short/empty dump, which the two-phase protocol then scores
    as invalid/empty — the reference's error taxonomy, not a crash)."""
    if "[TRACE]" in text:
        text = text.split("[TRACE]", 1)[1]
    text = text.split(TRACE_STOP, 1)[0]
    steps: list[dict] = []
    ret: str | None = None
    for raw in text.splitlines():
        line = raw.strip()
        m = _STEP_RE.match(line)
        if m:
            values: dict[str, str] = {}
            for pair in m.group(3).split("||"):
                pair = pair.strip(" |")
                if "=" not in pair:
                    continue
                name, _, value = pair.partition("=")
                if name.strip():
                    values[name.strip()] = value.strip()
            steps.append({"lineno": int(m.group(2)), "values": values})
        elif line.startswith("return ") and ret is None:
            ret = line[len("return "):].strip() or None
    return steps, ret


def render_trace_text(trace) -> str:
    """ExecutionTrace → grammar text (what a perfect model would emit).
    Used by tests to drive the FULL text path without an oracle dump."""
    from .format import format_value

    lines = []
    ret = None
    from ..dynamics import Nil

    for n, state in enumerate(trace):
        values = []
        for name, value in state.locals.items():
            try:
                values.append(f"{name} = {format_value(value)}")
            except Exception:
                continue
            if name == "self":
                for attr, av in getattr(value, "__dict__", {}).items():
                    try:
                        values.append(f"self.{attr} = {format_value(av)}")
                    except Exception:
                        continue
        lines.append(f"step {n}: line {state.lineno + 1} || " + " || ".join(values))
        if state.return_value is not Nil:
            try:
                ret = format_value(state.return_value)
            except Exception:
                ret = None
    lines.append(f"return {ret if ret is not None else 'None; NoneType'}")
    lines.append(TRACE_STOP)
    return "\n".join(lines)


def generate_trace_dumps(backend, dataset: str, base_dir: str, run_name: str,
                         *, split: str | None = None,
                         max_items: int | None = None,
                         sandbox_timeout: float = 120.0,
                         progress: bool = True) -> int:
    """Drive ``backend`` over every (task, input) pair: trace prompt →
    generation → parsed dump with ground-truth labels.  Returns the dump
    count; score with a ``prompt_type="tot"`` task run over the same
    base_dir/run_name."""
    pairs = capture_pairs(dataset, split=split, max_items=max_items,
                          sandbox_timeout=sandbox_timeout)
    keys = list(pairs)
    prompts = [build_trace_prompt(pairs[k][0], pairs[k][1]) for k in keys]
    if progress:
        print(f"[tot-generate] {len(prompts)} trace prompts → backend")
    # trace generations stop at [/TRACE], not the QA tasks' [/ANSWER]
    saved_stop = backend.config.stop
    backend.config.stop = [TRACE_STOP]
    try:
        gens = backend.infer_many(prompts)
    finally:
        backend.config.stop = saved_stop
    for key, gen in zip(keys, gens):
        code, invocation, trace = pairs[key]
        steps, ret = parse_trace_generation(gen)
        write_trace_dump(base_dir, run_name, dataset, key[0], key[1],
                         code=code, invocation=invocation, trace=trace,
                         steps=steps, with_labels=True, end_return=ret)
    if progress:
        print(f"[tot-generate] wrote {len(keys)} dumps under "
              f"{base_dir}/{run_name}/{dataset}")
    return len(keys)
