"""Oracle trace dumps: ground-truth ToT dumps for a dataset slice.

An external tracing harness normally produces the dumps; this module
generates *perfect* ones from the tracer ground truth instead.  Useful as
(a) executable documentation of the dump format, (b) an upper-bound
baseline run (every test case validates, every answer correct), and
(c) the fixture generator for tests.

It reuses the real task planner so dump keys, invocation strings, and code
bodies match what ``TaskRunner.run_tot`` will look up exactly.
"""

from __future__ import annotations

from .format import write_trace_dump

__all__ = ["write_oracle_dumps", "capture_pairs"]


def capture_pairs(dataset: str, *, split: str | None = None,
                  max_items: int | None = None,
                  sandbox_timeout: float = 120.0) -> dict[tuple, tuple]:
    """{(task_idx, input_idx): (code, invocation, ExecutionTrace)} for every
    benchmark pair — planned by the REAL task planner, so keys, invocation
    strings, and code bodies match what ``run_tot`` will look up exactly.
    Shared by the oracle writer and the model-driven generator."""
    from ..tasks.coverage import CoverageTask

    class _DumpPlanner(CoverageTask):
        def __init__(self):
            super().__init__(prompt_type="direct", dataset=dataset, split=split,
                             mock=True, progress=False, max_items=max_items,
                             sandbox_timeout=sandbox_timeout)
            self.captured: dict[tuple, tuple] = {}

        def _append_probe_job(self, jobs, gen_entry, *, states, probe, code,
                              codelines, invocation, invocation_abbr,
                              numbered, tot_key=None):
            self.captured[tot_key] = (code, invocation, states)

    planner = _DumpPlanner()
    planner._plan()
    return planner.captured


def write_oracle_dumps(dataset: str, base_dir: str, run_name: str, *,
                       split: str | None = None, max_items: int | None = None,
                       sandbox_timeout: float = 120.0) -> int:
    """Write one dump per (task, input) pair of ``dataset``; returns count."""
    pairs = capture_pairs(dataset, split=split, max_items=max_items,
                          sandbox_timeout=sandbox_timeout)
    for (task_idx, input_idx), (code, invocation, trace) in pairs.items():
        write_trace_dump(base_dir, run_name, dataset, task_idx, input_idx,
                         code=code, invocation=invocation, trace=trace,
                         with_labels=True)
    return len(pairs)
