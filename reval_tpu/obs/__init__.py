"""Observability: metrics registry, latency histograms, span tracing.

``metrics`` carries the process-wide metric namespace (``METRICS``) and
the mergeable :class:`MetricsRegistry` that backs
:class:`~reval_tpu.inference.tpu.engine.EngineStats`; ``trace`` emits
Chrome-trace/Perfetto span trees per served request (``serve
--trace-out``).  The serving server exposes both: ``GET /metrics``
(Prometheus text) and ``GET /statusz`` (JSON snapshot).
"""

from .metrics import METRICS, LATENCY_BUCKETS, MetricsRegistry
from .trace import Tracer

__all__ = ["METRICS", "LATENCY_BUCKETS", "MetricsRegistry", "Tracer"]
