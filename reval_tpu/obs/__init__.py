"""Observability: metrics, span tracing, structured logs, flight recorder.

``metrics`` carries the process-wide metric namespace (``METRICS``) and
the mergeable :class:`MetricsRegistry` that backs
:class:`~reval_tpu.inference.tpu.engine.EngineStats`; ``trace`` emits
Chrome-trace/Perfetto span trees per served request (``serve
--trace-out``); ``logging`` is the structured JSON event log (one
declared-namespace event per line, ``EVENTS`` linted like ``METRICS``);
``flightrec`` is the always-on per-step ring buffer behind crash-dump
postmortem bundles; ``determinism`` is the cross-backend divergence
matrix (the determinism observatory — ``tools/determinism_matrix.py``
is its CLI).  The serving server exposes all of it: ``GET /metrics``
(Prometheus text), ``GET /statusz`` (JSON snapshot), and ``GET
/debugz`` (a live postmortem bundle).

``determinism`` is imported lazily (it pulls engines at run time, not
import time) — ``from reval_tpu.obs import determinism`` when needed.
"""

from .flightrec import FlightRecorder, PostmortemWriter
from .logging import EVENTS, log_event
from .metrics import METRICS, LATENCY_BUCKETS, MetricsRegistry
from .trace import Tracer

__all__ = ["METRICS", "LATENCY_BUCKETS", "MetricsRegistry", "Tracer",
           "EVENTS", "log_event", "FlightRecorder", "PostmortemWriter"]
