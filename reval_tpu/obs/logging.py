"""Structured JSON logging: one event, one JSON object, one line.

The serving stack's operational logging used to be ad-hoc — a
``logging.warning`` here, a bare ``print`` there — which meant a
postmortem grep had to know five message formats and could correlate
nothing.  This module replaces those call sites with :func:`log_event`:

    log_event("session.watchdog_trip", level="error",
              watchdog_s=120.0, pending=3)

emits exactly one line of JSON to stderr::

    {"ts": "2026-08-03T12:00:00.123+00:00", "level": "error",
     "component": "session", "event": "session.watchdog_trip",
     "fields": {"watchdog_s": 120.0, "pending": 3}}

Contracts (mirroring the metrics registry's namespace discipline):

- **One namespace.**  Every event name is declared ONCE in :data:`EVENTS`
  (``component.event``; the component is the prefix).  ``tools/
  check_metrics.py`` lints call-site literals against the table in both
  directions — an event cannot ship undeclared, or stay declared after
  its last call site is deleted.  ``log_event`` itself never raises on an
  unknown name (a typo in an ``except`` block must not mask the real
  error); the lint is the enforcement.
- **Correlation.**  ``request_id`` is a first-class key: the server, the
  session, and the client's retry loop all pass the wire
  ``X-Request-Id``, so one grep assembles a request's full story across
  both sides.
- **Bounded recall.**  The last :data:`RING_CAPACITY` events are kept in
  an in-process ring regardless of the emission level — the flight
  recorder's postmortem bundles (:mod:`~reval_tpu.obs.flightrec`) attach
  them as the ``recent_logs`` section, so a crash dump carries the log
  context that led up to it even when stderr scrolled away.

Knobs: ``REVAL_TPU_LOG_LEVEL`` (default ``info``) filters emission;
``REVAL_TPU_LOG=0`` silences stderr entirely (the ring still records, so
postmortems stay complete).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

from ..env import env_flag, env_str

__all__ = ["EVENTS", "RING_CAPACITY", "log_event", "recent"]

#: The canonical event namespace: name -> one-line meaning.  Declared
#: once, linted by ``tools/check_metrics.py`` against every
#: ``log_event("...")`` literal in the tree (both directions) and
#: against the README events table.
EVENTS: dict[str, str] = {
    # client side (inference/client.py, resilience/retry.py)
    "client.retry": "an HTTP attempt failed and will be retried",
    "client.wait": "waiting for server readiness during the handshake",
    "client.receipt_invalid": "a response's X-Reval-Receipt failed "
                              "verification (unparseable, wrong schema, "
                              "or header/body disagreement)",
    # engine (inference/tpu/paged_engine.py)
    "engine.preempt": "a running sequence was preempted on pool exhaustion",
    "engine.deadlock": "nothing running or admissible while work remains",
    "engine.ragged_fallback": "a ragged backend was requested but the "
                              "engine fell back to split dispatch",
    # jit-discipline tracker (analysis/jitcheck.py)
    "jit.recompile": "a tracked jit entry compiled a new variant past "
                     "its declared warmup budget",
    # mesh-discipline guard (analysis/shardcheck.py)
    "shard.respec": "a guarded jit entry saw an array whose actual "
                    "sharding diverged from the declared spec "
                    "(unintended cross-device reshard)",
    # persistent AOT executable cache (inference/tpu/aot_cache.py)
    "aot.cache_hit": "a tracked jit variant loaded from the persistent "
                     "AOT cache (compile skipped)",
    "aot.cache_miss": "a tracked jit variant compiled fresh (cold, "
                      "stale, or mismatched cache entry)",
    "aot.cache_error": "an AOT cache entry failed to load or store "
                       "(corrupt/mismatched/unwritable); degraded to a "
                       "fresh compile",
    "aot.unsupported": "AOT serialize/export declined: this jax build "
                       "cannot export the program (Mosaic canary/"
                       "jax.export)",
    "aot.gc": "the AOT cache evicted LRU entries past its size bound",
    # kernel CI harness (reval_tpu/kernelbench.py)
    "kernelbench.cell_retry": "a kernel-CI cell attempt failed transient "
                              "(wedge kill / timeout / device loss) and "
                              "was retried under backoff",
    "kernelbench.cell_stale": "a kernel-CI cell exhausted its attempts "
                              "and degraded to a stale-marked entry "
                              "carrying its last-known value + commit",
    "kernelbench.regression": "the kernel-CI gate found HEAD slower than "
                              "the incumbent winner cell beyond the "
                              "noise band (round exits 1)",
    "kernelbench.pick": "the kernel-CI leaderboard emitted an autotune "
                        "serving-config pick for the winning cell",
    # serving session (serving/session.py)
    "spec.wedge": "a request's speculative drafter faulted; the row "
                  "degrades to plain decode for the rest of the request",
    "session.watchdog_trip": "no engine progress past watchdog_s; "
                             "pending submissions failed typed",
    "session.driver_error": "the driver tick raised; in-flight submissions "
                            "failed and the drive state was reset",
    "session.deadline_expired": "a submission was cancelled at its deadline",
    "session.deadline_storm": "several deadlines expired in one sweep",
    "session.drain_stuck": "the driver did not exit within the close timeout",
    "session.postmortem": "a postmortem bundle was written (or failed)",
    "session.snapshot_written": "a warm-state snapshot was written at drain",
    "session.snapshot_restored": "a warm-state snapshot was replayed "
                                 "through prefill at boot",
    "session.snapshot_error": "a warm-state snapshot could not be "
                              "written or read (corrupt/unwritable); "
                              "the engine boots cold",
    "session.receipt_error": "a completed submission's reproducibility "
                             "receipt callback raised; the response "
                             "ships unreceipted, never fails",
    # hierarchical KV tiering (inference/tpu/kv_tiers.py)
    "kvtier.degrade": "a tier fault (integrity/io/timeout rung) dropped "
                      "the page; it recomputes from its token chain via "
                      "prefill — never wrong KV",
    "kvtier.integrity_failure": "a promotion's payload failed its "
                                "spill-time sha256 (bit rot, torn "
                                "write, or injected corruption)",
    "kvtier.spill_error": "a spill copy faulted on the copier thread; "
                          "the page loses tier warmth, never "
                          "correctness",
    "kvtier.disk_error": "a disk-tier page file could not be written "
                         "or read; the drain/boot degrades gracefully",
    # crash-loop supervisor (serving/supervisor.py)
    "supervisor.spawn": "the supervisor (re)spawned the child server",
    "supervisor.death": "the supervised child server died; a postmortem "
                        "bundle was written",
    "supervisor.sticky_failed": "the rapid-death budget was spent; the "
                                "supervisor stopped respawning",
    # HTTP server (serving/server.py)
    "server.request_error": "a completions request failed server-side",
    "server.drained": "graceful drain finished; lifecycle counters attached",
    "server.trace_written": "the span trace file was written at drain",
    "server.trace_error": "writing the span trace file failed",
    # fleet router (serving/router.py)
    "router.eject": "a replica was ejected after consecutive failures",
    "router.recover": "an ejected replica rejoined (half-open probe or "
                      "clean health poll)",
    "router.failover": "a forward was re-routed to a non-primary replica",
    "router.shed": "the router shed a request fleet-wide (no replica "
                   "could take it)",
    "router.drain": "an operator drained or rejoined a replica",
    "router.fingerprint_skew": "ready replicas disagreed on their "
                               "receipt config fingerprint (half-"
                               "upgraded fleet; edge-triggered)",
    "router.resize": "the replica membership changed at runtime "
                     "(admin add_replica/remove_replica rebuilt the "
                     "hash ring)",
    # SLO-driven autoscaler (serving/autoscaler.py)
    "autoscale.up": "the autoscaler spawned a replica and added it to "
                    "the router ring",
    "autoscale.down": "the autoscaler drained a replica, removed it "
                      "from the ring, and stopped it",
    "autoscale.blocked": "an indicated scaling action was suppressed "
                         "(cooldown, min/max replica bound, or a "
                         "sticky-failed spawn)",
    # open-loop load generator (tools/loadgen.py)
    "loadgen.start": "an open-loop load run started (arrival schedule "
                     "fixed up front)",
    "loadgen.done": "an open-loop load run finished; the artifact "
                    "carries goodput/SLO attainment",
    "loadgen.lost": "a generated request exhausted its retry/deadline "
                    "budget without completing",
    # fleet (fleet.py)
    "fleet.resume_skip": "a journaled (repeat, task) chunk was skipped",
    "fleet.lost_prompts": "prompts exhausted retries and took the sentinel",
    "fleet.snapshot_error": "writing fleet_metrics.json failed",
}

#: events retained in-process for postmortem bundles
RING_CAPACITY = 512

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "error": logging.ERROR}

_ring: deque = deque(maxlen=RING_CAPACITY)      # guarded-by: _ring_lock
_ring_lock = threading.Lock()
_logger = logging.getLogger("reval_tpu.events")
# unguarded: worst case two racing first calls both configure the (idempotent)
# sink; the handler-presence check keeps it single
_configured = False


def _ensure_sink() -> logging.Logger:
    """Attach the raw-JSON stderr handler once (idempotent).  The logger
    does not propagate: the line IS the record — a root formatter
    wrapping it would break one-object-per-line."""
    global _configured
    if not _configured:
        if not _logger.handlers:
            handler = logging.StreamHandler()
            handler.setFormatter(logging.Formatter("%(message)s"))
            _logger.addHandler(handler)
        _logger.propagate = False
        level = env_str("REVAL_TPU_LOG_LEVEL", "info").lower()
        _logger.setLevel(_LEVELS.get(level, logging.INFO))
        if not env_flag("REVAL_TPU_LOG", True):
            _logger.setLevel(logging.CRITICAL + 1)
        _configured = True
    return _logger


def _iso_now() -> str:
    t = time.time()
    ms = int((t - int(t)) * 1000)
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(t)) + f".{ms:03d}"


def _jsonable(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


def log_event(event: str, *, level: str = "info",
              request_id: str | None = None, exc: BaseException | None = None,
              **fields) -> dict:
    """Record one structured event; returns the record dict (tests and
    callers that embed it in a bundle use the return value).

    ``event`` must be a declared :data:`EVENTS` name (``component.event``
    — the component is derived from the prefix); unknown names still log
    (flagged by the lint, never a runtime crash in an error path).
    ``exc`` attaches ``repr(exc)`` as the ``error`` field.
    """
    rec: dict = {"ts": _iso_now(), "level": level,
                 "component": event.split(".", 1)[0], "event": event}
    if request_id is not None:
        rec["request_id"] = str(request_id)
    if exc is not None:
        rec["error"] = repr(exc)
    if fields:
        rec["fields"] = {k: _jsonable(v) for k, v in fields.items()}
    with _ring_lock:
        _ring.append(rec)
    logger = _ensure_sink()
    lvl = _LEVELS.get(level, logging.INFO)
    if logger.isEnabledFor(lvl):
        logger.log(lvl, json.dumps(rec, default=str))
    return rec


def recent(n: int | None = None, min_level: str = "debug") -> list[dict]:
    """The last ``n`` (default: all retained) events at or above
    ``min_level``, oldest first — the ``recent_logs`` postmortem
    section."""
    floor = _LEVELS.get(min_level, logging.DEBUG)
    with _ring_lock:
        events = list(_ring)
    events = [e for e in events if _LEVELS.get(e["level"], 20) >= floor]
    return events if n is None else events[-n:]
