"""Span tracing: Chrome-trace / Perfetto JSON for the serving stack.

One request = one span tree.  The serving session records, per prompt of
each submission, the lifecycle stamps the engine already keeps on its
``_Request`` (submit, admission, first token, done) and this module turns
them into nested complete ("X") events:

    request                          [submit ........................ done]
    ├── queue_wait                   [submit .. admit]
    └── generate                              [admit ............... done]
        ├── first_token                       [admit .. first]
        └── decode                                    [first ....... done]

Each (request_id, prompt index) pair gets its own trace ``tid`` with a
``thread_name`` metadata event naming it, so Perfetto / chrome://tracing
shows one labelled track per request and nesting is purely by time
containment — no duplicate-depth overlaps.

Cost model: recording is a list append under a lock, only on request
*completion* (and only when a tracer is installed at all — ``serve
--trace-out PATH``); nothing runs per token or per chunk.  Memory is
bounded: past ``max_events`` (default 500k ≈ 80k requests) new events
are dropped and counted, so a long-lived daemon cannot grow without
bound and a truncated capture announces itself (``dropped_events`` in
the envelope).  ``save()`` writes the standard ``{"traceEvents":
[...]}`` envelope.

Timestamps ride ``time.perf_counter()`` (the clock every engine stamp
uses) scaled to microseconds; viewers normalise to the earliest event.
"""

from __future__ import annotations

import json
import threading
import time

__all__ = ["Tracer"]


def _us(t: float) -> float:
    return round(t * 1e6, 1)


class Tracer:
    #: event-count cap: a long-lived server must not grow without bound
    #: (each request records ~6 events, so the default holds ~80k
    #: requests — plenty for a capture session, bounded for a daemon).
    #: Past it new events are DROPPED and counted; save() reports the
    #: drop so a truncated capture is never mistaken for a quiet server.
    MAX_EVENTS = 500_000

    def __init__(self, max_events: int = MAX_EVENTS):
        self._lock = threading.Lock()
        self._events: list[dict] = []   # guarded-by: _lock
        self._tids: dict[str, int] = {}  # guarded-by: _lock
        self._serial = 0                # guarded-by: _lock
        self.max_events = int(max_events)
        # guarded-by: _lock (writes) — save()/bundles read the count racily
        self.dropped = 0

    def _append(self, event: dict) -> bool:
        """Append under the cap (caller holds no lock); False = dropped."""
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return False
            self._events.append(event)
            return True

    def _tid(self, label: str) -> int:
        with self._lock:
            tid = self._tids.get(label)
            if tid is not None:
                return tid
            tid = self._tids[label] = len(self._tids) + 1
        self._append({"name": "thread_name", "ph": "M", "pid": 1,
                      "tid": tid, "args": {"name": label}})
        return tid

    def span(self, name: str, t0: float, t1: float, tid: int,
             args: dict | None = None) -> None:
        if t1 < t0:
            t1 = t0
        event = {"name": name, "ph": "X", "pid": 1, "tid": tid,
                 "ts": _us(t0), "dur": _us(t1 - t0)}
        if args:
            event["args"] = args
        self._append(event)

    def record_request(self, request_id: str | None, pos: int, *,
                       t_submit: float, t_admit: float | None,
                       t_first: float | None, t_done: float,
                       n_tokens: int = 0, error: str | None = None) -> None:
        """Emit the span tree for one finished prompt.  Stamps that never
        happened (an error before admission) simply drop their spans —
        the root span always exists, so every request is visible."""
        if request_id is None:
            with self._lock:
                self._serial += 1
                request_id = f"anon-{self._serial}"
        label = (f"request {request_id}" if pos == 0
                 else f"request {request_id}[{pos}]")
        tid = self._tid(label)
        args = {"request_id": request_id, "prompt_index": pos,
                "tokens": n_tokens}
        if error is not None:
            args["error"] = error
        self.span("request", t_submit, t_done, tid, args)
        if t_admit is not None:
            self.span("queue_wait", t_submit, t_admit, tid)
            self.span("generate", t_admit, t_done, tid)
            if t_first is not None and t_first >= t_admit:
                self.span("first_token", t_admit, t_first, tid)
                self.span("decode", t_first, t_done, tid)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def save(self, path: str) -> int:
        """Write the Chrome-trace envelope; returns the event count."""
        events = self.events()
        other = {"producer": "reval_tpu.obs.trace",
                 "saved_at": time.strftime("%Y-%m-%dT%H:%M:%S")}
        if self.dropped:
            other["dropped_events"] = self.dropped
        payload = {"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": other}
        with open(path, "w") as f:
            json.dump(payload, f)
        return len(events)
