"""Determinism observatory: the cross-backend divergence matrix engine.

*The Silent Hyperparameter* (arxiv 2605.19537) measured what serving
folklore suspected: the inference backend is a hyperparameter — switch
the kernel, the scheduler, the parallelism layout, or the weight dtype
and eval scores move, silently.  This repo is exactly that risk surface:
one REval reproduction with direct/paged/dp/pp/sp/quant execution paths
and xla/pallas kernel variants, any of which could perturb the probe
answers the whole reproduction stands on.

This module turns the risk into an *instrument*.  A **cell** is one
point in the backend taxonomy (engine × kernel × parallelism × dtype ×
batch width).  The matrix runs a fixed, seeded probe set through every
loadable cell and captures three observables per cell:

- **greedy tokens** — the RAW generated id stream of each probe's
  greedy generation (temperature 0; ``generate(return_ids=True)``,
  EOS-cut but EOS kept): the bit-identity observable, sensitive to
  every cell axis because it runs through the cell's real engine and
  kernel.  Raw ids, not re-encoded text — EOS and vocab-padding ids
  decode to nothing, so a text round-trip would be blind to argmax
  flips among them.  A diff names the first divergent token.
- **logits fingerprint** — top-k ids + quantized logit values at the
  last prompt position from a full-sequence forward with the cell's
  params.  This is the *weight-dtype axis* magnitude observable (how
  far bf16/int8 move the logits): it is engine/kernel-independent by
  construction (one shared forward per dtype), so same-dtype cells
  always fingerprint identically — kernel/engine divergence is the
  greedy stream's job.
- **answers** — the decoded generation text per probe (what the REval
  scorers would consume; with a real checkpoint these are the scored
  task answers, so an answer digest is the score-relevant observable).

Every cell diffs against a declared **reference cell** (default
``paged-xla-fp32-b2`` — the production engine with the XLA oracle
kernel; override ``REVAL_TPU_DETERMINISM_REF``).  Cells declare an
expectation: ``bit_identical`` cells (kernel variants, paged-vs-static,
dp widths, batch widths) are greedy-parity contracts the tier-1 gate
enforces; ``drift_allowed`` cells (bf16, int8 weights, int8 KV) are
telemetry — their measured drift is the product, not a failure.

Unloadable cells are SKIPPED with a reason (never a crash): the matrix
must render on a CPU dev host, a one-chip v5e, and a dp pod alike, and a
cell silently missing from the report is itself a divergence hazard —
the ``detmatrix`` reval-lint pass pins every taxonomy cell to appear as
run or skipped-with-reason.

``REVAL_TPU_DETERMINISM_PERTURB=<cell>`` injects a logit perturbation
(an lm_head column boost) into that cell when it is built — the chaos
hook the tier-1 gate test uses to prove a perturbed kernel fails loudly
with a named cell and first divergent token.

Entry points: ``tools/determinism_matrix.py`` (CLI, writes
``tpu_watch/determinism-<ts>.json`` + the rendered parity table),
``bench.py`` (the ``determinism`` block: reference-cell fingerprint per
round, so BENCH history detects drift across *commits*), and
``tests/test_determinism.py`` (the tier-1 parity slice).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..env import env_int, env_raw, env_str
from . import metrics as obs_metrics
from .metrics import MetricsRegistry

__all__ = [
    "SCHEMA", "CellSpec", "PROBES", "DEFAULT_MAX_NEW", "DEFAULT_REFERENCE",
    "PARITY_SLICE", "BENCH_SLICE",
    "default_cells", "discover_cells", "run_matrix", "diff_tokens",
    "gate_failures", "render_table", "write_matrix", "record_matrix",
    "reference_fingerprint", "bench_block", "validate_matrix",
    "GOLDEN_SCHEMA", "GOLDEN_FILE", "GOLDEN_SLICE",
    "golden_doc", "validate_golden", "golden_gate",
]

#: matrix artifact schema id — bump on breaking layout changes; the
#: ``detmatrix`` lint pass and ``tools/obs_report.py --determinism``
#: both refuse unknown versions rather than misread them
SCHEMA = "reval-determinism-v1"

#: the fixed probe set: REval-probe-shaped snippets (coverage / state /
#: output / path flavours).  NEVER edit casually — the bench
#: ``determinism`` block fingerprints the reference cell's greedy tokens
#: on these exact strings each round, and an edit here reads as silent
#: drift in BENCH history.
PROBES = (
    "def add(a, b):\n    return a + b\n# [QUESTION] is line 2 executed? ",
    "x = 1\nwhile x < 9:\n    x *= 2\n# [STATE] x = ",
    "y = [k * k for k in range(5)]\nassert y[3] == ",
    "def f(n):\n    if n % 2:\n        return 'odd'\n    return 'even'\n# f(7) -> ",
)

DEFAULT_MAX_NEW = 12
DEFAULT_REFERENCE = "paged-xla-fp32-b2"

#: the tier-1 parity slice: every bit_identical fp32 cell — kernel
#: oracle (xla vs both Pallas formulations), paged vs static, dp2 vs
#: dp1, batch width.  CPU-runnable; a kernel PR that perturbs greedy
#: outputs fails this slice with a named cell + first divergent token.
PARITY_SLICE = ("paged-xla-fp32-b2", "static-fp32-b2",
                "paged-pallas_seq-fp32-b2", "paged-pallas-fp32-b2",
                "paged-ragged-fp32-b2", "paged-ragged-fp32-b4",
                "paged-xla-fp32-dp2-b2", "paged-xla-fp32-b4",
                "spec-paged-xla-fp32-b2", "spec-paged-xla-fp32-b4",
                "spec-paged-ragged-fp32-b2", "kvtier-paged-xla-fp32-b2")

#: the bench garnish slice: cheap cross-backend sanity (reference +
#: static engine + seq kernel + the speculative greedy-accept
#: contract) — the fingerprint is the cross-COMMIT drift detector, so
#: it must stay affordable every round
BENCH_SLICE = ("paged-xla-fp32-b2", "static-fp32-b2",
               "paged-pallas_seq-fp32-b2", "spec-paged-xla-fp32-b2")

_DTYPE_ARG = {"fp32": "float32", "bf16": "bfloat16", "int8": "int8"}

#: the lm_head column boosted by the perturbation hook (byte 'A') and
#: the boost size — large enough that the perturbed cell's greedy
#: argmax flips deterministically, so the gate test is not flaky
_PERTURB_TOKEN = 65
_PERTURB_BOOST = 8.0


@dataclass(frozen=True)
class CellSpec:
    """One point in the backend taxonomy.

    ``expect="bit_identical"`` cells are parity contracts (the tier-1
    gate fails when they diverge from the reference);
    ``expect="drift_allowed"`` cells measure numeric drift that is
    expected to exist (dtype changes move logits by design)."""

    name: str
    engine: str                 # static | paged | dp_paged
    kernel: str = "-"           # xla | pallas | pallas_seq | "-" (static
    #                             full attention has no paged kernel)
    dp: int = 1
    dtype: str = "fp32"         # fp32 | bf16 | int8 (weights)
    kv_dtype: str = ""          # "" | int8 (paged KV pool)
    batch: int = 2              # max_slots / static batch width
    #: speculative decoding forced on (self-drafting + batched verify):
    #: the greedy-accept contract cells — bit-identical to plain decode
    #: by contract, with the measured accept rate recorded as
    #: drift-allowed telemetry on the cell row
    spec: bool = False
    #: KV tiering exercised (inference/tpu/kv_tiers.py): the cell's
    #: measured generation promotes every cached prefix page back out of
    #: the host-DRAM tier (a priming pass spills them first) — the
    #: spilled-and-promoted stream must be bit-identical to resident
    kvtier: bool = False
    expect: str = "bit_identical"

    def axes(self) -> dict:
        return {"engine": self.engine, "kernel": self.kernel,
                "dp": self.dp, "dtype": self.dtype,
                "kv_dtype": self.kv_dtype, "batch": self.batch,
                "spec": self.spec, "kvtier": self.kvtier}


def default_cells() -> list[CellSpec]:
    """The full taxonomy, reference first.  Order is presentation order
    in the rendered table; names are stable identifiers (BENCH history,
    the lint pass, and ``REVAL_TPU_DETERMINISM_REF`` all key on them)."""
    return [
        # the declared reference: production engine, oracle kernel
        CellSpec("paged-xla-fp32-b2", "paged", "xla"),
        # engine axis: rectangular static batches vs continuous batching
        CellSpec("static-fp32-b2", "static"),
        # kernel axis: the two Pallas formulations vs the XLA oracle
        CellSpec("paged-pallas_seq-fp32-b2", "paged", "pallas_seq"),
        CellSpec("paged-pallas-fp32-b2", "paged", "pallas"),
        # ragged axis: the one-dispatch-per-tick continuous-batching
        # engine (ragged paged attention serves prefill, decode, and
        # verify windows in a single wave) must emit exactly the
        # reference stream — the PR-17 kernel's parity contract
        CellSpec("paged-ragged-fp32-b2", "paged", "ragged"),
        CellSpec("paged-ragged-fp32-b4", "paged", "ragged", batch=4),
        # parallelism axis: dp=2 replicas vs dp=1
        CellSpec("paged-xla-fp32-dp2-b2", "dp_paged", "xla", dp=2),
        # batch-width axis: wider slot count must not change greedy
        CellSpec("paged-xla-fp32-b4", "paged", "xla", batch=4),
        # speculative axis: the greedy-accept CONTRACT — self-drafted +
        # batch-verified decode must emit exactly the reference stream
        # (accept rate rides the row as drift-allowed telemetry)
        CellSpec("spec-paged-xla-fp32-b2", "paged", "xla", spec=True),
        CellSpec("spec-paged-xla-fp32-b4", "paged", "xla", batch=4,
                 spec=True),
        # speculative × ragged: draft windows verified INSIDE the ragged
        # wave (no separate verify dispatch) keep the greedy-accept
        # contract
        CellSpec("spec-paged-ragged-fp32-b2", "paged", "ragged",
                 spec=True),
        # KV-tier axis: the spill→promote round trip (host-DRAM tier)
        # must serve byte-for-byte what the resident pages would have
        CellSpec("kvtier-paged-xla-fp32-b2", "paged", "xla", kvtier=True),
        # dtype axis: numeric drift is expected; its SIZE is telemetry
        CellSpec("paged-xla-bf16-b2", "paged", "xla", dtype="bf16",
                 expect="drift_allowed"),
        CellSpec("static-bf16-b2", "static", dtype="bf16",
                 expect="drift_allowed"),
        CellSpec("paged-xla-int8-b2", "paged", "xla", dtype="int8",
                 expect="drift_allowed"),
        CellSpec("paged-xla-fp32-kvint8-b2", "paged", "xla",
                 kv_dtype="int8", expect="drift_allowed"),
    ]


def discover_cells(specs: list[CellSpec] | None = None,
                   ) -> tuple[list[CellSpec], dict[str, str]]:
    """Partition the taxonomy into (loadable-here, {name: skip reason}).

    Static constraints only (device count); a cell that passes discovery
    can still fail to build — ``run_matrix`` degrades that to a skip
    with the error as the reason, because the matrix must never crash on
    a host where one backend is broken: a broken backend is a FINDING."""
    import jax

    specs = list(specs if specs is not None else default_cells())
    have = len(jax.devices())
    avail: list[CellSpec] = []
    skipped: dict[str, str] = {}
    for spec in specs:
        need = spec.dp
        if need > have:
            skipped[spec.name] = (f"needs >= {need} devices, have {have} "
                                  f"(set --xla_force_host_platform_"
                                  f"device_count on CPU)")
            continue
        avail.append(spec)
    return avail, skipped


@contextmanager
def _cell_env(spec: CellSpec):
    """Pin the kernel-dispatch env for one cell's whole lifetime (build
    → trace → generate): the backend choice is read at *trace* time, so
    it must cover the first ``generate`` call, not just construction."""
    name = "REVAL_TPU_PAGED_BACKEND"
    old = env_raw("REVAL_TPU_PAGED_BACKEND")
    if spec.engine in ("paged", "dp_paged"):
        os.environ[name] = spec.kernel
    try:
        yield
    finally:
        if spec.engine in ("paged", "dp_paged"):
            if old is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = old


def _tiny_cfg():
    from ..inference.tpu.tokenizer import ByteTokenizer
    from ..models import ModelConfig

    # head_dim 128 keeps the Pallas kernels lane-aligned in interpret
    # mode (the same geometry the kernel parity tests pin on CPU)
    return ModelConfig(vocab_size=ByteTokenizer.vocab_size + 62,  # 320
                       hidden_size=64, intermediate_size=128,
                       num_layers=2, num_heads=4, num_kv_heads=2,
                       head_dim=128)


def _perturb_params(params: dict, cell: str) -> dict:
    """The injected-divergence hook: boost one lm_head column so the
    cell's greedy argmax flips deterministically.  Quantized lm_head
    (int8 cells) perturbs the scale column instead — same effect."""
    import jax.numpy as jnp

    out = dict(params)
    lm = out.get("lm_head")
    if lm is not None and jnp.issubdtype(lm.dtype, jnp.floating):
        out["lm_head"] = lm.at[:, _PERTURB_TOKEN].add(
            jnp.asarray(_PERTURB_BOOST, lm.dtype))
    elif "lm_head_scale" in out:
        out["lm_head_scale"] = out["lm_head_scale"].at[_PERTURB_TOKEN].mul(4.0)
    else:   # tied embeddings: perturb the shared table's row
        out["embed"] = out["embed"].at[_PERTURB_TOKEN].add(_PERTURB_BOOST)
    return out


class _MatrixRunner:
    """Owns the shared probe model (one seeded draw per weight dtype)
    and builds/runs/closes one engine per cell."""

    def __init__(self, probes, max_new_tokens: int, perturb: str):
        from ..inference.tpu.tokenizer import ByteTokenizer

        self.probes = list(probes)
        self.max_new = max_new_tokens
        self.perturb = perturb
        self.tokenizer = ByteTokenizer()
        self.cfg = _tiny_cfg()
        self._params: dict[str, dict] = {}      # dtype -> tree
        self._logits_rows: dict[tuple, list] = {}   # (dtype, k) -> rows

    def params_for(self, dtype: str) -> dict:
        if dtype not in self._params:
            from ..models import init_random_params

            self._params[dtype] = init_random_params(
                self.cfg, seed=0, dtype=_DTYPE_ARG[dtype])
        return self._params[dtype]

    def _build(self, spec: CellSpec):
        params = self.params_for(spec.dtype)
        if self.perturb and self.perturb == spec.name:
            params = _perturb_params(params, spec.name)
        if spec.engine == "static":
            from ..inference.tpu.engine import TPUEngine

            return TPUEngine(params, self.cfg, self.tokenizer,
                             batch_size=spec.batch, max_seq_len=256)
        if spec.engine == "dp_paged":
            from ..inference.tpu.dp_paged import DataParallelPagedEngine

            return DataParallelPagedEngine(
                params, self.cfg, self.tokenizer, dp_size=spec.dp,
                tp_size=1, max_slots=spec.batch, page_size=128,
                max_seq_len=256, kv_dtype=spec.kv_dtype)
        from ..inference.tpu.paged_engine import PagedTPUEngine

        return PagedTPUEngine(params, self.cfg, self.tokenizer,
                              max_slots=spec.batch,
                              # kvtier cells shrink pages so the ~66-token
                              # probes span FULL cacheable pages (only full
                              # pages spill); page geometry is a memory
                              # layout, not a numeric axis, so the stream
                              # must still match the 128-page reference
                              page_size=32 if spec.kvtier else 128,
                              max_seq_len=256, kv_dtype=spec.kv_dtype,
                              # spec cells FORCE speculation on (n-gram
                              # drafting engages without a grammar);
                              # None keeps the engine's default gating
                              speculative=True if spec.spec else None,
                              kv_tiering=True if spec.kvtier else None)

    def _logits_topk(self, spec: CellSpec, k: int) -> list[dict]:
        """Top-k ids + quantized logit values at the last prompt
        position, one row per probe — the WEIGHT-DTYPE observable.  One
        full-sequence forward per dtype, shared by every cell at that
        dtype (it is engine/kernel-independent by construction, so
        recomputing per cell would only waste compiles); a perturbed
        cell gets its own rows so the injected lm_head boost shows up
        in the fingerprint too."""
        import jax.numpy as jnp
        import numpy as np

        from ..models import logits_for_tokens

        perturbed = bool(self.perturb) and self.perturb == spec.name
        key = (spec.dtype, perturbed, k)
        if key in self._logits_rows:
            return self._logits_rows[key]
        params = self.params_for(spec.dtype)
        if perturbed:
            params = _perturb_params(params, spec.name)
        rows = []
        for probe in self.probes:
            ids = self.tokenizer.encode(probe)
            logits = logits_for_tokens(params, self.cfg,
                                       jnp.asarray([ids], jnp.int32))
            last = np.asarray(logits[0, -1], np.float32)
            top = np.argsort(-last)[:k]
            rows.append({"ids": [int(i) for i in top],
                         "vals": [round(float(last[i]), 5) for i in top]})
        self._logits_rows[key] = rows
        return rows

    def run_cell(self, spec: CellSpec, topk: int) -> dict:
        """One cell end-to-end.  Any failure degrades to a skip row
        carrying the error — a broken backend is a report finding, not
        a crash."""
        try:
            spec_row = tier_row = None
            with _cell_env(spec):
                eng = self._build(spec)
                try:
                    if spec.kvtier:
                        # prime the prefix cache, force-evict it so
                        # every page spills to the host tier, then let
                        # the copier drain: the measured generate below
                        # is served from PROMOTED pages, and must match
                        # the resident streams of the reference cell
                        eng.generate(list(self.probes),
                                     max_new_tokens=self.max_new,
                                     temperature=0.0)
                        eng.prefix_cache.evict_lru(10**6)
                        eng.kv_tiers.drain(5.0)
                    # raw id streams, not re-encoded text: EOS and
                    # vocab-padding ids are invisible in text, and an
                    # argmax flip between two of them is exactly the
                    # silent divergence this instrument exists to catch
                    answers, tokens = eng.generate(
                        list(self.probes), max_new_tokens=self.max_new,
                        temperature=0.0, return_ids=True)
                    if spec.spec:
                        # drift-ALLOWED telemetry riding a bit-identical
                        # contract cell: the accept rate may move round
                        # to round; the token stream may not
                        spec_row = eng.spec_counters()
                    if spec.kvtier:
                        # telemetry proving the tier round trip really
                        # ran (promotions > 0) — drift-allowed counts
                        tier_row = eng.kv_tier_counters()
                finally:
                    if hasattr(eng, "close"):
                        eng.close()
            row = {"axes": spec.axes(), "expect": spec.expect,
                   "status": "run", "answers": answers, "tokens": tokens,
                   "fingerprint": _fingerprint(tokens),
                   "logits_topk": self._logits_topk(spec, topk)}
            if spec_row is not None:
                row["spec_counters"] = spec_row
            if tier_row is not None:
                row["kv_tier_counters"] = tier_row
            return row
        except Exception as e:  # noqa: BLE001 — per-cell isolation is
            # the contract: discovery is static, load failures land here
            return {"axes": spec.axes(), "expect": spec.expect,
                    "status": "skipped",
                    "reason": f"load/run failed: {type(e).__name__}: {e}"}


def _fingerprint(tokens: list[list[int]]) -> str:
    blob = json.dumps(tokens, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def diff_tokens(ref: list[list[int]], got: list[list[int]]
                ) -> dict | None:
    """First divergence between two per-probe token streams: the probe
    and token index of the earliest mismatch (earliest token index wins
    across probes — the DEPTH of the divergence is the signal), with the
    differing ids.  ``None`` when the streams are identical."""
    best: dict | None = None
    for p, (a, b) in enumerate(zip(ref, got)):
        n = max(len(a), len(b))
        for t in range(n):
            ra = a[t] if t < len(a) else None
            rb = b[t] if t < len(b) else None
            if ra != rb:
                if best is None or t < best["token"]:
                    best = {"probe": p, "token": t, "ref": ra, "got": rb}
                break
    if len(ref) != len(got) and best is None:
        best = {"probe": min(len(ref), len(got)), "token": 0,
                "ref": None, "got": None}
    return best


def _topk_drift(ra: dict, rb: dict) -> float:
    """Drift between two top-k fingerprints: the max over (a) per-id
    deltas for ids BOTH rows rank (the same token's logit moved) and
    (b) rank-aligned order-statistic deltas (the k-th largest logit
    moved — catches a new entrant whose counterpart value the other row
    never recorded, e.g. a perturbed column storming the top).  A naive
    positional diff alone would subtract logits of unrelated tokens
    whenever the id lists reorder."""
    drift = 0.0
    for va, vb in zip(ra["vals"], rb["vals"]):      # sorted descending
        drift = max(drift, abs(va - vb))
    av = dict(zip(ra["ids"], ra["vals"]))
    bv = dict(zip(rb["ids"], rb["vals"]))
    for i in set(av) & set(bv):
        drift = max(drift, abs(av[i] - bv[i]))
    return drift


def _diff_cell(ref_row: dict, row: dict) -> dict:
    first = diff_tokens(ref_row["tokens"], row["tokens"])
    drift = 0.0
    ids_equal = True
    for ra, rb in zip(ref_row["logits_topk"], row["logits_topk"]):
        ids_equal = ids_equal and (ra["ids"] == rb["ids"])
        drift = max(drift, _topk_drift(ra, rb))
    return {"tokens_equal": first is None,
            "first_divergence": first,
            "logit_drift": round(drift, 6),
            "topk_ids_equal": ids_equal,
            "answers_equal": ref_row["answers"] == row["answers"]}


def run_matrix(specs: list[CellSpec] | None = None, *,
               probes=None, max_new_tokens: int | None = None,
               reference: str | None = None, select=None,
               registry: MetricsRegistry | None = None) -> dict:
    """Run the divergence matrix and return the artifact dict (see
    :data:`SCHEMA`).  ``registry`` (optional) receives the
    ``reval_determinism_*`` telemetry via :func:`record_matrix`; the
    returned artifact embeds a snapshot either way, so ``/metrics``-less
    consumers (``tools/obs_report.py``) read the same numbers.

    ``select`` (names) narrows which cells EXECUTE without narrowing the
    report: unselected cells are recorded as skipped with a "not
    selected" reason, so a filtered run can never masquerade as a clean
    full audit — the vanished-cell lint rule stays enforceable."""
    import jax

    t0 = time.time()
    probes = list(probes if probes is not None else PROBES)
    max_new = (max_new_tokens if max_new_tokens is not None
               else DEFAULT_MAX_NEW)
    reference = (reference or env_str("REVAL_TPU_DETERMINISM_REF")
                 or DEFAULT_REFERENCE)
    topk = env_int("REVAL_TPU_DETERMINISM_TOPK", 8)
    perturb = env_str("REVAL_TPU_DETERMINISM_PERTURB", "") or ""
    avail, skipped = discover_cells(specs)
    names = {s.name for s in avail} | set(skipped)
    if reference not in names:
        raise ValueError(f"reference cell {reference!r} is not in the "
                         f"taxonomy {sorted(names)}")
    if reference in skipped:
        raise RuntimeError(f"reference cell {reference!r} is not loadable "
                           f"here: {skipped[reference]}")
    if select is not None:
        chosen = set(select) | {reference}
        unknown = chosen - names
        if unknown:
            raise ValueError(f"unknown cell(s) {sorted(unknown)}; "
                             f"taxonomy: {sorted(names)}")
        for spec in list(avail):
            if spec.name not in chosen:
                avail.remove(spec)
                skipped[spec.name] = "not selected for this run (--cells)"

    runner = _MatrixRunner(probes, max_new, perturb)
    cells: dict[str, dict] = {}
    order = sorted(avail, key=lambda s: s.name != reference)  # ref first
    for spec in order:
        cells[spec.name] = runner.run_cell(spec, topk)
    for name, reason in skipped.items():
        spec = next(s for s in (specs or default_cells()) if s.name == name)
        cells[name] = {"axes": spec.axes(), "expect": spec.expect,
                       "status": "skipped", "reason": reason}

    ref_row = cells[reference]
    if ref_row["status"] != "run":
        raise RuntimeError(f"reference cell {reference!r} failed to run: "
                           f"{ref_row.get('reason')}")
    ref_row["status"] = "ref"
    for name, row in cells.items():
        if name == reference or row["status"] != "run":
            continue
        row["diff"] = _diff_cell(ref_row, row)
        agree = row["diff"]["tokens_equal"] and row["diff"]["topk_ids_equal"]
        row["status"] = "agree" if agree else "diverged"

    diverged = [(n, r) for n, r in cells.items() if r["status"] == "diverged"]
    depths = [r["diff"]["first_divergence"]["token"] for _, r in diverged
              if r["diff"]["first_divergence"] is not None]
    matrix = {
        "schema": SCHEMA,
        "created_unix": round(t0, 3),
        "elapsed_s": round(time.time() - t0, 3),
        "host": {"platform": jax.default_backend(),
                 "device": str(jax.devices()[0].device_kind),
                 "devices": len(jax.devices()),
                 "jax": jax.__version__},
        "reference": reference,
        "probes": {"n": len(probes), "max_new_tokens": max_new,
                   "digest": hashlib.sha256(
                       "\x1e".join(probes).encode()).hexdigest()[:16]},
        "perturb": perturb or None,
        "cells": cells,
        "summary": {
            "cells_run": sum(1 for r in cells.values()
                             if r["status"] in ("ref", "agree", "diverged")),
            "cells_agree": sum(1 for r in cells.values()
                               if r["status"] == "agree"),
            "cells_diverged": len(diverged),
            "cells_skipped": sum(1 for r in cells.values()
                                 if r["status"] == "skipped"),
            "divergence_depth": max(depths) if depths else None,
        },
    }
    matrix["summary"]["gate_failures"] = gate_failures(matrix)
    reg = registry if registry is not None else MetricsRegistry()
    record_matrix(matrix, reg)
    matrix["metrics"] = reg.snapshot()
    return matrix


def gate_failures(matrix: dict) -> list[str]:
    """The tier-1 parity verdict: every ``bit_identical`` cell that
    diverged from the reference, with the first divergent token named —
    the loud failure a kernel PR that perturbs greedy outputs must hit."""
    out = []
    ref = matrix["reference"]
    for name, row in sorted(matrix["cells"].items()):
        if row["status"] != "diverged" or row["expect"] != "bit_identical":
            continue
        first = row["diff"]["first_divergence"]
        if first is not None:
            out.append(
                f"cell {name}: greedy tokens diverge from {ref} at "
                f"probe {first['probe']} token {first['token']} "
                f"(ref {first['ref']!r} != got {first['got']!r})")
        else:
            out.append(f"cell {name}: top-{len(row['logits_topk'][0]['ids'])}"
                       f" logit ids diverge from {ref} "
                       f"(greedy tokens still agree)")
    return out


def record_matrix(matrix: dict, registry: MetricsRegistry) -> None:
    """Fold one matrix run into a registry: the ``reval_determinism_*``
    telemetry the README table documents.  Counters accumulate across
    runs (a long-lived registry sums repeated audits); the depth gauge
    keeps the newest run's reading."""
    s = matrix["summary"]
    registry.counter(obs_metrics.DET_CELLS).add(s["cells_run"])
    registry.counter(obs_metrics.DET_AGREE).add(s["cells_agree"])
    registry.counter(obs_metrics.DET_DIVERGED).add(s["cells_diverged"])
    registry.counter(obs_metrics.DET_SKIPPED).add(s["cells_skipped"])
    registry.gauge(obs_metrics.DET_DEPTH).set(
        float(s["divergence_depth"] if s["divergence_depth"] is not None
              else -1.0))
    hist = registry.histogram(obs_metrics.DET_DRIFT)
    for row in matrix["cells"].values():
        if "diff" in row:
            hist.observe(row["diff"]["logit_drift"])


def reference_fingerprint(matrix: dict) -> str:
    return matrix["cells"][matrix["reference"]]["fingerprint"]


def bench_block(select=BENCH_SLICE) -> dict:
    """The ``determinism`` block ``bench.py`` embeds in every round's
    artifact: the reference cell's greedy-token fingerprint (the
    cross-commit silent-drift detector ``tools/obs_report.py
    --determinism`` diffs over BENCH history) plus the slice's
    divergence counts."""
    m = run_matrix(select=list(select))
    block = {"schema": m["schema"],
             "reference": m["reference"],
             "fingerprint": reference_fingerprint(m),
             "probes_digest": m["probes"]["digest"],
             "cells_run": m["summary"]["cells_run"],
             "cells_diverged": m["summary"]["cells_diverged"],
             "gate_failures": m["summary"]["gate_failures"],
             # a leftover REVAL_TPU_DETERMINISM_PERTURB must be traceable
             # in BENCH history, or its fingerprint change reads as a
             # phantom cross-commit numerics drift
             "perturb": m["perturb"]}
    for name, row in m["cells"].items():
        if row.get("spec_counters"):
            # accept-rate telemetry riding the certified contract cell —
            # obs_report --speculative reads it across rounds
            block.setdefault("spec_cells", {})[name] = {
                "accept_rate": row["spec_counters"]["accept_rate"],
                "rounds": row["spec_counters"]["rounds"]}
    return block


def render_table(matrix: dict) -> str:
    """The generated parity table (markdown) — the machine-written
    successor of PARITY.md's hand-maintained backend rows."""
    ref = matrix["reference"]
    host = matrix["host"]
    lines = [
        "# Determinism matrix — generated by tools/determinism_matrix.py",
        "",
        f"Reference cell: `{ref}` · host: {host['platform']} "
        f"({host['device']} ×{host['devices']}, jax {host['jax']}) · "
        f"probes: {matrix['probes']['n']} × "
        f"{matrix['probes']['max_new_tokens']} new tokens · schema "
        f"`{matrix['schema']}`",
        "",
        "| cell | engine | kernel | dp | dtype | kv | batch | spec | "
        "tier | expect | verdict | first divergence | logit drift |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for name, row in sorted(matrix["cells"].items(),
                            key=lambda kv: (kv[1]["status"] != "ref",
                                            kv[0])):
        ax = row["axes"]
        if row["status"] == "skipped":
            verdict, first, drift = "skipped", row["reason"], "—"
        elif row["status"] == "ref":
            verdict, first, drift = "REFERENCE", "—", "—"
        else:
            verdict = ("agree" if row["status"] == "agree"
                       else ("DIVERGED" if row["expect"] == "bit_identical"
                             else "drift"))
            fd = row["diff"]["first_divergence"]
            first = (f"probe {fd['probe']} token {fd['token']}"
                     if fd else "—")
            drift = f"{row['diff']['logit_drift']:g}"
        sc = row.get("spec_counters")
        spec_col = (f"on ({sc['accept_rate']:.0%} acc)" if sc
                    else ("on" if ax.get("spec") else "—"))
        tc = row.get("kv_tier_counters")
        tier_col = (f"on ({tc['promotions']} promo, "
                    f"{tc['promote_hit_rate']:.0%} hit)" if tc
                    else ("on" if ax.get("kvtier") else "—"))
        lines.append(
            f"| `{name}` | {ax['engine']} | {ax['kernel']} | {ax['dp']} "
            f"| {ax['dtype']} | {ax['kv_dtype'] or '—'} | {ax['batch']} "
            f"| {spec_col} | {tier_col} | {row['expect']} | {verdict} "
            f"| {first} | {drift} |")
    s = matrix["summary"]
    lines += ["",
              f"{s['cells_run']} run · {s['cells_agree']} agree · "
              f"{s['cells_diverged']} diverged · {s['cells_skipped']} "
              f"skipped"
              + (f" · max divergence depth {s['divergence_depth']}"
                 if s["divergence_depth"] is not None else "")]
    if s["gate_failures"]:
        lines += ["", "**PARITY GATE FAILURES:**", ""]
        lines += [f"- {msg}" for msg in s["gate_failures"]]
    return "\n".join(lines) + "\n"


def validate_matrix(obj: dict, taxonomy: list[CellSpec] | None = None
                    ) -> list[str]:
    """Schema check shared by the ``detmatrix`` lint pass, the CLI's
    self-check before writing, and the tests.  Returns human-readable
    errors (empty = valid).  The vanished-cell rule: every taxonomy cell
    must appear, as run/agree/diverged/ref or skipped WITH a reason."""
    errors: list[str] = []
    if not isinstance(obj, dict):
        return ["matrix artifact is not a JSON object"]
    if obj.get("schema") != SCHEMA:
        return [f"schema {obj.get('schema')!r} != expected {SCHEMA!r}"]
    cells = obj.get("cells")
    if not isinstance(cells, dict) or not cells:
        return ["no cells in report"]
    ref = obj.get("reference")
    if ref not in cells:
        errors.append(f"reference cell {ref!r} missing from cells")
    elif cells[ref].get("status") != "ref":
        errors.append(f"reference cell {ref!r} has status "
                      f"{cells[ref].get('status')!r}, expected 'ref'")
    for name, row in sorted(cells.items()):
        status = row.get("status")
        if status not in ("ref", "agree", "diverged", "skipped"):
            errors.append(f"cell {name}: unknown status {status!r}")
            continue
        if row.get("expect") not in ("bit_identical", "drift_allowed"):
            errors.append(f"cell {name}: unknown expect "
                          f"{row.get('expect')!r}")
        if not isinstance(row.get("axes"), dict):
            errors.append(f"cell {name}: missing axes")
        if status == "skipped":
            if not row.get("reason"):
                errors.append(f"cell {name}: skipped without a reason")
            continue
        for key in ("tokens", "answers", "fingerprint", "logits_topk"):
            if key not in row:
                errors.append(f"cell {name}: run cell missing {key!r}")
        if status in ("agree", "diverged") and "diff" not in row:
            errors.append(f"cell {name}: compared cell missing diff")
    for key in ("summary", "probes", "host"):
        if not isinstance(obj.get(key), dict):
            errors.append(f"missing {key!r} block")
    expected = {s.name for s in (taxonomy if taxonomy is not None
                                 else default_cells())}
    for name in sorted(expected - set(cells)):
        errors.append(f"cell {name}: in the declared taxonomy but absent "
                      f"from the report (cells must be run or skipped "
                      f"with a reason, never dropped)")
    return errors


def write_matrix(matrix: dict, out_dir: str | None = None) -> str:
    """Atomically write ``determinism-<ts>.json`` into ``out_dir``
    (default ``REVAL_TPU_DETERMINISM_DIR``, else ``tpu_watch/``) and
    return the path."""
    out_dir = (out_dir or env_str("REVAL_TPU_DETERMINISM_DIR")
               or _default_dir())
    os.makedirs(out_dir, exist_ok=True)
    ts = time.strftime("%Y%m%d-%H%M%S", time.gmtime(matrix["created_unix"]))
    path = os.path.join(out_dir, f"determinism-{ts}.json")
    n = 1
    while os.path.exists(path):     # two runs in one second must not
        # clobber an audit record — a vanished report reads as clean
        path = os.path.join(out_dir, f"determinism-{ts}.{n}.json")
        n += 1
    with open(path + ".tmp", "w") as f:
        json.dump(matrix, f, indent=1)
    os.replace(path + ".tmp", path)
    return path


def _default_dir() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "tpu_watch")


# -- golden-stream registry (tools/golden_streams.py) ------------------------
#
# The COMMITTED counterpart of the scratch matrix artifacts above: one
# checked-in file holding the probe set's exact greedy token streams per
# matrix cell, so an upgrade (new jax pin, new kernel revision, new
# scheduler) diffs against the last blessed streams instead of only
# against the same-commit reference cell.  Paired with serving-time
# receipts (obs/receipts.py): each probe stream carries its
# ``token_digest``, the same 16-hex digest receipts certify per prompt.

GOLDEN_SCHEMA = "reval-golden-streams-v1"

#: the committed registry's filename at the repo root
GOLDEN_FILE = "GOLDEN_STREAMS.json"

#: cells recorded by default — the host-runnable BENCH slice, so the
#: gate runs anywhere tier-1 runs
GOLDEN_SLICE = BENCH_SLICE


def golden_doc(matrix: dict) -> dict:
    """Build the registry document from one matrix run: every EXECUTED
    cell's greedy token streams, their per-probe receipt digests, and
    the cell fingerprint.  Skipped cells stay out — the registry records
    what was observed, never a placeholder."""
    from .receipts import token_digest

    cells: dict[str, dict] = {}
    for name, row in sorted(matrix["cells"].items()):
        if row["status"] not in ("ref", "agree", "diverged"):
            continue
        tokens = [[int(t) for t in probe] for probe in row["tokens"]]
        cells[name] = {"fingerprint": row["fingerprint"],
                       "digests": [token_digest(p) for p in tokens],
                       "tokens": tokens}
    return {"schema": GOLDEN_SCHEMA,
            "reference": matrix["reference"],
            "probes_digest": matrix["probes"]["digest"],
            "max_new_tokens": matrix["probes"]["max_new_tokens"],
            # a registry recorded under a perturb drill is poisoned: it
            # would gate every CLEAN run red.  Recorded so the validator
            # can refuse it.
            "perturb": matrix["perturb"],
            "cells": cells}


def validate_golden(obj) -> list[str]:
    """Schema check shared by the ``goldenstreams`` lint pass, the
    tool's pre-write self-check, and the tests.  Returns human-readable
    errors (empty = valid).  Digests are RECOMPUTED from the stored
    streams — a hand-edited or bit-rotted registry cannot pass."""
    from .receipts import token_digest

    if not isinstance(obj, dict):
        return ["golden-stream registry is not a JSON object"]
    if obj.get("schema") != GOLDEN_SCHEMA:
        return [f"schema {obj.get('schema')!r} != expected "
                f"{GOLDEN_SCHEMA!r}"]
    errors: list[str] = []
    if obj.get("perturb"):
        errors.append(
            f"registry was recorded under REVAL_TPU_DETERMINISM_PERTURB="
            f"{obj['perturb']!r} — a perturbed golden gates every clean "
            f"run red; re-record without the drill")
    if not isinstance(obj.get("probes_digest"), str):
        errors.append("missing/mistyped probes_digest")
    cells = obj.get("cells")
    if not isinstance(cells, dict) or not cells:
        return errors + ["no cells in registry"]
    for name, row in sorted(cells.items()):
        if not isinstance(row, dict):
            errors.append(f"cell {name}: not an object")
            continue
        tokens = row.get("tokens")
        if not (isinstance(tokens, list) and tokens
                and all(isinstance(p, list)
                        and all(isinstance(t, int) for t in p)
                        for p in tokens)):
            errors.append(f"cell {name}: tokens is not a non-empty "
                          f"list of int lists")
            continue
        if not isinstance(row.get("fingerprint"), str):
            errors.append(f"cell {name}: missing/mistyped fingerprint")
        if row.get("digests") != [token_digest(p) for p in tokens]:
            errors.append(f"cell {name}: digests do not recompute from "
                          f"the stored token streams (corrupt or "
                          f"hand-edited registry)")
    return errors


def golden_gate(golden: dict, matrix: dict) -> list[str]:
    """Diff one HEAD matrix run against the committed registry.  Every
    failure names the cell and the FIRST divergent (probe, token) —
    :func:`diff_tokens`' earliest-token attribution, the same rule the
    same-commit parity gate uses.  Empty = HEAD matches golden."""
    if golden["probes_digest"] != matrix["probes"]["digest"]:
        return [f"probe set changed (digest {matrix['probes']['digest']} "
                f"!= recorded {golden['probes_digest']}) — the recorded "
                f"streams answer a different question; re-record "
                f"{GOLDEN_FILE}"]
    out: list[str] = []
    for name, want in sorted(golden["cells"].items()):
        row = matrix["cells"].get(name)
        if row is None or row.get("status") == "skipped":
            reason = ((row or {}).get("reason")
                      or "cell absent from the taxonomy")
            out.append(f"cell {name}: recorded in {GOLDEN_FILE} but did "
                       f"not execute at HEAD ({reason})")
            continue
        first = diff_tokens(want["tokens"], row["tokens"])
        if first is not None:
            out.append(
                f"cell {name}: token stream diverges from golden at "
                f"probe {first['probe']} token {first['token']} "
                f"(golden {first['ref']!r} != head {first['got']!r})")
        elif want["fingerprint"] != row["fingerprint"]:
            out.append(f"cell {name}: fingerprint {row['fingerprint']} "
                       f"!= golden {want['fingerprint']} while the "
                       f"streams agree (fingerprint scheme changed — "
                       f"re-record {GOLDEN_FILE})")
    return out
