"""Reproducibility receipts: serving-time provenance on every response.

*The Silent Hyperparameter* (arxiv 2605.19537) measured that the
inference backend is a hyperparameter — switch the kernel, the dtype, or
the scheduler and model outputs move, silently.  PR 8's determinism
observatory catches that drift OFFLINE (a matrix run diffing backend
cells); this module closes the serving-time half: every response carries
a verifiable **receipt** naming exactly which configuration produced it,
so an eval score, a bench round, or a goodput number can be tied to the
config that emitted its tokens after the fact.

A ``reval-receipt-v1`` receipt has three parts:

- **config fingerprint** — the same canonical sha256 the AOT executable
  cache keys warm restarts on (:func:`~reval_tpu.inference.tpu.
  aot_cache.fingerprint` over model config, dtypes, kernel backend +
  trace-time knobs, mesh, page geometry, jax/jaxlib versions), extended
  by each engine's :meth:`receipt_context` with the serving axes the AOT
  key never needed: speculative decoding on/off + K, KV-tier enablement,
  the decode-chunk cadence.  Engine-level and stable per process — two
  replicas with byte-identical configs fingerprint identically, which is
  what makes fingerprint-pinned routing (serving/router.py) possible.
- **token digest** — a rolling sha256 over the RAW emitted token ids
  (the per-request stream, EOS included), folded across the request's
  prompts in order.  The bit-identity observable: two replicas claiming
  the same fingerprint must also produce the same digest for the same
  greedy prompt, and the golden-stream gate (tools/golden_streams.py)
  holds exactly that across commits.
- **provenance** — the engine/replica id that actually served the
  request (router failover makes "which replica answered" a real
  question) plus the per-request serving axes that vary per call and
  therefore stay OUT of the fingerprint: grammar name and sampling
  params.

Wire form: compact JSON in the ``X-Reval-Receipt`` response header, a
``receipt`` field on the JSON body, and a ``reval.receipt`` SSE trailer
event just before ``[DONE]`` on streams.  ``fleet`` journals one per
task; ``tools/loadgen.py`` records the fleet's fingerprint set per run.
"""

from __future__ import annotations

import hashlib
import json

__all__ = [
    "SCHEMA", "config_fingerprint", "token_digest", "fold_digests",
    "build_receipt", "encode_receipt", "parse_receipt", "validate_receipt",
    "digest_matches_ids", "digest_matches_text",
]

#: receipt schema id — bump on breaking layout changes; parsers refuse
#: unknown versions rather than misread them
SCHEMA = "reval-receipt-v1"

#: hex width of token digests (matches the determinism matrix's
#: fingerprint width — both are sha256 prefixes over token streams)
_DIGEST_HEX = 16


def config_fingerprint(context: dict) -> str:
    """The engine-level half of a receipt: the AOT cache's canonical
    sha256 (sorted-key JSON, stringified values, jax/jaxlib versions
    folded in) over an engine's :meth:`receipt_context` dict.  Stable
    per process by construction — trace-time knobs are snapshotted at
    engine build, exactly like the executables they key."""
    from ..inference.tpu.aot_cache import fingerprint, runtime_context

    return fingerprint(runtime_context(**context))


def token_digest(ids) -> str:
    """Rolling sha256 over one raw emitted id stream (4-byte LE words,
    so the digest is a function of the ids alone — not of any text
    re-encoding, which is blind to EOS/padding id flips)."""
    h = hashlib.sha256()
    for t in ids:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return h.hexdigest()[:_DIGEST_HEX]


def fold_digests(digests: list[str]) -> str:
    """One response digest over a request's per-prompt digests (order
    matters: prompt order is part of what the receipt certifies)."""
    h = hashlib.sha256()
    for d in digests:
        h.update(d.encode())
    return h.hexdigest()[:_DIGEST_HEX]


def build_receipt(fingerprint: str, engine_id: str,
                  digests: list[str], n_tokens: int, *,
                  grammar: str | None = None,
                  sampling: dict | None = None) -> dict:
    """Assemble one canonical receipt dict (see module doc for the
    field semantics).  ``digests`` are per-prompt, in prompt order."""
    return {"schema": SCHEMA,
            "fingerprint": fingerprint,
            "engine_id": engine_id,
            "digest": fold_digests(digests),
            "digests": list(digests),
            "n_tokens": int(n_tokens),
            "grammar": grammar,
            "sampling": dict(sampling or {})}


def encode_receipt(receipt: dict) -> str:
    """Compact single-line JSON — the ``X-Reval-Receipt`` header value
    and the SSE trailer payload's ``receipt`` field."""
    return json.dumps(receipt, separators=(",", ":"), sort_keys=True)


def parse_receipt(text: str) -> dict:
    """Parse + validate a wire-form receipt.  Raises ``ValueError`` on
    garbage or an unknown schema — a client must not half-trust a
    receipt it cannot fully read."""
    try:
        obj = json.loads(text)
    except Exception as e:
        raise ValueError(f"unparseable receipt: {e}") from None
    errors = validate_receipt(obj)
    if errors:
        raise ValueError("invalid receipt: " + "; ".join(errors))
    return obj


def validate_receipt(obj) -> list[str]:
    """Structural check shared by :func:`parse_receipt`, the serve
    smoke's self-verification, and the tests.  Returns human-readable
    errors (empty = valid)."""
    if not isinstance(obj, dict):
        return ["receipt is not a JSON object"]
    errors: list[str] = []
    if obj.get("schema") != SCHEMA:
        return [f"schema {obj.get('schema')!r} != expected {SCHEMA!r}"]
    for key, kind in (("fingerprint", str), ("engine_id", str),
                      ("digest", str), ("digests", list),
                      ("n_tokens", int), ("sampling", dict)):
        if not isinstance(obj.get(key), kind):
            errors.append(f"missing/mistyped field {key!r}")
    if not errors:
        if not all(isinstance(d, str) and len(d) == _DIGEST_HEX
                   for d in obj["digests"]):
            errors.append("digests entries are not 16-hex strings")
        elif obj["digest"] != fold_digests(obj["digests"]):
            errors.append("digest does not fold from the per-prompt digests")
    return errors


def digest_matches_ids(receipt: dict, streams: list[list[int]]) -> bool:
    """Does the receipt's digest certify exactly these raw id streams
    (one per prompt, in order)?  The server-side truth check."""
    digests = [token_digest(ids) for ids in streams]
    return (receipt.get("digests") == digests
            and receipt.get("digest") == fold_digests(digests))


def digest_matches_text(receipt: dict, texts: list[str], tokenizer) -> bool:
    """Client-side digest verification for round-trippable tokenizers
    (the serve smoke's self-check): re-encode each returned text and
    accept either the bare stream or stream+EOS — the raw emitted ids
    include the EOS the finalized text cannot carry.  A lossy tokenizer
    makes this check inapplicable (return False), never a crash."""
    try:
        bos = getattr(tokenizer, "bos_id", None)
        eos = getattr(tokenizer, "eos_id", None)
        digests = receipt.get("digests")
        if not isinstance(digests, list) or len(digests) != len(texts):
            return False
        for text, want in zip(texts, digests):
            ids = [t for t in tokenizer.encode(text) if t != bos]
            if token_digest(ids) != want and (
                    eos is None or token_digest(ids + [eos]) != want):
                return False
        return receipt.get("digest") == fold_digests(digests)
    except Exception:
        return False
