"""Metrics registry: counters, gauges, and fixed-bucket histograms.

The observability backbone for the serving stack (ISSUE 4 / PAPERS.md:
serving-systems studies report TTFT/TPOT *distributions*, not averages —
the operative SLOs are percentiles, so the primitive here is a mergeable
fixed-bucket histogram, not a mean).

Design constraints, in order:

- **Hot-path cost.**  Nothing here runs per *token*.  Counters update per
  request or per decode chunk (a few Hz), histograms observe once per
  request or chunk.  Each mutation takes one uncontended lock (~100 ns);
  the bench A/B (``bench.py --no-obs``, PERF.md) pins the total under the
  2% acceptance bar.  ``MetricsRegistry(enabled=False)`` additionally
  swaps histograms for a shared no-op — the knob the A/B flips — while
  counters keep working (engine accounting depends on them).
- **Mergeable.**  dp replicas and :class:`~reval_tpu.serving.session.
  MultiSession` each own a registry; a ``/metrics`` scrape or a fleet
  trailer merges them: counters SUM, histogram buckets ADD (same bounds
  by construction — every histogram takes its buckets from the central
  ``METRICS`` spec), gauges take the LAST merged value that was ever set.
- **One namespace.**  Every metric name is declared ONCE in ``METRICS``
  below; the registry rejects undeclared names, and
  ``tools/check_metrics.py`` lints the spec against the README table and
  against rogue ``reval_*`` literals elsewhere in the tree.  A metric
  cannot be added to the code and silently missed in the docs.

Exposition: :meth:`MetricsRegistry.render_prometheus` emits the
Prometheus text format (version 0.0.4) directly — no ``prometheus_client``
dependency; :meth:`snapshot` is the JSON twin (``/statusz``, fleet
snapshots, ``tools/obs_report.py``).
"""

from __future__ import annotations

import math as _math
import re as _re
import threading

__all__ = [
    "METRICS", "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "parse_prometheus", "percentile_from_buckets", "snapshot_percentile",
    "snapshot_fraction_le", "labeled", "series_base",
    "scrape_delta_histogram",
    "LATENCY_BUCKETS", "STEP_BUCKETS",
    "REQUESTS", "QUEUE_WAIT", "TTFT", "TPOT", "E2E",
    "ENGINE_STEP", "DECODE_CHUNK", "PREFILL_BATCH",
    "QUEUED_TOKENS", "FREE_PAGES", "HTTP_REQUESTS",
    "ROUTER_REQUESTS", "ROUTER_ROUTED", "ROUTER_FAILOVERS",
    "ROUTER_EJECTIONS", "ROUTER_RECOVERIES", "ROUTER_SHEDS",
    "ROUTER_REPLICAS_READY",
    "JIT_COMPILES", "JIT_CACHE_MISSES",
    "SHARD_CHECKS", "SHARD_RESPECS",
    "DET_CELLS", "DET_AGREE", "DET_DIVERGED", "DET_SKIPPED",
    "DET_DEPTH", "DET_DRIFT", "DRIFT_BUCKETS",
    "KVTIER_SPILLS", "KVTIER_SPILL_DROPS", "KVTIER_SPILL_ERRORS",
    "KVTIER_PROMOTIONS", "KVTIER_DISK_PROMOTIONS", "KVTIER_RECOMPUTES",
    "KVTIER_INTEGRITY_FAILURES", "KVTIER_HOST_EVICTIONS",
    "KVTIER_HOST_PAGES", "KVTIER_HOST_BYTES", "KVTIER_DISK_PAGES",
    "KVTIER_QUEUE_DEPTH", "KVTIER_PROMOTE_SECONDS",
    "AOT_HITS", "AOT_MISSES", "AOT_ERRORS", "AOT_UNSUPPORTED",
    "AOT_SAVED_SECONDS", "AOT_ENTRIES", "AOT_BYTES",
    "RESTART_TO_READY", "RESTART_WARM_PREFIXES",
    "RESTART_DEATHS", "RESTART_RESPAWNS",
    "SPEC_ROUNDS", "SPEC_DRAFTED", "SPEC_ACCEPTED", "SPEC_ROLLED_BACK",
    "SPEC_WEDGES", "SPEC_ACCEPTED_PER_ROUND", "SPEC_BUCKETS",
    "GRAMMAR_REQUESTS", "GRAMMAR_FORCED",
    "TENANT_REQUESTS", "TENANT_SHEDS", "TENANT_E2E",
    "ROUTER_GOODPUT", "ROUTER_SLO_MISS", "RECEIPT_SKEW",
    "AUTOSCALE_UP", "AUTOSCALE_DOWN", "AUTOSCALE_BLOCKED",
    "AUTOSCALE_REPLICAS",
]

# Log-spaced seconds buckets spanning sub-ms host paths (mock engine,
# --tiny CPU smoke) through multi-minute cold-compile tails.  Upper
# bounds are INCLUSIVE (Prometheus `le` semantics).
LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0, 300.0)

# Engine-step / chunk timings sit in the 0.1 ms – 10 s band.
STEP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)

# Draft tokens accepted per speculative verify round (0 = every draft
# rejected, the dispatch still yielded its bonus token).  Upper bounds
# inclusive; REVAL_TPU_SPEC_K caps rounds at the high buckets.
SPEC_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)

# Logit-drift magnitudes (obs/determinism.py, the weight-dtype
# observable): same-dtype cells read exactly 0, bf16 weights sit near
# 1e-2, int8 near 0.2, an injected perturbation above 1 — the decades
# between those are what the histogram must resolve.  Fingerprint
# values are quantized at 1e-5, so that is the smallest resolvable
# bucket.
DRIFT_BUCKETS = (1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0)

# -- metric name constants (import these; never inline the literals) -------
REQUESTS = "reval_requests_total"
QUEUE_WAIT = "reval_request_queue_wait_seconds"
TTFT = "reval_request_ttft_seconds"
TPOT = "reval_request_tpot_seconds"
E2E = "reval_request_e2e_seconds"
ENGINE_STEP = "reval_engine_step_seconds"
DECODE_CHUNK = "reval_decode_chunk_seconds"
PREFILL_BATCH = "reval_prefill_batch_seconds"
QUEUED_TOKENS = "reval_session_queued_tokens"
FREE_PAGES = "reval_engine_free_pages"
HTTP_REQUESTS = "reval_http_requests_total"
ROUTER_REQUESTS = "reval_router_requests_total"
ROUTER_ROUTED = "reval_router_routed_total"
ROUTER_FAILOVERS = "reval_router_failovers_total"
ROUTER_EJECTIONS = "reval_router_ejections_total"
ROUTER_RECOVERIES = "reval_router_recoveries_total"
ROUTER_SHEDS = "reval_router_sheds_total"
ROUTER_REPLICAS_READY = "reval_router_replicas_ready"
JIT_COMPILES = "reval_jit_compiles_total"
JIT_CACHE_MISSES = "reval_jit_cache_misses_total"
SHARD_CHECKS = "reval_shard_checks_total"
SHARD_RESPECS = "reval_shard_respec_total"
AOT_HITS = "reval_aot_cache_hits_total"
AOT_MISSES = "reval_aot_cache_misses_total"
AOT_ERRORS = "reval_aot_cache_errors_total"
AOT_UNSUPPORTED = "reval_aot_unsupported_total"
AOT_SAVED_SECONDS = "reval_aot_compile_seconds_saved_total"
AOT_ENTRIES = "reval_aot_cache_entries"
AOT_BYTES = "reval_aot_cache_bytes"
RESTART_TO_READY = "reval_restart_to_ready_seconds"
RESTART_WARM_PREFIXES = "reval_restart_warm_prefixes_total"
RESTART_DEATHS = "reval_restart_deaths_total"
RESTART_RESPAWNS = "reval_restart_respawns_total"
SPEC_ROUNDS = "reval_spec_verify_rounds_total"
SPEC_DRAFTED = "reval_spec_drafted_tokens_total"
SPEC_ACCEPTED = "reval_spec_accepted_tokens_total"
SPEC_ROLLED_BACK = "reval_spec_rolled_back_tokens_total"
SPEC_WEDGES = "reval_spec_wedges_total"
SPEC_ACCEPTED_PER_ROUND = "reval_spec_accepted_per_round"
GRAMMAR_REQUESTS = "reval_grammar_requests_total"
GRAMMAR_FORCED = "reval_grammar_forced_tokens_total"
TENANT_REQUESTS = "reval_tenant_requests_total"
TENANT_SHEDS = "reval_tenant_sheds_total"
TENANT_E2E = "reval_tenant_e2e_seconds"
ROUTER_GOODPUT = "reval_router_goodput_total"
ROUTER_SLO_MISS = "reval_router_slo_miss_total"
RECEIPT_SKEW = "reval_receipt_skew_total"
AUTOSCALE_UP = "reval_autoscale_up_total"
AUTOSCALE_DOWN = "reval_autoscale_down_total"
AUTOSCALE_BLOCKED = "reval_autoscale_blocked_total"
AUTOSCALE_REPLICAS = "reval_autoscale_replicas"
KB_CELLS = "reval_kernelbench_cells_total"
KB_STALE = "reval_kernelbench_cells_stale_total"
KB_SKIPPED = "reval_kernelbench_cells_skipped_total"
KB_RETRIES = "reval_kernelbench_cell_retries_total"
KB_REGRESSIONS = "reval_kernelbench_regressions_total"
KB_BEST_MS = "reval_kernelbench_best_ms"
DET_CELLS = "reval_determinism_cells_total"
DET_AGREE = "reval_determinism_cells_agree_total"
DET_DIVERGED = "reval_determinism_cells_diverged_total"
DET_SKIPPED = "reval_determinism_cells_skipped_total"
DET_DEPTH = "reval_determinism_divergence_depth"
DET_DRIFT = "reval_determinism_logit_drift"
KVTIER_SPILLS = "reval_kvtier_spills_total"
KVTIER_SPILL_DROPS = "reval_kvtier_spill_drops_total"
KVTIER_SPILL_ERRORS = "reval_kvtier_spill_errors_total"
KVTIER_PROMOTIONS = "reval_kvtier_promotions_total"
KVTIER_DISK_PROMOTIONS = "reval_kvtier_disk_promotions_total"
KVTIER_RECOMPUTES = "reval_kvtier_recomputes_total"
KVTIER_INTEGRITY_FAILURES = "reval_kvtier_integrity_failures_total"
KVTIER_HOST_EVICTIONS = "reval_kvtier_host_evictions_total"
KVTIER_HOST_PAGES = "reval_kvtier_host_pages"
KVTIER_HOST_BYTES = "reval_kvtier_host_bytes"
KVTIER_DISK_PAGES = "reval_kvtier_disk_pages"
KVTIER_QUEUE_DEPTH = "reval_kvtier_queue_depth"
KVTIER_PROMOTE_SECONDS = "reval_kvtier_promote_seconds"

#: The canonical metric namespace: name -> (type, help[, buckets]).
#: ``tools/check_metrics.py`` lints this dict against the README table.
METRICS: dict[str, dict] = {
    # per-request latency distributions (EngineStats.observe_request)
    REQUESTS: {"type": "counter",
               "help": "Requests retired by the engine (one per prompt)"},
    QUEUE_WAIT: {"type": "histogram", "buckets": LATENCY_BUCKETS,
                 "help": "Submit-to-admission wait (slot + scheduler queue)"},
    TTFT: {"type": "histogram", "buckets": LATENCY_BUCKETS,
           "help": "Time to first token, from submit"},
    TPOT: {"type": "histogram", "buckets": LATENCY_BUCKETS,
           "help": "Per-token decode latency after the first token"},
    E2E: {"type": "histogram", "buckets": LATENCY_BUCKETS,
          "help": "End-to-end request latency, submit to final token"},
    # engine internals
    ENGINE_STEP: {"type": "histogram", "buckets": STEP_BUCKETS,
                  "help": "One admission+prefill+decode-chunk drive tick"},
    DECODE_CHUNK: {"type": "histogram", "buckets": STEP_BUCKETS,
                   "help": "Decode-chunk dispatch-to-fetch wall interval"},
    PREFILL_BATCH: {"type": "histogram", "buckets": STEP_BUCKETS,
                    "help": "One admission wave's bucketed prefill wall"},
    # EngineStats counters (the pre-obs dataclass fields, same names
    # on the Python side — see engine.EngineStats)
    "reval_engine_prompts_total": {
        "type": "counter", "help": "Prompts completed by generate()/serve"},
    "reval_engine_generated_tokens_total": {
        "type": "counter",
        "help": "Decode tokens delivered to live rows (in-chunk overrun "
                "included; chunks fetched after retirement discarded)"},
    "reval_engine_prefill_tokens_total": {
        "type": "counter", "help": "Prompt tokens prefilled"},
    "reval_engine_decode_seconds_total": {
        "type": "counter", "help": "Wall seconds in decode (union of chunks)"},
    "reval_engine_prefill_seconds_total": {
        "type": "counter", "help": "Wall seconds in prefill"},
    "reval_engine_decode_chunks_total": {
        "type": "counter", "help": "Decode chunks fetched"},
    "reval_engine_decode_steps_total": {
        "type": "counter", "help": "Decode weight passes (batch forward runs)"},
    "reval_engine_pipelined_chunks_total": {
        "type": "counter", "help": "Chunks whose fetch rode behind dispatch"},
    "reval_engine_patched_tables_total": {
        "type": "counter", "help": "In-place device table patches (no flush)"},
    "reval_prefix_hit_tokens_total": {
        "type": "counter", "help": "Prompt tokens served from cached KV"},
    "reval_prefix_lookup_tokens_total": {
        "type": "counter", "help": "Prompt tokens that consulted the cache"},
    "reval_prefix_inserted_pages_total": {
        "type": "counter", "help": "Pages prefilled into the prefix cache"},
    "reval_prefix_evictions_total": {
        "type": "counter", "help": "LRU cache nodes evicted under pressure"},
    "reval_ragged_ticks_total": {
        "type": "counter",
        "help": "Ragged continuous-batching drive ticks (one dispatch each)"},
    "reval_ragged_useful_tokens_total": {
        "type": "counter",
        "help": "Real query+chunk positions the ragged waves asked for"},
    "reval_ragged_padded_tokens_total": {
        "type": "counter",
        "help": "Padded b*w rectangle positions the ragged waves computed"},
    "reval_serving_sheds_total": {
        "type": "counter", "help": "Submissions shed by admission control"},
    "reval_serving_deadline_expired_total": {
        "type": "counter", "help": "Submissions cancelled at their deadline"},
    "reval_serving_watchdog_trips_total": {
        "type": "counter", "help": "No-progress watchdog activations"},
    "reval_serving_drain_seconds_total": {
        "type": "counter", "help": "Wall seconds in graceful drain"},
    # gauges — POINT values: a merged dp/MultiSession scrape keeps the
    # last-merged replica's reading (the spec'd take-last rule), it does
    # NOT sum a fleet-wide total; alert per replica, not on the merge
    QUEUED_TOKENS: {"type": "gauge",
                    "help": "Prompt tokens pending in the session queue "
                            "(last-merged replica)"},
    FREE_PAGES: {"type": "gauge",
                 "help": "Free KV pool pages (last drive tick, "
                         "last-merged replica)"},
    # server-side
    HTTP_REQUESTS: {"type": "counter",
                    "help": "Completion POSTs received by the HTTP server "
                            "(any outcome, incl. shed/drain rejections)"},
    # fleet router (serving/router.py) — the standalone tier's own view;
    # a federated /metrics scrape shows these next to the summed replica
    # counters
    ROUTER_REQUESTS: {"type": "counter",
                      "help": "Completion POSTs received by the fleet "
                              "router (any outcome)"},
    ROUTER_ROUTED: {"type": "counter",
                    "help": "Forwards that landed on the hash-ring "
                            "primary replica (warm prefix cache)"},
    ROUTER_FAILOVERS: {"type": "counter",
                       "help": "Forwards re-routed to a non-primary "
                               "replica (primary unhealthy or forward "
                               "failed)"},
    ROUTER_EJECTIONS: {"type": "counter",
                       "help": "Replica ejections (consecutive "
                               "forward/health failures)"},
    ROUTER_RECOVERIES: {"type": "counter",
                        "help": "Replicas rejoined after a half-open "
                                "probe or clean health poll"},
    ROUTER_SHEDS: {"type": "counter",
                   "help": "Requests the router shed fleet-wide (every "
                           "replica saturated or unavailable)"},
    ROUTER_REPLICAS_READY: {"type": "gauge",
                            "help": "Replicas currently healthy and "
                                    "passing /readyz (router poller "
                                    "view)"},
    ROUTER_GOODPUT: {"type": "counter",
                     "help": "Forwards that completed within their "
                             "declared deadline_s (requests without a "
                             "deadline count on any 2xx) — the goodput "
                             "numerator the loadgen/SLO reports read"},
    ROUTER_SLO_MISS: {"type": "counter",
                      "help": "Forwards that completed but blew their "
                              "declared deadline_s, plus 504 "
                              "deadline_exceeded pass-throughs"},
    RECEIPT_SKEW: {"type": "counter",
                   "help": "Fingerprint-skew episodes: ready replicas "
                           "disagreed on their receipt config "
                           "fingerprint (edge-triggered per "
                           "converged-to-skewed transition)"},
    # per-tenant QoS (serving/router.py) — the ONLY labeled series in
    # the registry (label: tenant=, sanitized wire value); weighted
    # admission sheds a noisy tenant before it starves the others
    TENANT_REQUESTS: {"type": "counter",
                      "help": "Completion POSTs received per tenant "
                              "(label tenant=; any outcome)"},
    TENANT_SHEDS: {"type": "counter",
                   "help": "Requests shed per tenant (label tenant=): "
                           "weighted admission over-share sheds plus "
                           "fleet-wide sheds attributed to the tenant"},
    TENANT_E2E: {"type": "histogram", "buckets": LATENCY_BUCKETS,
                 "help": "Router-side end-to-end forward latency per "
                         "tenant (label tenant=), completed forwards "
                         "only"},
    # SLO-driven autoscaler (serving/autoscaler.py) — the control
    # loop's own registry (not federated; the drill and `reval_tpu
    # watch` read its actions from the router admin log)
    AUTOSCALE_UP: {"type": "counter",
                   "help": "Scale-up actions taken (replica spawned "
                           "and added to the router ring)"},
    AUTOSCALE_DOWN: {"type": "counter",
                     "help": "Scale-down actions taken (replica "
                             "drained, removed from the ring, and "
                             "stopped)"},
    AUTOSCALE_BLOCKED: {"type": "counter",
                        "help": "Indicated scaling actions suppressed "
                                "by cooldown or the min/max replica "
                                "bounds (each also logs "
                                "autoscale.blocked)"},
    AUTOSCALE_REPLICAS: {"type": "gauge",
                         "help": "Replicas the autoscaler currently "
                                 "targets (its own view; the router "
                                 "gauge counts ready ones)"},
    # jit-discipline (analysis/jitcheck.py) — compile-variant tracking
    # over the engines' declared jit entry points
    JIT_COMPILES: {"type": "counter",
                   "help": "Distinct compile variants observed across "
                           "tracked jit entry points (one per new "
                           "shape-key signature)"},
    JIT_CACHE_MISSES: {"type": "counter",
                       "help": "Compile variants observed PAST an "
                               "entry's declared warmup budget "
                               "(post-warmup recompiles; each also "
                               "logs jit.recompile)"},
    # mesh-discipline (analysis/shardcheck.py) — declared-vs-actual
    # sharding comparisons over the engines' guarded jit entries
    SHARD_CHECKS: {"type": "counter",
                   "help": "Declared-vs-actual sharding comparisons "
                           "over guarded jit entries (ShardGuard; "
                           "attribute reads only, never a sync)"},
    SHARD_RESPECS: {"type": "counter",
                    "help": "Arrays whose actual sharding diverged "
                            "from the declared spec (each is an "
                            "unintended cross-device reshard; also "
                            "logs shard.respec once per signature)"},
    # persistent AOT executable cache (inference/tpu/aot_cache.py) —
    # warm restarts skip XLA compilation when a fingerprint-keyed
    # serialized executable already exists on disk
    AOT_HITS: {"type": "counter",
               "help": "Tracked jit variants loaded from the persistent "
                       "AOT executable cache (no XLA compile paid)"},
    AOT_MISSES: {"type": "counter",
                 "help": "Tracked jit variants compiled fresh and "
                         "serialized into the AOT cache (cold entry, "
                         "corrupt/stale payload, or fingerprint miss)"},
    AOT_ERRORS: {"type": "counter",
                 "help": "AOT cache entries that failed to load or "
                         "store (corrupt payload, checksum/fingerprint "
                         "mismatch, unwritable dir) — each degrades to "
                         "a fresh compile, never a crash"},
    AOT_UNSUPPORTED: {"type": "counter",
                      "help": "AOT serialize/export requests declined "
                              "because this host's jax build cannot "
                              "export the program (Mosaic kernel canary "
                              "failed or jax.export absent)"},
    AOT_SAVED_SECONDS: {"type": "counter",
                        "help": "Compile wall seconds skipped by AOT "
                                "cache hits (the stored entry's "
                                "measured compile cost)"},
    AOT_ENTRIES: {"type": "gauge",
                  "help": "Entries currently in the AOT cache directory "
                          "(last touch, this process's view)"},
    AOT_BYTES: {"type": "gauge",
                "help": "Total payload bytes in the AOT cache directory "
                        "(last touch, this process's view)"},
    # warm restarts (serving/session.py + serving/supervisor.py)
    RESTART_TO_READY: {"type": "histogram", "buckets": LATENCY_BUCKETS,
                       "help": "Session boot to /readyz-ready wall "
                               "seconds, observed when a warm restore "
                               "finishes (the restart SLO)"},
    RESTART_WARM_PREFIXES: {"type": "counter",
                            "help": "Prefix chains replayed through "
                                    "prefill from a warm-state snapshot "
                                    "at boot"},
    RESTART_DEATHS: {"type": "counter",
                     "help": "Child server deaths observed by the "
                             "crash-loop supervisor (supervisor-process "
                             "registry: rides its postmortem bundles "
                             "and logs, not the child's /metrics)"},
    RESTART_RESPAWNS: {"type": "counter",
                       "help": "Child servers (re)spawned by the "
                               "crash-loop supervisor (supervisor-"
                               "process registry: rides its postmortem "
                               "bundles and logs, not the child's "
                               "/metrics)"},
    # speculative + constrained decoding (reval_tpu/decoding/ + the
    # paged engine's batched verify path)
    SPEC_ROUNDS: {"type": "counter",
                  "help": "Batched speculative verify dispatches (one "
                          "forward scoring a whole draft window)"},
    SPEC_DRAFTED: {"type": "counter",
                   "help": "Draft tokens proposed to verify windows "
                           "(grammar-forced + n-gram prompt lookup)"},
    SPEC_ACCEPTED: {"type": "counter",
                    "help": "Draft tokens accepted by the verify step "
                            "(equal to its masked greedy argmax; bonus "
                            "tokens excluded)"},
    SPEC_ROLLED_BACK: {"type": "counter",
                       "help": "Rejected draft tokens rolled back "
                               "(their reserved KV pages returned via "
                               "the runtime rollback)"},
    SPEC_WEDGES: {"type": "counter",
                  "help": "Requests whose drafter faulted and degraded "
                          "to plain decode for the rest of the request "
                          "(each also logs spec.wedge)"},
    SPEC_ACCEPTED_PER_ROUND: {"type": "histogram", "buckets": SPEC_BUCKETS,
                              "help": "Draft tokens accepted per verify "
                                      "round (the accept-rate "
                                      "distribution)"},
    GRAMMAR_REQUESTS: {"type": "counter",
                       "help": "Requests submitted with a grammar= "
                               "constraint (token-level logit masking "
                               "active)"},
    GRAMMAR_FORCED: {"type": "counter",
                     "help": "Draft tokens proposed by grammar forcing "
                             "(single-legal states, or the canonical "
                             "token along a state's deterministic "
                             "character chain)"},
    # kernel CI harness (reval_tpu/kernelbench.py) — one leaderboard
    # round increments the counters once per cell; the registry
    # snapshot rides the kernelbench-<ts>.json artifact, so instrument
    # health (stale cells, retries, regressions) reads like any other
    # subsystem in obs_report
    KB_CELLS: {"type": "counter",
               "help": "Kernel-CI cells measured fresh (a supervised "
                       "subprocess completed and returned a positive "
                       "ms/step)"},
    KB_STALE: {"type": "counter",
               "help": "Kernel-CI cells degraded to stale-marked "
                       "entries (every attempt failed; last-known value "
                       "+ commit carried, never a blind 0.0)"},
    KB_SKIPPED: {"type": "counter",
                 "help": "Kernel-CI cells skipped with a reason "
                         "(unselected, or failed with no last-known "
                         "value to carry)"},
    KB_RETRIES: {"type": "counter",
                 "help": "Kernel-CI cell attempts retried under backoff "
                         "after a transient failure (wedge kill, "
                         "timeout, device loss)"},
    KB_REGRESSIONS: {"type": "counter",
                     "help": "Kernel-CI rounds whose regression gate "
                             "fired: HEAD slower than the incumbent "
                             "winner cell beyond the noise band (each "
                             "also logs kernelbench.regression and "
                             "exits 1)"},
    KB_BEST_MS: {"type": "gauge",
                 "help": "Winning cell's measured ms/step, newest "
                         "kernel-CI round (this process's view)"},
    # determinism observatory (obs/determinism.py) — one matrix run
    # increments the counters once per cell; the snapshot rides the
    # determinism-<ts>.json artifact and merges into any registry
    DET_CELLS: {"type": "counter",
                "help": "Divergence-matrix cells executed (ref + "
                        "compared; skipped cells excluded)"},
    DET_AGREE: {"type": "counter",
                "help": "Cells bit-identical with the reference cell "
                        "(greedy tokens and top-k logit ids)"},
    DET_DIVERGED: {"type": "counter",
                   "help": "Cells that diverged from the reference cell "
                           "(incl. expected drift_allowed divergence)"},
    DET_SKIPPED: {"type": "counter",
                  "help": "Taxonomy cells not loadable on this host "
                          "(each carries a reason in the matrix JSON)"},
    DET_DEPTH: {"type": "gauge",
                "help": "Deepest first-divergent greedy-token index "
                        "across diverged cells, newest matrix run "
                        "(-1 = no divergence observed)"},
    DET_DRIFT: {"type": "histogram", "buckets": DRIFT_BUCKETS,
                "help": "Max abs top-k logit delta vs the reference "
                        "cell (weight-dtype observable; shared-id + "
                        "rank-aligned), one observation per compared "
                        "cell"},
    # hierarchical KV tiering (inference/tpu/kv_tiers.py) — HBM →
    # host-DRAM → disk page store behind the radix prefix cache; every
    # degrade-ladder rung is a counter, promotion correctness is the
    # bit-identity contract
    KVTIER_SPILLS: {"type": "counter",
                    "help": "Evicted prefix-cache pages copied down to "
                            "the host-DRAM tier (copier thread; sha256 "
                            "stamped at spill)"},
    KVTIER_SPILL_DROPS: {"type": "counter",
                         "help": "Spills dropped at the bounded handoff "
                                 "queue (backpressure: the drive tick "
                                 "never waits on the host path)"},
    KVTIER_SPILL_ERRORS: {"type": "counter",
                          "help": "Spill copies that faulted on the "
                                  "copier thread (warmth lost, never "
                                  "correctness; each also logs "
                                  "kvtier.spill_error)"},
    KVTIER_PROMOTIONS: {"type": "counter",
                        "help": "Pages promoted back into the HBM pool "
                                "from a colder tier (sha256 verified; "
                                "byte-identical to the resident page)"},
    KVTIER_DISK_PROMOTIONS: {"type": "counter",
                             "help": "Promotions whose payload came off "
                                     "the disk tier (snapshot sidecar) "
                                     "rather than host DRAM"},
    KVTIER_RECOMPUTES: {"type": "counter",
                        "help": "Degrade-ladder fallbacks: pages "
                                "recomputed from their token chain via "
                                "prefill after a tier fault (each also "
                                "logs kvtier.degrade with the rung)"},
    KVTIER_INTEGRITY_FAILURES: {"type": "counter",
                                "help": "Promotions rejected on sha256 "
                                        "mismatch (bit rot, torn write, "
                                        "or injected corruption) — the "
                                        "never-wrong-KV gate"},
    KVTIER_HOST_EVICTIONS: {"type": "counter",
                            "help": "Host-tier payloads LRU-dropped "
                                    "past REVAL_TPU_KVTIER_HOST_MB "
                                    "(disk-backed entries demote to "
                                    "path-only instead)"},
    KVTIER_HOST_PAGES: {"type": "gauge",
                        "help": "Pages resident in the host-DRAM tier "
                                "(copier's view, last touch)"},
    KVTIER_HOST_BYTES: {"type": "gauge",
                        "help": "Payload bytes resident in the "
                                "host-DRAM tier (last touch)"},
    KVTIER_DISK_PAGES: {"type": "gauge",
                        "help": "Disk-tier entries attached from a "
                                "snapshot sidecar and not yet promoted "
                                "or dropped (last touch)"},
    KVTIER_QUEUE_DEPTH: {"type": "gauge",
                         "help": "Spill handoff queue depth (bounded by "
                                 "REVAL_TPU_KVTIER_QUEUE; last touch)"},
    KVTIER_PROMOTE_SECONDS: {"type": "histogram", "buckets": STEP_BUCKETS,
                             "help": "One page promotion: tier fetch + "
                                     "verify + jitted scatter into the "
                                     "pool"},
}


# -- labeled series ----------------------------------------------------------

_LABEL_KEY_RE = _re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_LABEL_VALUE_RE = _re.compile(r"^[A-Za-z0-9._:\- ]*$")


def labeled(name: str, **labels) -> str:
    """The exposition series name for ``name`` with ``labels`` attached:
    ``reval_tenant_requests_total{tenant="alpha"}``.  Labels are sorted
    (one dict, one series) and validated — the registry is the LAST stop
    before the wire, so a label value that could smuggle a quote or
    newline into the exposition is rejected here, not escaped into
    ambiguity.  Callers sanitize wire-derived values first (the router's
    tenant parser does)."""
    if not labels:
        return name
    parts = []
    for key in sorted(labels):
        value = str(labels[key])
        if not _LABEL_KEY_RE.match(key):
            raise ValueError(f"bad label key {key!r}")
        if not _LABEL_VALUE_RE.match(value):
            raise ValueError(f"bad label value {value!r} for {key!r}")
        parts.append(f'{key}="{value}"')
    return f"{name}{{{','.join(parts)}}}"


def series_base(series: str) -> str:
    """The declaring metric name of a (possibly labeled) series."""
    return series.split("{", 1)[0]


def _series_labels(series: str) -> str:
    """The label body (without braces) of a series; '' when unlabeled."""
    if "{" in series:
        return series.split("{", 1)[1].rstrip("}")
    return ""


class Counter:
    """Monotonic-by-convention accumulator.  ``add`` may carry floats
    (seconds counters) and ``set`` exists for the EngineStats property
    setters (test fixtures assign counters; prefix-cache rollbacks
    subtract a mistakenly credited hit) — exposition still types it
    ``counter``."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        # guarded-by: _lock (writes) — the ``value`` read is deliberately
        # lock-free: a float load is atomic under the GIL, and a scrape
        # racing an ``add`` may see either side of it
        self._value = 0.0
        self._lock = threading.Lock()

    def add(self, v: float = 1.0) -> None:
        with self._lock:
            self._value += v

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value.  ``updated`` distinguishes "never set" from
    "set to 0", so a merge can take the LAST set value instead of
    clobbering a live reading with a default zero."""

    __slots__ = ("name", "_value", "updated", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0       # guarded-by: _lock (writes)
        self.updated = False    # guarded-by: _lock (writes)
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            self.updated = True

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram with INCLUSIVE upper bounds (Prometheus
    ``le``) plus an implicit ``+Inf`` overflow bucket.  Stores per-bucket
    (non-cumulative) counts; exposition cumulates at render time.
    """

    __slots__ = ("name", "buckets", "counts", "sum", "count", "_lock")

    def __init__(self, name: str, buckets: tuple[float, ...]):
        assert buckets == tuple(sorted(buckets)), "bucket bounds must ascend"
        self.name = name
        self.buckets = tuple(float(b) for b in buckets)
        # guarded-by: _lock — reads go through _read() so a merge/render
        # racing an observe never sees a count that disagrees with its
        # buckets (counts is mutated in place, sum/count alongside)
        self.counts = [0] * (len(self.buckets) + 1)   # +1: the +Inf bucket
        self.sum = 0.0          # guarded-by: _lock
        self.count = 0          # guarded-by: _lock
        self._lock = threading.Lock()

    def _bucket_index(self, v: float) -> int:
        import bisect

        # first bound >= v (le is inclusive: v exactly on a bound lands
        # IN that bucket, tests/test_obs.py pins the boundary)
        return bisect.bisect_left(self.buckets, v)

    def observe(self, v: float) -> None:
        v = float(v)
        i = self._bucket_index(v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def _read(self) -> tuple[list[int], float, int]:
        """Consistent (counts, sum, count) snapshot under the lock —
        merges and renders racing a live ``observe`` must never see a
        count that disagrees with its buckets."""
        with self._lock:
            return list(self.counts), self.sum, self.count

    def merge(self, other: "Histogram") -> None:
        if other.buckets != self.buckets:
            raise ValueError(f"histogram {self.name}: bucket bounds differ")
        # read the source under ITS lock first (never hold both at once —
        # a pair of cross-merges must not deadlock), then fold in
        counts, o_sum, o_count = other._read()
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += c
            self.sum += o_sum
            self.count += o_count

    def percentile(self, q: float) -> float:
        """``histogram_quantile``-style estimate (see
        :func:`percentile_from_buckets`)."""
        counts, _, count = self._read()
        return percentile_from_buckets(self.buckets, counts, count, q)


def percentile_from_buckets(bounds: tuple[float, ...], counts,
                            count: int, q: float) -> float:
    """``histogram_quantile``-style estimate over raw bucket data: walk
    the per-bucket (non-cumulative) counts — ``counts`` may carry the
    +Inf bucket as its last element or omit it — to the target rank and
    interpolate linearly inside the landing bucket.  The +Inf bucket
    reports the highest finite bound (a floor, like Prometheus).  THE
    one estimator: ``Histogram.percentile`` and ``tools/obs_report.py``
    (snapshot diffs) both call it, so their numbers cannot diverge."""
    if count <= 0:
        return 0.0
    rank = q * count
    cum = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        if cum + c >= rank:
            if i >= len(bounds):                    # +Inf bucket
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            return lo + (bounds[i] - lo) * max(0.0, rank - cum) / c
        cum += c
    return bounds[-1]


_SCRAPE_LE_RE = _re.compile(r'le="([^"]+)"')


def _scrape_buckets(samples: dict, name: str) -> dict[float, float]:
    """``{upper_bound: cumulative_count}`` for one histogram's bucket
    samples in a :func:`parse_prometheus` result (labels beyond ``le``
    are summed — callers want the fleet distribution, not per-label)."""
    out: dict[float, float] = {}
    prefix = f"{name}_bucket{{"
    for series, value in samples.items():
        if not series.startswith(prefix):
            continue
        m = _SCRAPE_LE_RE.search(series)
        if m is None:
            continue
        bound = _math.inf if m.group(1) == "+Inf" else float(m.group(1))
        out[bound] = out.get(bound, 0.0) + value
    return out


def scrape_delta_histogram(samples: dict, prev: dict | None,
                           name: str) -> dict | None:
    """The snapshot-encoded histogram of ``name``'s observations BETWEEN
    two parsed expositions (cumulative bucket counts subtract) — THE one
    cumulative→delta assembly: the autoscaler's interval percentiles and
    loadgen's attainment both build on it, so their delta math cannot
    diverge.  None when the scrape carries no such histogram; with
    ``prev`` None the deltas are the lifetime totals."""
    cur = _scrape_buckets(samples, name)
    if not cur:
        return None
    old = _scrape_buckets(prev or {}, name)
    bounds = sorted(b for b in cur if b != _math.inf)
    rows: list[list[float]] = []
    last = 0.0
    for b in bounds:
        cum = cur.get(b, 0.0) - old.get(b, 0.0)
        rows.append([b, max(0.0, cum - last)])
        last = cum
    total = cur.get(_math.inf, 0.0) - old.get(_math.inf, 0.0)
    return {"buckets": rows, "inf": max(0.0, total - last), "sum": 0.0,
            "count": total}


def snapshot_fraction_le(hist: dict, threshold: float) -> float:
    """Fraction of a snapshot histogram's observations at or below
    ``threshold`` — the SLO-attainment estimator (linear interpolation
    inside the landing bucket, the same model the percentile estimator
    uses, so attainment and percentiles cannot disagree).  Shared by
    ``tools/loadgen.py``, ``tools/obs_report.py --slo``, and the
    ``reval_tpu watch`` fleet-load view.  1.0 on an empty histogram
    (no observations = nothing violated)."""
    count = hist.get("count", 0)
    if count <= 0:
        return 1.0
    below = 0.0
    lo = 0.0
    for bound, c in hist["buckets"]:
        if threshold >= bound:
            below += c
        elif threshold > lo and c:
            below += c * (threshold - lo) / (bound - lo)
            break
        else:
            break
        lo = bound
    # the +Inf bucket never counts below a finite threshold
    return min(1.0, below / count)


def snapshot_percentile(hist: dict, q: float) -> float:
    """:func:`percentile_from_buckets` applied to the SNAPSHOT encoding
    (``{"buckets": [[bound, count], ...], "inf": n, "count": n}`` — what
    :meth:`MetricsRegistry.snapshot`, ``/statusz``, and
    ``fleet_metrics.json`` carry).  One estimator, two encodings:
    ``tools/obs_report.py`` and the ``reval_tpu watch`` console both call
    this, so no rendered percentile can disagree with a live scrape."""
    bounds = tuple(b for b, _ in hist["buckets"])
    counts = [c for _, c in hist["buckets"]] + [hist.get("inf", 0)]
    return percentile_from_buckets(bounds, counts, hist["count"], q)


class _NullHistogram:
    """Shared no-op stand-in when observation is disabled (``--no-obs``
    A/B): observe costs one attribute lookup + a pass."""

    __slots__ = ("name",)
    buckets: tuple[float, ...] = ()
    counts: list[int] = []
    sum = 0.0
    count = 0

    def __init__(self, name: str):
        self.name = name

    def observe(self, v: float) -> None:
        pass

    def merge(self, other) -> None:
        pass

    def percentile(self, q: float) -> float:
        return 0.0


class MetricsRegistry:
    """Get-or-create store of named metrics, thread-safe for concurrent
    registration and mutation.  Names must be declared in :data:`METRICS`
    unless ``strict=False`` (ad-hoc experiments); requesting an existing
    name as a different type raises — that is a namespace collision, not
    a cache miss."""

    def __init__(self, enabled: bool = True, strict: bool = True):
        self.enabled = enabled
        self.strict = strict
        self._metrics: dict[str, object] = {}   # guarded-by: _lock
        self._lock = threading.Lock()

    # -- registration ------------------------------------------------------
    def _get(self, name: str, cls, factory):
        # a labeled series (see :func:`labeled`) is declared by its base
        # name; the full series string is the storage/exposition key
        spec = METRICS.get(series_base(name))
        if spec is None and self.strict:
            raise KeyError(
                f"metric {name!r} is not declared in obs.metrics.METRICS — "
                f"declare it there (and in the README table) first")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name!r} already registered as "
                                 f"{type(m).__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter, lambda: Counter(name))

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge, lambda: Gauge(name))

    def histogram(self, name: str,
                  buckets: tuple[float, ...] | None = None) -> Histogram:
        spec = METRICS.get(series_base(name)) or {}
        bounds = tuple(buckets if buckets is not None
                       else spec.get("buckets", LATENCY_BUCKETS))
        if not self.enabled:
            return self._get(name, _NullHistogram,
                             lambda: _NullHistogram(name))
        return self._get(name, Histogram, lambda: Histogram(name, bounds))

    # -- aggregation -------------------------------------------------------
    def merge(self, other: "MetricsRegistry") -> None:
        """Fold ``other`` into this registry: counters sum, histogram
        buckets add, gauges take the last merged SET value."""
        with other._lock:
            items = list(other._metrics.items())
        for name, m in items:
            if isinstance(m, Counter):
                self.counter(name).add(m.value)
            elif isinstance(m, Gauge):
                if m.updated:
                    self.gauge(name).set(m.value)
            elif isinstance(m, Histogram):
                self.histogram(name, m.buckets).merge(m)
            # _NullHistogram: nothing to carry

    @staticmethod
    def merged(registries) -> "MetricsRegistry":
        out = MetricsRegistry()
        for reg in registries:
            out.merge(reg)
        return out

    # -- export ------------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able view: ``/statusz``, fleet snapshots, obs_report."""
        counters: dict[str, float] = {}
        gauges: dict[str, float] = {}
        histograms: dict[str, dict] = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in sorted(items):
            if isinstance(m, Counter):
                v = m.value
                counters[name] = int(v) if float(v).is_integer() else v
            elif isinstance(m, Gauge):
                gauges[name] = m.value
            elif isinstance(m, Histogram):
                counts, h_sum, h_count = m._read()
                histograms[name] = {
                    "buckets": [[b, c] for b, c in zip(m.buckets, counts)],
                    "inf": counts[-1], "sum": h_sum, "count": h_count}
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 (no client library).
        Labeled series render under their base metric's single
        HELP/TYPE header (sorting keeps one base's label variants
        adjacent — ``{`` collates after every name character)."""
        lines: list[str] = []
        emitted: set[str] = set()
        with self._lock:
            items = sorted(self._metrics.items())
        for name, m in items:
            base = series_base(name)
            labels = _series_labels(name)
            spec = METRICS.get(base, {})
            help_text = spec.get("help", "")
            if isinstance(m, Counter):
                if base not in emitted:
                    emitted.add(base)
                    lines.append(f"# HELP {base} {help_text}")
                    lines.append(f"# TYPE {base} counter")
                lines.append(f"{name} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                if base not in emitted:
                    emitted.add(base)
                    lines.append(f"# HELP {base} {help_text}")
                    lines.append(f"# TYPE {base} gauge")
                lines.append(f"{name} {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                if base not in emitted:
                    emitted.add(base)
                    lines.append(f"# HELP {base} {help_text}")
                    lines.append(f"# TYPE {base} histogram")
                pre = f"{labels}," if labels else ""
                suffix = f"{{{labels}}}" if labels else ""
                counts, h_sum, h_count = m._read()
                cum = 0
                for bound, c in zip(m.buckets, counts):
                    cum += c
                    lines.append(
                        f'{base}_bucket{{{pre}le="{_fmt(bound)}"}} {cum}')
                cum += counts[-1]
                lines.append(f'{base}_bucket{{{pre}le="+Inf"}} {cum}')
                lines.append(f"{base}_sum{suffix} {_fmt(h_sum)}")
                lines.append(f"{base}_count{suffix} {h_count}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers bare, floats via repr."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


_SAMPLE_RE = _re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^{}]*\})?'
    r'\s+(?P<value>[^\s]+)$')
_META_RE = _re.compile(
    r'^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*$')


def parse_prometheus(text: str) -> dict[str, float]:
    """Minimal exposition-format (0.0.4) checker + reader: returns
    ``{series (incl. label string): value}`` and raises ``ValueError`` on
    any line that fits neither the sample nor the comment grammar — the
    ``serve --smoke`` self-test and tests/test_obs.py both gate on it."""
    samples: dict[str, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if not _META_RE.match(line):
                raise ValueError(f"line {lineno}: bad comment {line!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: bad sample {line!r}")
        try:
            value = float(m.group("value"))
        except ValueError:
            raise ValueError(f"line {lineno}: bad value in {line!r}") from None
        samples[m.group("name") + (m.group("labels") or "")] = value
    return samples
