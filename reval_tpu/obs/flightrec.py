"""Flight recorder: a bounded ring of per-step engine state + postmortems.

PR 3's watchdog tells you *that* the engine wedged and PR 4's metrics
tell you the aggregate shape of the run — but when the trip (or a chaos
fault, preemption storm, or drain hang) actually fires, the state of the
last N ticks is gone.  Production serving postmortems (FlashInfer-Bench;
the vLLM/TGI comparison — PAPERS.md) need a continuous, cheap recording
of per-step engine state, captured *before* anyone knew it would matter.

:class:`FlightRecorder` is that recording: an always-on ring buffer
(default :data:`CAPACITY` = 4096 records) the engine's drive tick feeds
once per step.  One record is ONE tuple assignment into a preallocated
list — no locks, no allocation beyond the tuple, no formatting — i.e.
O(100ns)-class per tick (measured ~0.6 µs with its input reads, PERF.md)
against a tick wall of ≥1 ms host-only and ~100 ms on the tunneled chip.
``REVAL_TPU_FLIGHTREC=0`` disables recording for the A/B.

Writers are single-threaded by design (the engine is single-owner: one
driver thread feeds one recorder); readers (``/debugz`` scrapes, dump
triggers) copy the list and tolerate a record landing mid-copy — every
element is an immutable tuple, so a snapshot is always a set of
well-formed records, merely fuzzy at the newest edge.

On top of it, this module assembles **postmortem bundles**: one JSON
document carrying the flight-record runway, the metrics registry
snapshot, readiness, the in-flight request table with lifecycle stamps,
the span-tree tail, the recent structured-log ring
(:mod:`~reval_tpu.obs.logging`), and an env/config fingerprint.
:class:`PostmortemWriter` lands them as ``postmortem-<ts>.json`` with
retention (keep the newest :data:`KEEP` bundles) and a rate limit (a
fault storm must not turn into a disk storm).  Triggers live with their
owners: watchdog trip / driver exception / deadline storm in the serving
session, SIGUSR1 + SIGTERM-drain in the CLI, and ``GET /debugz`` serves
the same bundle live without writing anything.
``tools/postmortem_report.py`` renders a bundle as a human timeline.
"""

from __future__ import annotations

import json
import os
import sys
import time

from ..env import env_flag, env_str

__all__ = ["CAPACITY", "KEEP", "FIELDS", "FlightRecorder",
           "PostmortemWriter", "build_bundle", "env_fingerprint"]

#: ring capacity: at one record per drive tick and ~32 decode steps per
#: tick, 4096 records cover ~130k decode steps of runway — minutes of
#: serving before a trip, a full run on the fast tier
CAPACITY = 4096

#: bundles retained on disk (oldest pruned) — see PostmortemWriter
KEEP = 8

#: positional field names of one flight record (tuples in the ring carry
#: values in exactly this order; snapshot() zips them back to dicts)
FIELDS = (
    "step",              # recorder ordinal (monotonic, never wraps)
    "ts",                # wall clock (time.time) at record
    "running",           # sequences in decode slots
    "queued",            # sequences waiting in the native scheduler
    "free_pages",        # KV pool pages free
    "cached_pages",      # pages held by the radix prefix cache
    "pinned_pages",      # cache pages pinned by riders (decimated sample)
    "tier_queue",        # KV-tier spill queue depth (kv_tiers.py; 0 = off)
    "prefix_hit_tokens",  # cumulative cache-hit tokens (delta = per-step)
    "spec_accepted",     # cumulative accepted draft tokens (speculative)
    "chunk_steps",       # decode steps of the in-flight/last chunk
    "step_ms",           # this drive tick's wall time
    "hb_age_ms",         # watchdog heartbeat age when the tick ended
    "seq_ids",           # sequence ids in the active slots (last touched)
)


class FlightRecorder:
    """Bounded ring of per-step records; see the module docstring for
    the concurrency and cost model."""

    __slots__ = ("capacity", "enabled", "total", "_buf")

    def __init__(self, capacity: int = CAPACITY, enabled: bool | None = None):
        if enabled is None:
            enabled = env_flag("REVAL_TPU_FLIGHTREC", True)
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self.total = 0                       # records ever written
        self._buf: list = [None] * self.capacity

    def record(self, running: int, queued: int, free_pages: int,  # hot-path
               cached_pages: int, pinned_pages: int, tier_queue: int,
               prefix_hit_tokens: int, spec_accepted: int, chunk_steps: int,
               step_s: float, hb_age: float, seq_ids: tuple) -> None:
        """One drive tick's state.  Single tuple store; no locking (one
        writer — the engine's driver thread)."""
        if not self.enabled:
            return
        n = self.total
        self._buf[n % self.capacity] = (
            n, time.time(), running, queued, free_pages, cached_pages,
            pinned_pages, tier_queue, prefix_hit_tokens, spec_accepted,
            chunk_steps, step_s * 1e3, hb_age * 1e3, seq_ids)
        self.total = n + 1

    def records(self, last: int | None = None) -> list[tuple]:
        """Retained records oldest → newest (raw tuples, FIELDS order)."""
        n, cap = self.total, self.capacity
        buf = list(self._buf)                # one racy-but-atomic copy
        if n <= cap:
            out = [r for r in buf[:n] if r is not None]
        else:
            head = n % cap
            out = [r for r in buf[head:] + buf[:head] if r is not None]
        out.sort(key=lambda r: r[0])         # writer may race the copy
        return out[-last:] if last is not None else out

    def snapshot(self, last: int | None = None) -> list[dict]:
        """Retained records as JSON-able dicts (postmortem encoding)."""
        return [
            {k: (list(v) if isinstance(v, tuple) else
                 round(v, 3) if isinstance(v, float) else v)
             for k, v in zip(FIELDS, rec)}
            for rec in self.records(last)
        ]


def env_fingerprint(extra: dict | None = None) -> dict:
    """What was this process?  Every ``REVAL_TPU_*`` env knob, the
    interpreter, and the jax version if jax was loaded (never imports
    it — a mock serve stays host-only)."""
    jax_mod = sys.modules.get("jax")
    fp = {
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "platform": sys.platform,
        "jax": getattr(jax_mod, "__version__", None),
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith("REVAL_TPU_") or k == "JAX_PLATFORMS"},
    }
    if extra:
        fp.update(extra)
    return fp


def build_bundle(reason: str, envelope: bool = True, **sections) -> dict:
    """Assemble a postmortem bundle: the common envelope (version,
    timestamps, reason, env fingerprint, recent structured-log ring)
    plus whatever sections the caller owns (``flight``, ``metrics``,
    ``readiness``, ``inflight``, ``requests``, ``spans``, ``replicas``,
    ``error`` …).  ``envelope=False`` skips the process-global parts —
    a dp replica's sub-bundle must not repeat the fingerprint and log
    ring its parent envelope already carries, once per replica."""
    bundle: dict = {"reason": reason}
    if envelope:
        from . import logging as obs_logging

        bundle.update(
            postmortem_version=1,
            ts=time.time(),
            iso=time.strftime("%Y-%m-%dT%H:%M:%S"),
            fingerprint=env_fingerprint(),
            recent_logs=obs_logging.recent(64),
        )
    for key, value in sections.items():
        if value is not None:
            bundle[key] = value
    return bundle


class PostmortemWriter:
    """Land bundles on disk: ``<dir>/postmortem-<ts>-<seq>-<pid>.json``.

    - **atomic**: written to a ``.tmp`` sibling and renamed, so a
      concurrent reader (or a crash mid-write) never sees a torn file;
    - **retained**: only the newest ``keep`` bundles survive — a
      long-lived server cannot fill the disk with trip history;
    - **rate-limited PER REASON**: at most one bundle per
      ``min_interval_s`` for a given trigger — a chaos/fault storm
      collapses to its first dump per window, but a ``sigterm_drain``
      landing right after a ``driver_exception`` still writes (distinct
      triggers carry distinct stories);
    - **non-fatal**: every failure is swallowed into a structured log
      event; diagnostics must never take the serving path down.

    Default directory: ``REVAL_TPU_POSTMORTEM_DIR`` or ``tpu_watch/``
    (the repo's scratch-artifact convention; created on demand).
    """

    def __init__(self, directory: str | None = None, keep: int = KEEP,
                 min_interval_s: float = 2.0):
        self.directory = (directory
                          or env_str("REVAL_TPU_POSTMORTEM_DIR")
                          or "tpu_watch")
        self.keep = int(keep)
        self.min_interval_s = float(min_interval_s)
        self._last_dump: dict[str, float] = {}   # reason -> last success
        self._seq = 0                            # per-writer write counter

    def dump(self, bundle: dict) -> str | None:
        """Write one bundle; returns the path, or None (rate-limited or
        failed — failure is logged, never raised).  The rate limit is
        per ``reason`` and only a SUCCESSFUL write arms it, so a failed
        attempt (disk hiccup) does not suppress the retry."""
        from . import logging as obs_logging

        reason = str(bundle.get("reason"))
        now = time.monotonic()
        last = self._last_dump.get(reason)
        if last is not None and now - last < self.min_interval_s:
            return None
        try:
            os.makedirs(self.directory, exist_ok=True)
            stamp = time.strftime("%Y%m%d-%H%M%S")
            ms = int(time.time() * 1000) % 1000
            # fixed-width per-writer sequence: two dumps in the same
            # millisecond must not collide (retention prunes by name
            # sort, so the disambiguator has to sort in write order)
            self._seq += 1
            name = (f"postmortem-{stamp}-{ms:03d}"
                    f"-{self._seq:04d}-{os.getpid()}.json")
            path = os.path.join(self.directory, name)
            with open(path + ".tmp", "w") as f:
                json.dump(bundle, f, default=str)
            os.replace(path + ".tmp", path)
            self._last_dump[reason] = now
            self._prune()
            obs_logging.log_event("session.postmortem", path=path,
                                  reason=reason)
            return path
        except OSError as exc:
            obs_logging.log_event("session.postmortem", level="error",
                                  exc=exc, reason=reason)
            return None

    def _prune(self) -> None:
        bundles = sorted(
            f for f in os.listdir(self.directory)
            if f.startswith("postmortem-") and f.endswith(".json"))
        for stale in bundles[:-self.keep] if self.keep > 0 else bundles:
            try:
                os.remove(os.path.join(self.directory, stale))
            except OSError:
                pass
