"""Declared ``REVAL_TPU_*`` environment-variable namespace.

The metrics registry (``obs/metrics.py::METRICS``) and the structured-log
events (``obs/logging.py::EVENTS``) each declare their namespace ONCE and
lint call sites against it.  Env vars were the last config surface
without that discipline: knobs accreted per module (`os.environ.get`
scattered through eight files), so a typo'd name read as "unset" forever
and the README's knob documentation drifted silently — exactly the
backend-invariant rot *The Silent Hyperparameter* (arxiv 2605.19537)
warns turns into corrupted eval results.

:data:`ENV` is the one declaration: every ``REVAL_TPU_*`` variable the
tree reads, with its default and one-line meaning.  Runtime reads go
through the typed accessors below (:func:`env_str` / :func:`env_int` /
:func:`env_float` / :func:`env_flag`), which raise ``KeyError`` on an
undeclared name — a typo fails loudly at the read site instead of
silently returning the default.  The static side is the ``env`` lint
pass (``reval_tpu/analysis/envreg.py``): no raw ``os.environ[...]`` /
``getenv`` read of a ``REVAL_TPU_*`` literal may appear in ``reval_tpu/``
outside this module, every routed name must be declared here, and this
spec round-trips against the README environment table in both
directions.

Reads stay LAZY (each accessor hits ``os.environ`` at call time), so
test fixtures that ``monkeypatch.setenv`` keep working unchanged; the
handful of import-time reads (e.g. the deadline-storm threshold) keep
their historical timing at their call sites.

Writes (``os.environ["REVAL_TPU_X"] = ...``) are out of scope: tools and
benches legitimately *set* knobs for downstream readers and subprocesses.
"""

from __future__ import annotations

import os

__all__ = ["ENV", "env_raw", "env_str", "env_int", "env_float", "env_flag"]

#: falsy spellings for boolean knobs (the historical convention every
#: flag in the tree already used — keep them in one place)
_OFF = ("0", "false", "off")

#: The canonical env namespace: name -> {"default", "help"}.  ``default``
#: is the DOCUMENTED default (what an unset variable behaves like);
#: ``help``/``default`` are documentation the README table paraphrases.
#: The ``env`` lint pass round-trips the NAMES against that table in
#: both directions (defaults/meanings are prose, not machine-checked).
ENV: dict[str, dict] = {
    # -- kernel / backend selection (ops/pallas_attention.py) -------------
    "REVAL_TPU_PAGED_BACKEND": {
        "default": "autotune",
        "help": "decode-attention kernel: pallas | pallas_seq | xla | "
                "ragged | ragged_xla (ragged* also switches the engine "
                "to one-dispatch-per-tick continuous batching; default: "
                "the persisted autotune decision, else pallas on TPU / "
                "xla elsewhere)"},
    "REVAL_TPU_RAGGED_FEED": {
        "default": "256",
        "help": "ragged continuous batching: prompt tokens one drive "
                "tick feeds per still-prefilling row (the per-tick "
                "prefill quantum riding the same wave as decode rows)"},
    "REVAL_TPU_KERNEL_DOT": {
        "default": "swap",
        "help": "Pallas decode-kernel dot mode: swap | wide"},
    "REVAL_TPU_FORCE_MOSAIC": {
        "default": "0",
        "help": "force compiled (non-interpret) Pallas lowering even "
                "off-TPU — AOT capture tooling"},
    "REVAL_TPU_AUTOTUNE_FILE": {
        "default": "tpu_watch/autotune.json",
        "help": "path of the persisted autotune decision consulted for "
                "kernel defaults"},
    # -- engine ------------------------------------------------------------
    "REVAL_TPU_PIPELINE": {
        "default": "1",
        "help": "one-deep decode-chunk pipelining (0 disables — the A/B)"},
    "REVAL_TPU_PROFILE": {
        "default": "",
        "help": "when set to a directory, each generate() writes a "
                "jax.profiler trace into it"},
    # -- speculative + constrained decoding (reval_tpu/decoding/,
    #    inference/tpu/paged_engine.py) ------------------------------------
    "REVAL_TPU_SPEC": {
        "default": "1",
        "help": "speculative decoding master switch (0 restores plain "
                "decode byte-for-byte; grammar logit masking is a "
                "separate per-request feature and stays honored)"},
    "REVAL_TPU_SPEC_K": {
        "default": "8",
        "help": "max draft tokens per verify window (the batched verify "
                "scores K drafts + 1 bonus position per dispatch)"},
    "REVAL_TPU_SPEC_NGRAM": {
        "default": "3",
        "help": "prompt-lookup n-gram order for the self-drafting "
                "proposer (0 disables n-gram drafting; grammar-forced "
                "drafting stays on)"},
    # -- observability -----------------------------------------------------
    "REVAL_TPU_OBS": {
        "default": "1",
        "help": "latency-histogram observation (0 disables; counters "
                "stay on — bench --no-obs sets this)"},
    "REVAL_TPU_FLIGHTREC": {
        "default": "1",
        "help": "per-tick flight-recorder ring (0 disables — the A/B)"},
    "REVAL_TPU_POSTMORTEM_DIR": {
        "default": "tpu_watch",
        "help": "where crash-dump postmortem bundles land"},
    "REVAL_TPU_LOG_LEVEL": {
        "default": "info",
        "help": "structured-log emission floor: debug | info | warning "
                "| error"},
    "REVAL_TPU_LOG": {
        "default": "1",
        "help": "structured-log stderr emission (0 silences; the "
                "in-process ring still records)"},
    # -- warm restarts (inference/tpu/aot_cache.py, serving/session.py,
    #    serving/supervisor.py) -------------------------------------------
    "REVAL_TPU_AOT_CACHE_DIR": {
        "default": "",
        "help": "persistent AOT executable-cache directory (empty "
                "disables; engines serialize tracked-jit variants there "
                "and restarts load them instead of recompiling; also "
                "enables jax's own persistent compilation cache under "
                "<dir>/xla)"},
    "REVAL_TPU_AOT_CACHE_MAX_MB": {
        "default": "2048",
        "help": "AOT cache size bound in MB; LRU entries past it are "
                "GC'd after each store"},
    "REVAL_TPU_SNAPSHOT_PATH": {
        "default": "",
        "help": "warm-state snapshot file (empty disables): graceful "
                "drain writes the prefix-cache token tree there, boot "
                "replays it through prefill before /readyz flips"},
    "REVAL_TPU_SUPERVISE_MAX_DEATHS": {
        "default": "5",
        "help": "child deaths inside the rapid-death window before the "
                "supervisor goes sticky-failed instead of respawning"},
    "REVAL_TPU_SUPERVISE_WINDOW_S": {
        "default": "60",
        "help": "the supervisor's rapid-death window in seconds (deaths "
                "older than this age out of the budget)"},
    "REVAL_TPU_SUPERVISE_BACKOFF_S": {
        "default": "0.5",
        "help": "base respawn backoff in seconds (doubles per rapid "
                "death, jittered, capped at 30 s — RetryPolicy schedule)"},
    # -- hierarchical KV tiering (inference/tpu/kv_tiers.py) ---------------
    "REVAL_TPU_KVTIER": {
        "default": "1",
        "help": "hierarchical KV page tiering (0 disables: evicted "
                "prefix-cache pages are simply lost; spill/promote only "
                "run at eviction and insert, the resident hot path is "
                "unchanged either way)"},
    "REVAL_TPU_KVTIER_HOST_MB": {
        "default": "256",
        "help": "host-DRAM tier byte bound in MB; LRU payloads past it "
                "are dropped (disk-backed entries demote to path-only)"},
    "REVAL_TPU_KVTIER_QUEUE": {
        "default": "64",
        "help": "spill handoff queue bound in pages; a full queue drops "
                "the spill (counted) so a slow host path never wedges "
                "the drive tick"},
    "REVAL_TPU_KVTIER_TIMEOUT_S": {
        "default": "5.0",
        "help": "promotion deadline in seconds; a fetch past it raises "
                "the timeout rung of the degrade ladder and the page "
                "recomputes from its token chain"},
    # -- serving lifecycle (serving/session.py) ----------------------------
    "REVAL_TPU_MAX_QUEUED_TOKENS": {
        "default": "0",
        "help": "admission-control watermark in pending prompt tokens "
                "(0 = 4 x slots x max_seq_len)"},
    "REVAL_TPU_WATCHDOG_S": {
        "default": "120",
        "help": "no-progress watchdog threshold in seconds (0 disables)"},
    "REVAL_TPU_DEADLINE_STORM": {
        "default": "3",
        "help": "deadline expiries in one driver sweep that trigger a "
                "postmortem bundle"},
    # -- fleet router (serving/router.py) ----------------------------------
    "REVAL_TPU_ROUTER_VNODES": {
        "default": "64",
        "help": "virtual nodes per replica on the router's "
                "consistent-hash ring"},
    "REVAL_TPU_ROUTER_EJECT_FAILS": {
        "default": "3",
        "help": "consecutive forward/health failures before the router "
                "ejects a replica"},
    "REVAL_TPU_ROUTER_COOLDOWN_S": {
        "default": "5",
        "help": "seconds an ejected replica sits out before a half-open "
                "probe may rejoin it"},
    "REVAL_TPU_ROUTER_AFFINITY_WINDOW": {
        "default": "1024",
        "help": "prompt-prefix window (chars) hashed into the routing "
                "affinity key (an --affinity-table overrides it)"},
    "REVAL_TPU_ROUTER_HEALTH_INTERVAL_S": {
        "default": "1",
        "help": "router /readyz poll interval per replica, in seconds"},
    "REVAL_TPU_ROUTER_MAX_INFLIGHT": {
        "default": "0",
        "help": "fleet-wide concurrent-forward ceiling for weighted "
                "per-tenant admission (0 disables; above it a tenant "
                "over its weight share sheds first)"},
    "REVAL_TPU_ROUTER_PIN_TENANTS": {
        "default": "",
        "help": "comma-separated tenants pinned to one receipt config "
                "fingerprint: forwards skip divergent replicas and "
                "shed typed-429 when only those remain (empty "
                "disables)"},
    # -- open-loop load generator (tools/loadgen.py) -----------------------
    "REVAL_TPU_LOADGEN_SEED": {
        "default": "0",
        "help": "seed for the loadgen arrival processes and workload "
                "sampling (same seed = bit-identical schedule)"},
    "REVAL_TPU_LOADGEN_CONCURRENCY": {
        "default": "256",
        "help": "loadgen in-flight request ceiling; arrivals past it "
                "queue client-side with their lateness counted against "
                "the SLO, never re-timed (open-loop)"},
    # -- SLO-driven autoscaler (serving/autoscaler.py) ---------------------
    "REVAL_TPU_AUTOSCALE_INTERVAL_S": {
        "default": "2",
        "help": "autoscaler observation cadence: one router /metrics "
                "scrape + policy decision per interval"},
    "REVAL_TPU_AUTOSCALE_COOLDOWN_S": {
        "default": "15",
        "help": "seconds after any scaling action during which further "
                "actions are suppressed (anti-flap, with the "
                "consecutive-observation hysteresis)"},
    "REVAL_TPU_AUTOSCALE_MIN_REPLICAS": {
        "default": "1",
        "help": "floor the autoscaler never drains below"},
    "REVAL_TPU_AUTOSCALE_MAX_REPLICAS": {
        "default": "4",
        "help": "ceiling the autoscaler never spawns past"},
    "REVAL_TPU_AUTOSCALE_TTFT_P99_S": {
        "default": "0.5",
        "help": "scale-up SLO target: federated p99 TTFT (per "
                "observation interval) above this breaches"},
    # -- kernel CI / autotune leaderboard (reval_tpu/kernelbench.py) -------
    "REVAL_TPU_KERNELBENCH_DIR": {
        "default": "tpu_watch",
        "help": "where kernelbench-<ts>.json leaderboard artifacts land"},
    "REVAL_TPU_KERNELBENCH_PERTURB": {
        "default": "",
        "help": "chaos hook: '<cell>=<factor>' multiplies the named "
                "cell's measured ms/step so the regression gate's exit-1 "
                "path is drillable (tests only; the artifact is marked "
                "perturbed and never counts as evidence)"},
    "REVAL_TPU_KERNELBENCH_NOISE": {
        "default": "0.15",
        "help": "regression-gate noise band: HEAD slower than the "
                "incumbent winner cell by more than this fraction fails "
                "the round (exit 1, named cell)"},
    "REVAL_TPU_DECODE_CHUNK": {
        "default": "32",
        "help": "paged-engine decode steps per host sync (read once at "
                "import; the kernelbench autotune pick exports the "
                "measured-best cadence via decided_env.sh)"},
    # -- determinism observatory (obs/determinism.py) ----------------------
    "REVAL_TPU_DETERMINISM_REF": {
        "default": "paged-xla-fp32-b2",
        "help": "reference cell every divergence-matrix cell diffs "
                "against (a taxonomy cell name)"},
    "REVAL_TPU_DETERMINISM_TOPK": {
        "default": "8",
        "help": "logit-fingerprint width: top-k ids + quantized values "
                "recorded per probe per cell"},
    "REVAL_TPU_DETERMINISM_DIR": {
        "default": "tpu_watch",
        "help": "where determinism-<ts>.json matrix artifacts land"},
    "REVAL_TPU_DETERMINISM_PERTURB": {
        "default": "",
        "help": "chaos hook: inject an lm_head logit perturbation into "
                "the named cell so the parity gate trips (tests only)"},
    # -- multi-host rig (parallel/distributed.py) --------------------------
    "REVAL_TPU_COORDINATOR": {
        "default": "",
        "help": "jax.distributed coordinator address for manual "
                "multi-host launches"},
    "REVAL_TPU_NUM_PROCESSES": {
        "default": "",
        "help": "jax.distributed process count for manual multi-host "
                "launches"},
    "REVAL_TPU_PROCESS_ID": {
        "default": "",
        "help": "this host's jax.distributed process id for manual "
                "multi-host launches"},
    # -- tools / bench / tests ---------------------------------------------
    "REVAL_TPU_TOKENIZER": {
        "default": "",
        "help": "tokenizer dir (or tokenizer.json) bench.py prefers over "
                "cached HF snapshots"},
    "REVAL_TPU_DRYRUN_34B": {
        "default": "0",
        "help": "opt into the ~17 GB 34B-shape dryrun (graft entry + "
                "test_northstar_34b)"},
    "REVAL_TPU_DRYRUN_70B": {
        "default": "0",
        "help": "opt into the 70B-shape sharded-compile dryrun"},
    "REVAL_TPU_JITCHECK": {
        "default": "0",
        "help": "1 = run tests under the runtime recompile sanitizer "
                "(post-warmup jit variants fail the session; "
                "jax.transfer_guard over the paged drive tick — "
                "analysis/jitcheck.py; test-only, the reval_jit_* "
                "counters stay on regardless)"},
    "REVAL_TPU_EXCLUSIVE_DEVICE": {
        "default": "auto",
        "help": "bench stall-watchdog device ownership: 1 = this "
                "process owns the chip exclusively (never spawn a "
                "second jax process to probe; consult the tpu_watch "
                "tunnel-health marker instead), 0 = tunneled/shared "
                "(a LIVE watcher's heartbeat verdict takes precedence; "
                "subprocess probe only without one), auto = exclusive "
                "unless the tunnel watcher's marker files are fresh "
                "(<30 min)"},
    "REVAL_TPU_SHARDCHECK": {
        "default": "0",
        "help": "1 = run tests under the runtime sharding sanitizer "
                "(declared-vs-actual sharding divergences on guarded "
                "jit entries fail the session — analysis/shardcheck.py; "
                "test-only, the reval_shard_* counters stay on "
                "regardless)"},
    "REVAL_TPU_LOCKCHECK": {
        "default": "0",
        "help": "1 = run tests under the runtime lock sanitizer "
                "(acquisition-order inversions, off-lock guarded writes "
                "— analysis/lockcheck.py; test-only, never in prod "
                "paths)"},
}


def _spec(name: str) -> dict:
    spec = ENV.get(name)
    if spec is None:
        raise KeyError(
            f"env var {name!r} is not declared in reval_tpu.env.ENV — "
            f"declare it there (and in the README environment table) first")
    return spec


def env_raw(name: str) -> str | None:
    """The raw value, or None when unset.  ``name`` must be declared."""
    _spec(name)
    return os.environ.get(name)


def env_str(name: str, default: str | None = None) -> str | None:
    """String knob: the set value, else ``default`` exactly as given
    (callers keep their own ``or``-chains for empty-string semantics)."""
    value = env_raw(name)
    return value if value is not None else default


def env_int(name: str, default: int | None = None) -> int | None:
    """Integer knob; unset OR empty falls back to ``default``."""
    value = env_raw(name)
    if value is None or value == "":
        return default
    return int(value)


def env_float(name: str, default: float | None = None) -> float | None:
    """Float knob; unset OR empty falls back to ``default``."""
    value = env_raw(name)
    if value is None or value == "":
        return default
    return float(value)


def env_flag(name: str, default: bool = True) -> bool:
    """Boolean knob with the tree's historical falsy spellings
    (``0``/``false``/``off``, case-insensitive)."""
    value = env_raw(name)
    if value is None:
        return default
    return value.lower() not in _OFF
