"""reval_tpu — a TPU-native framework for evaluating LLMs on program
runtime-behavior reasoning (the DREval benchmark family).

Capabilities mirror the reference REval harness (see SURVEY.md): four tasks
(coverage / path / state / output) plus a cross-task consistency score, with
ground truth obtained by tracing real CPython execution.  Inference runs
in-tree on TPUs via JAX/XLA (pjit-sharded models over an ICI mesh, Pallas
attention kernels, paged KV cache) instead of the reference's vLLM/CUDA path.

Layout:
    dynamics/   ground-truth execution tracing (host CPU, pure Python)
    datasets/   DREval benchmark data loaders and constants
    prompting/  byte-compatible few-shot prompt templates
    tasks/      the four tasks + consistency scoring engine
    inference/  backends: tpu (in-tree JAX engine), openai, replay, mock
    models/     JAX model definitions (llama-family, gemma, starcoder2)
    ops/        Pallas TPU kernels and their XLA fallbacks
    parallel/   mesh construction, sharding rules, ring attention
    runtime/    scheduling / paged-KV bookkeeping (C++ with Python fallback)
"""

__version__ = "0.1.0"
