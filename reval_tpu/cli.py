"""Command-line interface: config wizard + run dispatch.

Capability parity with the reference CLI (evaluation.py:1065-1189):
``config`` interactively builds a JSON run-config; ``run`` loads it and
dispatches a task.  Differences by design: stdlib prompts instead of the
``bullet`` dependency, TPU knobs (mesh shape, chip count) instead of
``num_gpus``/``CUDA_VISIBLE_DEVICES``, and ``dataset``/``split`` are
explicit config (SURVEY §2.10 fix).

Usage:
    python -m reval_tpu config [-o .eval_config]
    python -m reval_tpu run    [-i .eval_config] [--mock]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["main", "run_with_config", "build_config_interactively"]

DEFAULT_CONFIG = ".eval_config"

TASK_CHOICES = ("coverage", "path", "state", "output", "consistency")
PROMPT_CHOICES = ("direct", "cot")
DATASET_CHOICES = ("humaneval", "classeval", "mbpp", "mathqa")
BACKEND_CHOICES = ("tpu", "openai", "server", "replay", "mock")


def _choose(prompt: str, choices: tuple[str, ...], default: str | None = None) -> str:
    default = default or choices[0]
    menu = ", ".join(choices)
    while True:
        raw = input(f"{prompt} [{menu}] (default {default}): ").strip()
        if not raw:
            return default
        if raw in choices:
            return raw
        print(f"  invalid choice {raw!r}")


def _ask(prompt: str, default, cast=str):
    raw = input(f"{prompt} (default {default}): ").strip()
    if not raw:
        return default
    return cast(raw)


def _ask_path(prompt: str, default: str = "") -> str:
    """`_ask` with readline TAB-completion over the filesystem — the
    reference wizard's path affordance (reference evaluation.py:1070-1125
    uses bullet + readline; stdlib readline covers the completion part).
    Falls back to a plain prompt where readline is unavailable (win32,
    non-tty pipes in tests)."""
    try:
        import glob
        import readline
    except ImportError:
        return _ask(prompt, default)

    def complete(text: str, state: int):
        hits = glob.glob(os.path.expanduser(text) + "*")
        hits = [h + ("/" if os.path.isdir(h) else "") for h in hits]
        return hits[state] if state < len(hits) else None

    old_completer = readline.get_completer()
    old_delims = readline.get_completer_delims()
    readline.set_completer(complete)
    readline.set_completer_delims(" \t\n")
    readline.parse_and_bind("tab: complete")
    try:
        return _ask(prompt, default)
    finally:
        readline.set_completer(old_completer)
        readline.set_completer_delims(old_delims)
        # parse_and_bind is global: un-bind TAB or every later plain
        # _ask prompt keeps filesystem completion
        readline.parse_and_bind('"\t": self-insert')


def build_config_interactively() -> dict:
    cfg: dict = {}
    cfg["task"] = _choose("Select a task", TASK_CHOICES)
    cfg["prompt_type"] = _choose("Select prompt type", PROMPT_CHOICES)
    cfg["dataset"] = _choose("Select dataset", DATASET_CHOICES)
    backend = _choose("Select backend", BACKEND_CHOICES, default="tpu")
    if backend == "openai":
        cfg["model_id"] = _choose("Select a model", ("gpt-3.5", "gpt-4"))
    else:
        cfg["model_id"] = _ask("Enter model name", "deepseek-coder-1.3b")
        if backend == "tpu":
            cfg["model_path"] = _ask_path(
                "Enter model path (HF checkpoint dir; TAB completes)", "")
            cfg["num_chips"] = _ask("Number of TPU chips (tensor-parallel)", 1, int)
            cfg["dp_size"] = _ask("Data-parallel degree", 1, int)
            cfg["pp_size"] = _ask("Pipeline-parallel stages (1 = off)", 1, int)
            cfg["sp_size"] = _ask("Sequence-parallel degree (1 = off; "
                                  "long-context ring prefill)", 1, int)
        elif backend == "server":
            cfg["port"] = _ask("Enter port number", 3000, int)
        elif backend == "replay":
            cfg["replay_task"] = cfg["task"]
    cfg["backend"] = backend
    cfg["temp"] = _ask("Set temperature", 0.8, float)
    return cfg


def write_config(path: str = DEFAULT_CONFIG) -> None:
    cfg = build_config_interactively()
    with open(path, "w") as f:
        json.dump(cfg, f)
    print(f"Configuration saved to {path}")


def run_with_config(load_path: str = DEFAULT_CONFIG, mock: bool = False,
                    overrides: dict | None = None) -> dict | float:
    """Load a config file and execute the selected task.  Returns the
    metrics dict (tasks) or score (consistency)."""
    if not os.path.exists(load_path):
        print(f"Error: {load_path} not found — run `python -m reval_tpu config` first")
        sys.exit(1)
    with open(load_path) as f:
        cfg = json.load(f)
    cfg.update(overrides or {})
    return run_config(cfg, mock=mock)


def run_config(cfg: dict, mock: bool = False) -> dict | float:
    from .inference import create_backend
    from .tasks import TASKS, ConsistencyScorer

    print(f"The arguments for this run: {cfg}")
    task_name = cfg["task"]
    if task_name == "consistency":
        from .inference.base import model_info_from_config

        if mock:
            cfg = {**cfg, "custom_mock": True}
        model_info = model_info_from_config(cfg)
        scorer = ConsistencyScorer(model_info, cfg["dataset"],
                                   results_dir=cfg.get("results_dir", "model_generations"))
        return scorer.run()

    if cfg.get("prompt_type") == "tot":
        # trace-of-thoughts runs score trace dumps; no model backend exists
        backend = None
    elif mock or cfg.get("custom_mock"):
        backend = None
        cfg["custom_mock"] = True
    else:
        backend = create_backend(
            **{k: v for k, v in cfg.items() if k not in ("task", "mock")},
            mock=bool(cfg.get("mock")) or cfg.get("backend") == "mock")
    task_cls = TASKS[task_name]
    # model_id stays in the kwargs: tot runs use it for the results-dir name
    task = task_cls(model=backend,
                    **{k: v for k, v in cfg.items() if k not in ("task", "backend")})
    try:
        return task.run()
    finally:
        if backend is not None:
            backend.close()


def run_taskgen(argv: list[str]) -> int:
    """Regenerate DREval task/data JSONL (reference taskgen.py __main__)."""
    from .datasets import Families, DREvalDataset
    from .datasets.dreval import data_dir
    from . import taskgen as tg

    parser = argparse.ArgumentParser(prog="reval_tpu taskgen",
                                     description="(Re)generate DREval task/data files")
    parser.add_argument("--dataset", default="humaneval_classeval",
                        choices=["humaneval", "classeval", "humaneval_classeval",
                                 "mbpp", "mathqa"])
    parser.add_argument("--out", default=str(data_dir()), help="output directory")
    args = parser.parse_args(argv)
    out_dir = args.out

    if args.dataset in ("humaneval", "classeval", "humaneval_classeval"):
        ds = DREvalDataset.load("humaneval", "main")
        indices = sorted(i for i in ds.by_idx if i <= Families.CLASSEVAL_END)
        if args.dataset == "humaneval":
            indices = [i for i in indices if i <= Families.HUMANEVAL_END]
        elif args.dataset == "classeval":
            indices = [i for i in indices if i >= Families.CLASSEVAL_START]
        rows, stats = tg.generate_humaneval_classeval(ds, indices=indices)
        path = tg.write_jsonl(f"{out_dir}/DREval_tasks.{args.dataset}.regen.jsonl", rows)
        print(f"wrote {path}  stats={stats.summary()}")
    elif args.dataset == "mbpp":
        rows = tg.load_mbpp_rows()
        tasks, data, stats = tg.generate_mbpp(rows)
        print(f"wrote {tg.write_jsonl(f'{out_dir}/DREval_tasks_mbpp.regen.jsonl', tasks)}")
        print(f"wrote {tg.write_jsonl(f'{out_dir}/DREval_data_mbpp.regen.jsonl', data)}")
        print(f"stats={stats.summary()}")
    else:
        rows = tg.load_mathqa_rows()
        tasks, data, stats = tg.generate_mathqa(rows)
        print(f"wrote {tg.write_jsonl(f'{out_dir}/DREval_tasks_mathqa.regen.jsonl', tasks)}")
        print(f"wrote {tg.write_jsonl(f'{out_dir}/DREval_data_mathqa.regen.jsonl', data)}")
        print(f"stats={stats.summary()}")
    return 0


def run_tot_oracle(argv: list[str]) -> int:
    """Write ground-truth trace-of-thoughts dumps for a dataset slice."""
    from .tot import write_oracle_dumps

    parser = argparse.ArgumentParser(prog="reval_tpu tot-oracle",
                                     description="Generate oracle ToT trace dumps")
    parser.add_argument("--dataset", default="humaneval",
                        choices=["humaneval", "classeval", "mbpp", "mathqa"])
    parser.add_argument("--base-dir", required=True)
    parser.add_argument("--run-name", default="oracle")
    parser.add_argument("--max-items", type=int, default=None)
    args = parser.parse_args(argv)
    n = write_oracle_dumps(args.dataset, args.base_dir, args.run_name,
                           max_items=args.max_items)
    print(f"wrote {n} trace dumps under {args.base_dir}/{args.run_name}/{args.dataset}")
    return 0


def run_tot_generate(argv: list[str]) -> int:
    """Model-driven trace dumps: the backend simulates execution in the
    trace grammar; generations become dumps the tot scoring run consumes
    (the loop the reference left to an external harness)."""
    from .inference.base import create_backend
    from .tot import generate_trace_dumps

    parser = argparse.ArgumentParser(
        prog="reval_tpu tot-generate",
        description="Generate ToT trace dumps from a model")
    parser.add_argument("-i", "--input", default=DEFAULT_CONFIG,
                        help="backend config file (model_id/model_path/…)")
    parser.add_argument("--dataset", default="humaneval",
                        choices=["humaneval", "classeval", "mbpp", "mathqa"])
    parser.add_argument("--base-dir", required=True)
    parser.add_argument("--run-name", default=None,
                        help="default: <model_id>_trace")
    parser.add_argument("--max-items", type=int, default=None)
    args = parser.parse_args(argv)
    with open(args.input) as f:
        cfg = json.load(f)
    # traces are long: use the CoT budget unless the config overrides it
    cfg.setdefault("max_new_tokens", 1024)
    backend = create_backend(**cfg)
    run_name = args.run_name or f"{cfg.get('model_id', 'model')}_trace".replace("/", "_")
    n = generate_trace_dumps(backend, args.dataset, args.base_dir, run_name,
                             max_items=args.max_items)
    print(f"wrote {n} model trace dumps under {args.base_dir}/{run_name}/{args.dataset}")
    return 0


def run_fleet(argv: list[str]) -> int:
    """All four tasks × repeats on one resident model, then consistency
    (replaces the reference's subprocess fleet, batch_run.py)."""
    from .fleet import FLEET_TASKS, FleetRunner
    from .inference import create_backend

    parser = argparse.ArgumentParser(prog="reval_tpu fleet",
                                     description="Run the full task fleet on one model")
    parser.add_argument("-i", "--input", default=DEFAULT_CONFIG,
                        help="run-config JSON (model/backend/dataset settings)")
    parser.add_argument("--repeats", type=int, default=None,
                        help="repeat count (default: config 'repeats' or 5)")
    parser.add_argument("--mock", action="store_true")
    parser.add_argument("--max-items", type=int, default=None)
    parser.add_argument("--multihost", choices=["replicate", "global"], default=None,
                        help="multi-host mode: engine replica per host with "
                             "sharded prompts, or one globally-sharded model")
    parser.add_argument("--resume", action="store_true",
                        help="skip (repeat, task) chunks already journaled in "
                             "<results_dir>/fleet_checkpoint.jsonl (crash recovery)")
    parser.add_argument("--chaos", type=float, default=None, metavar="RATE",
                        help="inject transient faults (timeouts, 500s, truncated "
                             "JSON, latency spikes) at this per-prompt rate — "
                             "deterministic under --chaos-seed; hardening/smoke tool")
    parser.add_argument("--chaos-seed", type=int, default=None,
                        help="seed for the chaos fault schedule (default 0)")
    parser.add_argument("--no-resilience", action="store_true",
                        help="disable retry + batch bisection around the backend")
    parser.add_argument("--grammar", action="store_true",
                        help="grammar-constrained decoding: each task decodes "
                             "under its answer-shape automaton (coverage → "
                             "yesno, path → line, state → value;type, output "
                             "→ assert — reval_tpu/decoding/), which also "
                             "feeds the speculative drafter; paged-engine "
                             "backends only")
    parser.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                        help="override a config key (repeatable; JSON values accepted)")
    args = parser.parse_args(argv)
    cfg = {}
    if os.path.exists(args.input):
        with open(args.input) as f:
            cfg = json.load(f)
    elif not args.mock:
        print(f"Error: {args.input} not found — run `python -m reval_tpu config` first")
        return 1
    for item in args.set:
        key, _, value = item.partition("=")
        try:
            cfg[key] = json.loads(value)
        except json.JSONDecodeError:
            cfg[key] = value
    # CLI flags win over config keys; config keys win over defaults
    repeats = args.repeats if args.repeats is not None else cfg.get("repeats", 5)
    max_items = args.max_items if args.max_items is not None else cfg.get("max_items")
    multihost = args.multihost or cfg.get("multihost")
    use_mock = (args.mock or bool(cfg.get("mock")) or bool(cfg.get("custom_mock")))
    if repeats < 1:
        print("Error: repeats must be >= 1")
        return 1
    if (args.chaos if args.chaos is not None else cfg.get("chaos")) and multihost == "global":
        # "global" runs can't wrap ResilientBackend (per-host retry would
        # desynchronise the pod's collectives), so injected faults would
        # abort the whole pod unretried — reject the combination up front
        print("Error: --chaos is incompatible with --multihost global "
              "(no retry layer can wrap pod-collective inference)")
        return 1
    if cfg.get("replay_task") or cfg.get("backend") == "replay":
        # a replay backend serves ONE task's recorded generations in order;
        # the fleet's fused batch would hand them to the wrong tasks
        print("Error: replay backends replay a single task's log — "
              "use `reval_tpu run` per task instead of `fleet`")
        return 1
    if multihost:
        from .parallel.distributed import ensure_initialized

        # must precede backend/device construction; an explicit multihost
        # request that cannot come up is fatal (N duplicate runs otherwise)
        ensure_initialized(strict=True)
    chaos = args.chaos if args.chaos is not None else cfg.get("chaos")
    chaos_seed = (args.chaos_seed if args.chaos_seed is not None
                  else cfg.get("chaos_seed", 0))
    resume = args.resume or bool(cfg.get("resume"))
    resilience = cfg.get("resilience", True) and not args.no_resilience
    # retry knobs ride the config as a dict, e.g. {"retry": {"max_attempts": 6}}
    retry_policy = None
    if cfg.get("retry"):
        from .resilience import RetryPolicy

        retry_policy = RetryPolicy(**cfg["retry"])
    backend = None
    if not use_mock:
        # "retry" stays IN backend_kwargs: HTTPClientBackend consumes the
        # same dict for its per-request policy (other backends ignore it)
        backend_kwargs = {k: v for k, v in cfg.items()
                          if k not in ("task", "mock", "backend", "chaos",
                                       "chaos_seed", "resume", "resilience",
                                       "grammar")}
        if multihost == "replicate":
            # each host runs a full replica on its OWN chips; without this
            # the engine would build its mesh over the global pod devices
            backend_kwargs["local_devices_only"] = True
        backend = create_backend(**backend_kwargs,
                                 mock=cfg.get("backend") == "mock")
    elif chaos:
        # chaos needs a shared backend to wrap; give the mock fleet one
        # explicitly (tasks still store under the mock_model_* identity)
        from .inference.mock import MockBackend

        backend = MockBackend(prompt_type=cfg.get("prompt_type", "direct"))
    if chaos and backend is not None:
        from .resilience import ChaosBackend

        backend = ChaosBackend(backend, rate=chaos, seed=chaos_seed)
        print(f"[chaos] injecting faults at rate {chaos} (seed {chaos_seed})")
    if retry_policy is not None and backend is not None:
        from .resilience import RetryPolicy as _RP

        # direct __dict__ check (matching ResilientBackend's detection):
        # a ChaosBackend wrapper would delegate getattr to the client,
        # but its faults fire above the client's retry loop, so the
        # configured policy must stay with the ResilientBackend layer
        if isinstance(getattr(backend, "__dict__", {}).get("retry"), _RP):
            # the HTTP client already applies cfg["retry"] per request;
            # handing the same policy to the ResilientBackend wrapper
            # would nest the schedules (attempts × attempts per leaf)
            retry_policy = None
    # every other config key (split, sandbox_timeout, valid_test_cases_path,
    # model_id, …) flows through to the tasks, same as `reval_tpu run`
    consumed = {"task", "backend", "mock", "custom_mock", "dataset",
                "prompt_type", "results_dir", "repeats", "progress", "tasks",
                "multihost", "run_consistency", "max_items", "chaos",
                "chaos_seed", "resume", "resilience", "retry", "grammar"}
    task_kwargs = {k: v for k, v in cfg.items() if k not in consumed}
    cfg_tasks = cfg.get("tasks", FLEET_TASKS)
    cfg_tasks = (cfg_tasks,) if isinstance(cfg_tasks, str) else tuple(cfg_tasks)
    fleet = FleetRunner(
        dataset=cfg.get("dataset", "humaneval"),
        prompt_type=cfg.get("prompt_type", "direct"),
        repeats=repeats, backend=backend, mock=use_mock,
        results_dir=cfg.get("results_dir", "model_generations"),
        run_consistency=cfg.get("run_consistency", True),
        progress=cfg.get("progress", True),
        tasks=cfg_tasks,
        multihost=multihost, resume=resume, resilience=resilience,
        retry_policy=retry_policy, max_items=max_items,
        grammar=args.grammar or bool(cfg.get("grammar")), **task_kwargs)
    try:
        result = fleet.run()
    finally:
        if backend is not None:
            backend.close()
    if chaos and hasattr(backend, "injected"):
        print(f"[chaos] {len(backend.injected)} faults injected, "
              f"{result.get('lost_prompts', 0)} prompts lost")
    print(json.dumps({"consistency": result.get("consistency"),
                      "final_repeat": result["repeats"][-1],
                      "lost_prompts": result.get("lost_prompts", 0)}))
    return 0


def _serve_smoke(server, cfg: dict, n: int, step_chaos) -> int:
    """Self-contained serve-path smoke (the tier-1 regression canary for
    the serving lifecycle, mirroring `fleet --mock --chaos`): post ``n``
    prompts CONCURRENTLY through the resilient HTTP client against the
    just-built server — engine-step chaos applies — while hammering
    ``/debugz`` from scraper threads (every response must be well-formed
    JSON, concurrency included), then scrape and VERIFY ``/metrics``
    (exposition grammar parses, every request shows up in the request
    counter and the ttft/e2e histograms), gracefully drain, and print
    one JSON summary line with the lifecycle counters.  Under
    ``--chaos-step``, additionally assert that injected ``error`` faults
    produced at least one postmortem bundle and that every bundle on
    disk parses."""
    import glob
    import threading
    import urllib.request

    from .inference.client import HTTPClientBackend
    from .obs.metrics import parse_prometheus

    server.start()
    client = HTTPClientBackend(
        model_id=cfg.get("model_id", "smoke"), port=server.port, temp=0.0,
        prompt_type="direct", wait_for_server_s=30,
        retry={"max_attempts": 10, "base_delay": 0.02,
               "max_delay": 0.5, "jitter": 0.1})
    prompts = [f"smoke prompt {i}" for i in range(n)]
    outs: dict[int, str] = {}
    errors: list[str] = []

    def post(i: int) -> None:
        try:
            outs[i] = client.infer_one(prompts[i])
        except Exception as exc:  # noqa: BLE001 — summarised below
            errors.append(f"prompt {i}: {exc!r}")

    # concurrent /debugz scrapes while requests are in flight: the live
    # bundle must be well-formed JSON no matter what the driver is doing
    debugz = {"scrapes": 0, "bad": 0}
    scrape_stop = threading.Event()

    def scrape() -> None:
        while not scrape_stop.is_set():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{server.port}/debugz",
                        timeout=10) as r:
                    bundle = json.loads(r.read())
                debugz["scrapes"] += 1
                if bundle.get("reason") != "debugz":
                    debugz["bad"] += 1
            except Exception as exc:  # noqa: BLE001 — summarised below
                debugz["bad"] += 1
                errors.append(f"/debugz: {exc!r}")
            scrape_stop.wait(0.01)

    threads = [threading.Thread(target=post, args=(i,)) for i in range(n)]
    scrapers = [threading.Thread(target=scrape, daemon=True)
                for _ in range(3)]
    for t in scrapers:
        t.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    scrape_stop.set()
    for t in scrapers:
        t.join(timeout=10)
    # receipt self-verification (tier-1 canary for the receipt path):
    # one sequential probe so the receipt↔text pairing is unambiguous,
    # then check the verified receipt surfaced (the client only keeps a
    # receipt whose X-Reval-Receipt header parsed AND agreed with the
    # body) and that its digest certifies the returned text's ids
    receipts = {"receipted": False, "digest_ok": False, "fingerprints": 0}
    try:
        from .obs.receipts import digest_matches_text

        probe_text = client.infer_one("receipt probe")
        receipt = client.last_receipt
        tok = getattr(getattr(getattr(server, "_session", None),
                              "engine", None), "tokenizer", None)
        if receipt is not None:
            receipts["receipted"] = True
            receipts["fingerprints"] = len(client.receipt_fingerprints)
            if tok is not None:
                receipts["digest_ok"] = digest_matches_text(
                    receipt, [probe_text], tok)
    except Exception as exc:  # noqa: BLE001 — summarised below
        errors.append(f"receipt probe: {exc!r}")
    # scrape BEFORE the drain (the listener closes during shutdown) and
    # self-verify: the smoke is the tier-1 canary for /metrics too
    obs = {"metrics_ok": False, "requests_total": 0,
           "ttft_count": 0, "e2e_count": 0}
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=10) as r:
            samples = parse_prometheus(r.read().decode())
        obs.update(
            metrics_ok=True,
            requests_total=int(samples.get("reval_requests_total", 0)),
            ttft_count=int(samples.get("reval_request_ttft_seconds_count", 0)),
            e2e_count=int(samples.get("reval_request_e2e_seconds_count", 0)))
    except Exception as exc:  # noqa: BLE001 — summarised below
        errors.append(f"/metrics: {exc!r}")
    server.shutdown()
    session = getattr(server, "_session", None)
    counters = (session.engine_stats()[0].serving_counters()
                if session is not None else {})   # session-less engines:
                                                  # no lifecycle counters
    # postmortem self-check: every bundle on disk parses; injected
    # `error` faults must have produced at least one (stall-only chaos
    # legitimately dumps nothing unless the watchdog trips)
    pm_dir = cfg.get("postmortem_dir")
    postmortems = 0
    if pm_dir:
        for path in glob.glob(os.path.join(pm_dir, "postmortem-*.json")):
            try:
                with open(path) as f:
                    bundle = json.load(f)
                assert bundle.get("reason"), path
                postmortems += 1
            except Exception as exc:  # noqa: BLE001 — summarised below
                errors.append(f"postmortem {path}: {exc!r}")
    chaos_errors = (sum(1 for mode, _ in step_chaos.injected
                        if mode == "error") if step_chaos else 0)
    summary = {
        "served": len(outs), "errors": len(errors), **counters, **obs,
        "chaos_injected": len(step_chaos.injected) if step_chaos else 0,
        "debugz_scrapes": debugz["scrapes"], "postmortems": postmortems,
        "receipt": receipts,
    }
    if server.trace_out:
        summary["trace_out"] = server.trace_out
    print(json.dumps(summary))
    # chaos-free runs must account for every request in the histograms;
    # under injected faults retries legitimately shift the counts
    metrics_bad = (not obs["metrics_ok"]
                   or (step_chaos is None
                       and not (obs["requests_total"] >= n
                                and obs["ttft_count"] >= n
                                and obs["e2e_count"] >= n)))
    debugz_bad = debugz["bad"] > 0 or debugz["scrapes"] == 0
    postmortem_bad = bool(pm_dir) and chaos_errors > 0 and postmortems == 0
    # the mock engine supports receipts and its ByteTokenizer round-trips
    # text↔ids exactly, so on the --mock path a receipt-less smoke, an
    # unverifiable digest, or >1 fingerprint from ONE server is a break;
    # a real checkpoint's tokenizer may be lossy — report, don't gate
    receipts_bad = bool(cfg.get("mock")) and not (
        receipts["receipted"] and receipts["digest_ok"]
        and receipts["fingerprints"] == 1)
    if (errors or len(outs) != n or metrics_bad or debugz_bad
            or postmortem_bad or receipts_bad):
        print(f"[smoke] failures: {errors[:3]}"
              + (" [metrics check failed]" if metrics_bad else "")
              + (" [debugz check failed]" if debugz_bad else "")
              + (" [postmortem check failed]" if postmortem_bad else "")
              + (" [receipt check failed]" if receipts_bad else ""))
        return 1
    return 0


def run_serve(argv: list[str]) -> int:
    """Serve the resident TPU engine over the OpenAI completions protocol
    (replaces the reference's vLLM api_server + start_server.sh)."""
    from .serving import serve_config

    parser = argparse.ArgumentParser(prog="reval_tpu serve",
                                     description="Serve the TPU engine over HTTP")
    parser.add_argument("-i", "--input", default=DEFAULT_CONFIG,
                        help="run-config JSON (model/backend settings)")
    parser.add_argument("--port", type=int, default=None,
                        help="listen port (default: config 'port' or 3000)")
    parser.add_argument("--warmup", action="store_true",
                        help="pre-compile the generation programs before "
                             "binding the port (first request otherwise "
                             "pays 20-40s of jit per shape)")
    parser.add_argument("--mock", action="store_true",
                        help="serve a host-only mock engine through the real "
                             "session/server lifecycle (no checkpoint/TPU) — "
                             "the serving smoke target")
    parser.add_argument("--chaos-step", type=float, default=None, metavar="RATE",
                        help="inject deterministic engine-step faults (stalled "
                             "step, mid-batch exception) at this per-step rate "
                             "into the serve loop — hardening/smoke tool")
    parser.add_argument("--chaos-stall-s", type=float, default=0.05,
                        help="stall duration for injected stalled steps")
    parser.add_argument("--chaos-seed", type=int, default=0,
                        help="seed for the engine-step fault schedule")
    parser.add_argument("--tier-chaos", type=float, default=None,
                        metavar="RATE",
                        help="inject deterministic KV-tier promotion faults "
                             "(corrupt page, stalled fetch, failed tier) at "
                             "this per-promotion rate — every fault must "
                             "degrade to a recompute, never a wrong token")
    parser.add_argument("--tier-chaos-modes", default=None,
                        metavar="M1,M2",
                        help="comma list of tier fault modes to draw from "
                             "(corrupt,stall,fail; default all)")
    parser.add_argument("--tier-chaos-seed", type=int, default=0,
                        help="seed for the tier fault schedule")
    parser.add_argument("--snapshot-fallback", default=None, metavar="PATH",
                        help="a SIBLING replica's warm-state snapshot to "
                             "boot from when --snapshot-path has none yet "
                             "(autoscaler scale-up warm boot; read-only)")
    parser.add_argument("--smoke", type=int, default=None, metavar="N",
                        help="self-test: serve N concurrent prompts through "
                             "the resilient client, verify /metrics covers "
                             "them, drain gracefully, print a JSON counter "
                             "summary, exit")
    parser.add_argument("--trace-out", default=None, metavar="PATH",
                        help="write a Chrome-trace/Perfetto JSON of per-"
                             "request span trees (queue wait, first token, "
                             "decode) here at shutdown; ids follow "
                             "X-Request-Id")
    parser.add_argument("--postmortem-dir", default=None, metavar="DIR",
                        help="where crash-dump bundles land (watchdog trip, "
                             "driver fault, deadline storm, SIGUSR1, SIGTERM "
                             "drain; default env REVAL_TPU_POSTMORTEM_DIR or "
                             "tpu_watch/)")
    parser.add_argument("--snapshot-path", default=None, metavar="PATH",
                        help="warm-state snapshot file: graceful drain "
                             "writes the prefix-cache token tree there, the "
                             "next boot replays it through prefill before "
                             "/readyz flips (default env "
                             "REVAL_TPU_SNAPSHOT_PATH; unset disables)")
    parser.add_argument("--supervise", action="store_true",
                        help="crash-loop supervisor: respawn this server "
                             "when it dies, with bounded exponential "
                             "backoff, a postmortem bundle per death, and "
                             "sticky-failed after REVAL_TPU_SUPERVISE_"
                             "MAX_DEATHS rapid deaths (never flaps the "
                             "router)")
    args = parser.parse_args(argv)
    cfg = {}
    if os.path.exists(args.input):
        with open(args.input) as f:
            cfg = json.load(f)
    elif not args.mock:
        print(f"Error: {args.input} not found — run `python -m reval_tpu config` first")
        return 1
    if args.mock:
        cfg["mock"] = True
    if args.snapshot_path:
        cfg["snapshot_path"] = args.snapshot_path
    if args.snapshot_fallback:
        cfg["snapshot_fallback"] = args.snapshot_fallback
    if args.tier_chaos:
        cfg["tier_chaos"] = args.tier_chaos
        cfg["tier_chaos_seed"] = args.tier_chaos_seed
        if args.tier_chaos_modes:
            cfg["tier_chaos_modes"] = args.tier_chaos_modes
        print(f"[chaos] KV-tier promotion faults at rate {args.tier_chaos} "
              f"(seed {args.tier_chaos_seed})")
    if args.supervise:
        # parent process: never builds an engine — it spawns `serve`
        # children (same argv minus --supervise) and respawns them per
        # the supervisor policy (serving/supervisor.py)
        import subprocess

        from .serving.supervisor import Supervisor

        import signal

        cmd = ([sys.executable, "-m", "reval_tpu", "serve"]
               + [a for a in argv if a != "--supervise"])
        supervisor = Supervisor(spawn=lambda: subprocess.Popen(cmd),
                                postmortem_dir=args.postmortem_dir)
        print(f"[supervise] respawning `{' '.join(cmd[2:])}` on death "
              f"(sticky-failed after {supervisor.max_deaths} rapid deaths)")
        # SIGTERM is the fleet's clean-stop signal (systemd/k8s/operator
        # kill): without a handler the default action kills only the
        # supervisor, orphaning a child that keeps holding the port —
        # the next supervisor's children then die EADDRINUSE into
        # sticky-failed while the orphan serves stale config.  Route it
        # through the same stop path as Ctrl-C.
        def _term(_signum, _frame):
            raise KeyboardInterrupt

        signal.signal(signal.SIGTERM, _term)
        try:
            return supervisor.run()
        except KeyboardInterrupt:
            supervisor.stop()
            child = supervisor.child
            if child is not None and child.poll() is None:
                child.terminate()   # SIGTERM → the child's graceful drain
                child.wait()
            return 0
    if args.trace_out:
        cfg["trace_out"] = args.trace_out
    if args.postmortem_dir:
        cfg["postmortem_dir"] = args.postmortem_dir
    elif args.smoke is not None and "postmortem_dir" not in cfg:
        # the smoke self-verifies bundle production: give it a private
        # dir so the assertion never counts someone else's dumps
        import tempfile

        cfg["postmortem_dir"] = tempfile.mkdtemp(prefix="reval-postmortem-")
    step_chaos = None
    if args.chaos_step:
        from .resilience import EngineStepChaos

        step_chaos = EngineStepChaos(rate=args.chaos_step,
                                     seed=args.chaos_seed,
                                     stall_s=args.chaos_stall_s)
        print(f"[chaos] engine-step faults at rate {args.chaos_step} "
              f"(seed {args.chaos_seed})")
    server = serve_config(cfg, port=args.port, warmup=args.warmup,
                          step_chaos=step_chaos)
    if args.smoke is not None:
        return _serve_smoke(server, cfg, args.smoke, step_chaos)
    print(f"serving {cfg.get('model_id')} on :{server.port} "
          f"(POST /v1/completions, GET /v1/models /healthz /readyz "
          f"/metrics /statusz /debugz; SIGUSR1 dumps a postmortem)")
    # orchestrators stop containers with SIGTERM: run the graceful drain
    # on a side thread WHILE serve_forever keeps answering — rejected
    # POSTs get their fast "503 draining" instead of hanging in the
    # listen backlog; shutdown() itself stops the accept loop last, which
    # unblocks serve_forever below.  Ctrl-C (KeyboardInterrupt inside the
    # accept loop) falls through to the same idempotent shutdown().
    # A SIGTERM-triggered drain first lands a postmortem bundle — the
    # flight-recorder runway of whatever the engine was doing when the
    # orchestrator pulled the plug.
    import signal
    import threading

    def _drain_with_postmortem():
        server.dump_postmortem("sigterm_drain")
        server.shutdown()

    def _sigterm(signum, frame):
        threading.Thread(target=_drain_with_postmortem, daemon=True,
                         name="sigterm-drain").start()

    def _sigusr1(signum, frame):
        # on-demand flight-data pull from a LIVE server: no drain, no
        # pause — the bundle is assembled from racy reads by design
        threading.Thread(target=server.dump_postmortem, args=("sigusr1",),
                         daemon=True, name="sigusr1-postmortem").start()

    signal.signal(signal.SIGTERM, _sigterm)
    if hasattr(signal, "SIGUSR1"):      # absent on win32
        signal.signal(signal.SIGUSR1, _sigusr1)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    server.shutdown()       # idempotent: waits for an in-progress drain
    return 0


def _router_smoke(router, servers, n: int, kill_one: bool) -> int:
    """Router self-test (the tier-1 canary for the fleet tier, the
    routing sibling of ``serve --smoke``): post ``n`` prompts spread over
    two distinct long templates through the resilient HTTP client —
    half, then (with ``kill_one`` and ≥2 replicas) hard-kill one replica
    WITHOUT drain, then the rest, so the second half exercises
    re-route/ejection — scrape and verify the federated ``/metrics``
    (exposition parses, the router accounted every request, ejections
    registered when a replica died), and print one JSON summary line."""
    import urllib.request

    from .inference.client import HTTPClientBackend
    from .obs import metrics as obs_metrics
    from .obs.metrics import parse_prometheus

    client = HTTPClientBackend(
        model_id="router-smoke", port=router.port, temp=0.0,
        prompt_type="direct", wait_for_server_s=30,
        retry={"max_attempts": 10, "base_delay": 0.05,
               "max_delay": 0.5, "jitter": 0.1})
    template_a = "TEMPLATE-A " * 40
    template_b = "TEMPLATE-B " * 40
    prompts = [(template_a if i % 2 == 0 else template_b) + f"probe {i}"
               for i in range(n)]
    outs: dict[int, str] = {}
    errors: list[str] = []

    def post(i: int) -> None:
        try:
            outs[i] = client.infer_one(prompts[i])
        except Exception as exc:  # noqa: BLE001 — summarised below
            errors.append(f"prompt {i}: {exc!r}")

    import threading

    def run_batch(indices) -> None:
        threads = [threading.Thread(target=post, args=(i,)) for i in indices]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)

    killed = False
    run_batch(range(n // 2))
    if kill_one and len(servers) >= 2:
        # a crash, not a drain: in-flight sockets die, the router must
        # eject the corpse and re-route the rest of the smoke
        victim = servers[0]
        victim._httpd.shutdown()
        victim._httpd.server_close()
        killed = True
    run_batch(range(n // 2, n))
    if killed:
        # give the health poller its consecutive-failure window so the
        # corpse's ejection lands in the scraped counters
        import time as _time

        deadline = _time.monotonic() + 10.0
        while (_time.monotonic() < deadline
               and not router._obs.counter(
                   obs_metrics.ROUTER_EJECTIONS).value):
            _time.sleep(0.05)
    obs = {"metrics_ok": False, "router_requests": 0, "ejections": 0,
           "failovers": 0}
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{router.port}/metrics", timeout=10) as r:
            samples = parse_prometheus(r.read().decode())
        obs.update(
            metrics_ok=True,
            router_requests=int(samples.get(obs_metrics.ROUTER_REQUESTS, 0)),
            ejections=int(samples.get(obs_metrics.ROUTER_EJECTIONS, 0)),
            failovers=int(samples.get(obs_metrics.ROUTER_FAILOVERS, 0)))
    except Exception as exc:  # noqa: BLE001 — summarised below
        errors.append(f"/metrics: {exc!r}")
    router.shutdown()
    for srv in (servers[1:] if killed else servers):
        srv.shutdown()
    summary = {"served": len(outs), "errors": len(errors),
               "killed_replica": killed, **obs}
    print(json.dumps(summary))
    bad = (errors or len(outs) != n or not obs["metrics_ok"]
           or obs["router_requests"] < n
           or (killed and obs["ejections"] < 1))
    if bad:
        print(f"[router-smoke] failures: {errors[:3]}")
        return 1
    return 0


def run_router(argv: list[str]) -> int:
    """Fleet router: consistent-hash prefix-affinity routing over N
    `reval_tpu serve` replicas, with health tracking, failover, and
    /metrics federation (serving/router.py)."""
    from .serving import FleetRouter, serve_config

    parser = argparse.ArgumentParser(
        prog="reval_tpu router",
        description="Route completions across a fleet of engine servers")
    parser.add_argument("--replicas", default=None,
                        help="comma-separated replica endpoints "
                             "(host:port or bare ports)")
    parser.add_argument("--port", type=int, default=3100,
                        help="router listen port (default 3100; 0 = any)")
    parser.add_argument("--affinity-table", default=None, metavar="PATH",
                        help="hash-ring seed from `tools/prefix_stats.py "
                             "--json` (sets the affinity window and names "
                             "the template keys)")
    parser.add_argument("--window-chars", type=int, default=None,
                        help="affinity-key prefix window in chars (default "
                             "env REVAL_TPU_ROUTER_AFFINITY_WINDOW or 1024)")
    parser.add_argument("--eject-fails", type=int, default=None,
                        help="consecutive failures before ejecting a replica")
    parser.add_argument("--cooldown-s", type=float, default=None,
                        help="ejection cooldown before a half-open probe")
    parser.add_argument("--health-interval-s", type=float, default=None,
                        help="/readyz poll interval per replica")
    parser.add_argument("--max-inflight", type=int, default=None,
                        help="fleet concurrency ceiling for weighted "
                             "per-tenant admission (default env "
                             "REVAL_TPU_ROUTER_MAX_INFLIGHT; 0 = off)")
    parser.add_argument("--tenant-weights", default=None, metavar="SPEC",
                        help="per-tenant admission weights: "
                             "'alpha:3,beta:1' or a JSON object "
                             "(unlisted tenants weigh 1.0)")
    parser.add_argument("--mock", type=int, default=None, metavar="N",
                        help="spawn N in-process mock replicas (host-only "
                             "fleet; the smoke/drill target)")
    parser.add_argument("--smoke", type=int, default=None, metavar="M",
                        help="self-test: M prompts through the resilient "
                             "client with a mid-smoke replica kill (when "
                             "≥2 replicas), verify the federated /metrics, "
                             "print a JSON summary, exit")
    parser.add_argument("--no-kill", action="store_true",
                        help="smoke only: skip the mid-smoke replica kill")
    args = parser.parse_args(argv)
    servers = []
    replicas = []
    if args.mock:
        for _ in range(args.mock):
            srv = serve_config({"mock": True, "mock_echo": True}, port=0)
            srv.start()
            servers.append(srv)
            replicas.append(f"127.0.0.1:{srv.port}")
    if args.replicas:
        replicas.extend(r.strip() for r in args.replicas.split(",")
                        if r.strip())
    if not replicas:
        print("Error: no replicas (--replicas and/or --mock N)")
        return 1
    tenant_weights = None
    if args.tenant_weights:
        from .serving.router import parse_tenant_weights

        try:
            tenant_weights = parse_tenant_weights(args.tenant_weights)
        except ValueError as exc:
            print(f"Error: {exc}")
            return 1
    router = FleetRouter(
        replicas, port=args.port if args.smoke is None else 0,
        window_chars=args.window_chars, eject_fails=args.eject_fails,
        cooldown_s=args.cooldown_s,
        health_interval_s=(args.health_interval_s
                           if args.health_interval_s is not None
                           else (0.1 if args.smoke is not None else None)),
        affinity_table=args.affinity_table,
        tenant_weights=tenant_weights, max_inflight=args.max_inflight)
    router.start()
    if args.smoke is not None:
        return _router_smoke(router, servers, args.smoke,
                             kill_one=not args.no_kill)
    print(f"routing {len(replicas)} replicas on :{router.port} "
          f"(POST /v1/completions; GET /healthz /readyz /metrics /statusz; "
          f"POST /admin/drain /admin/rejoin /admin/add_replica "
          f"/admin/remove_replica)")
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        pass
    router.shutdown()
    for srv in servers:
        srv.shutdown()
    return 0


def run_analyze(argv: list[str]) -> int:
    """Valid-test-case statistics (reference analyze_testcases.py)."""
    from .analyze import analyze_valid_test_cases

    parser = argparse.ArgumentParser(prog="reval_tpu analyze")
    parser.add_argument("path", help="a *.valid_test_cases.*.json artifact")
    args = parser.parse_args(argv)
    print(json.dumps(analyze_valid_test_cases(args.path), indent=4))
    return 0


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "fleet":
        return run_fleet(argv[1:])
    if argv and argv[0] == "serve":
        return run_serve(argv[1:])
    if argv and argv[0] == "router":
        return run_router(argv[1:])
    if argv and argv[0] == "watch":
        from .watch import run_watch

        return run_watch(argv[1:])
    if argv and argv[0] == "lint":
        # the codebase-native static analysis suite (analysis/):
        # lock discipline, hot-path purity, jit-entry registry,
        # host-sync discipline, Pallas tile contracts, typed-error
        # boundary, env registry, metric/event namespaces
        from .analysis.driver import main as lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "analyze":
        return run_analyze(argv[1:])
    if argv and argv[0] == "taskgen":
        # taskgen has its own flag namespace (keeps -o/--output semantics of
        # config/run intact)
        return run_taskgen(argv[1:])
    if argv and argv[0] == "tot-oracle":
        return run_tot_oracle(argv[1:])
    if argv and argv[0] == "tot-generate":
        return run_tot_generate(argv[1:])

    parser = argparse.ArgumentParser(prog="reval_tpu",
                                     description="Run DREval tasks with TPU-native inference")
    parser.add_argument("command", nargs="?", default="run", choices=["config", "run"])
    parser.add_argument("-i", "--input", default=DEFAULT_CONFIG, help="config file to load")
    parser.add_argument("-o", "--output", default=DEFAULT_CONFIG, help="config file to save")
    parser.add_argument("--mock", action="store_true", help="run without any model")
    parser.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                        help="override a config key (repeatable; JSON values accepted)")
    args = parser.parse_args(argv)

    if args.command == "config":
        write_config(args.output)
        return 0

    overrides = {}
    for item in args.set:
        key, _, value = item.partition("=")
        try:
            overrides[key] = json.loads(value)
        except json.JSONDecodeError:
            overrides[key] = value
    run_with_config(args.input, mock=args.mock, overrides=overrides)
    return 0


if __name__ == "__main__":
    sys.exit(main())
