"""StallWatchdog: fast-exit a wedged accelerator measurement.

Historically lived in ``bench.py`` (learned from the kv8s64 pass,
PERF.md round-5 session 2: the tunnel died 8 minutes into warmup and the
step burned its full 40-minute timeout against a dead chip); now in the
resilience layer so the kernel-CI harness (``reval_tpu/kernelbench.py``)
can arm one PER CELL and ``bench.py`` keeps its per-round instance —
one implementation, two cadences.  ``bench.StallWatchdog`` remains an
alias for existing callers.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

__all__ = ["StallWatchdog"]


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


class StallWatchdog:
    """Trips only when BOTH hold: zero progress for ``stall_s`` AND
    ``probe_fails`` consecutive failed device probes (killable
    subprocesses ``probe_gap_s`` apart — a healthy chip mid-compile
    answers them, and a successful probe resets the failure count).
    The caller exits (or kills its supervised cell) promptly so the
    runbook's wedge-abort fires minutes, not tens of minutes, later."""

    def __init__(self, stall_s: float = 420.0, probe_gap_s: float = 120.0,
                 probe_fails: int = 3, prober=None):
        self.stall_s, self.probe_gap_s = stall_s, probe_gap_s
        self.probe_fails = probe_fails
        self._probe = prober if prober is not None else self._probe_device
        self._progress = None
        self._changed = time.monotonic()
        self._probed = 0.0
        self._fails = 0

    @staticmethod
    def _probe_device() -> bool:
        from ..env import env_str

        root = _repo_root()
        alive = os.path.join(root, "tpu_watch", "ALIVE")
        probe_log = os.path.join(root, "tpu_watch", "probe.log")
        mode = (env_str("REVAL_TPU_EXCLUSIVE_DEVICE") or "auto").lower()

        def _fresh(path: str) -> bool:
            try:
                return time.time() - os.path.getmtime(path) < 1800.0
            except OSError:
                return False

        # A watcher verdict only counts while the watcher is demonstrably
        # RUNNING — freshness, not mere existence, of its marker files.
        # probe.log accumulates forever and ALIVE is removed on a wedge,
        # so a leftover stale probe.log from a long-dead watcher must not
        # flip a process-exclusive setup into "watcher says wedged" and
        # resurrect the false _exit(3) this logic exists to prevent.
        alive_fresh = _fresh(alive)
        watcher = alive_fresh or _fresh(probe_log)
        if mode in ("1", "true", "on") or (mode not in ("0", "false", "off")
                                           and not watcher):
            # Process-exclusive device ownership (plain TPU VM libtpu
            # lock, unlike the tunneled setup): a second jax-initializing
            # process fails against a HEALTHY chip, so a subprocess probe
            # would read any long zero-stat-progress window (a first
            # compile, say) as a dead device and falsely _exit(3)
            # (ADVICE r5).  No out-of-process health signal exists here;
            # report healthy and leave wedge-abort to the runbook timeout.
            return True
        if watcher:
            # Tunneled setup with tools/tpu_watch.sh running: its loop
            # touches tpu_watch/ALIVE on every good probe and removes it
            # when the tunnel wedges — that heartbeat IS the tunnel
            # health endpoint, no second jax process needed.  A fresh
            # probe.log with ALIVE gone/stale is the live watcher's
            # wedged verdict.
            return alive_fresh
        # explicit tunneled/shared mode with no live watcher: the
        # tunneled runtime tolerates a second client — subprocess probe
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; assert jax.devices()[0].platform == 'tpu'"],
                capture_output=True, timeout=45)
            return r.returncode == 0
        except subprocess.TimeoutExpired:
            return False

    def stalled_and_dead(self, progress) -> bool:
        now = time.monotonic()
        if progress != self._progress:
            self._progress, self._changed, self._fails = progress, now, 0
            return False
        if (now - self._changed < self.stall_s
                or now - self._probed < self.probe_gap_s):
            return False
        self._probed = now
        self._fails = 0 if self._probe() else self._fails + 1
        return self._fails >= self.probe_fails
