"""ChaosBackend: deterministic, seeded fault injection for any backend.

The test harness that proves the resilience layer actually works — and a
reusable hardening tool: point a fleet at ``--chaos 0.3`` and watch it
finish anyway.  Faults are *scheduled per prompt* from a seeded stream
keyed on ``crc32(prompt) ^ seed`` (never Python's salted ``hash``), so the
schedule is reproducible across processes and independent of call order —
however a bisecting caller slices the batch, each prompt injects exactly
the same faults in the same sequence.

Faults are raised as the real exception types the transport produces
(``TimeoutError``, ``urllib.error.HTTPError`` 500, ``json.JSONDecodeError``
for a truncated body), so retry classification treats injected and genuine
failures identically.  Latency spikes don't raise — they just stall.

Each prompt's fault budget is finite (``max_faults_per_prompt``), i.e.
chaos is *transient*: a caller with enough retries loses zero prompts.
Keep ``max_faults_per_prompt`` below the retry policy's ``max_attempts``
or single-prompt leaves can exhaust their budget and take the sentinel.
Budgets are per *serve epoch*: once a prompt is successfully served, its
next appearance (the fleet's next repeat) re-arms a fresh deterministic
schedule — a 5-repeat chaos fleet is exercised on all 5 repeats, not
just the first.
"""

from __future__ import annotations

import json
import random
import threading as _threading
import time
import urllib.error
import zlib

__all__ = ["CHAOS_MODES", "ENGINE_STEP_MODES", "KERNEL_CELL_MODES",
           "TIER_MODES", "ChaosBackend", "EngineStepChaos",
           "KernelCellChaos", "TierChaos"]

CHAOS_MODES = ("timeout", "http_500", "bad_json", "latency")

ENGINE_STEP_MODES = ("stall", "error")

KERNEL_CELL_MODES = ("wedge", "timeout", "flaky-device")

TIER_MODES = ("corrupt", "stall", "fail")


class TierChaos:
    """Seeded fault injection for the hierarchical KV tier store
    (``inference/tpu/kv_tiers.py``) — the ``EngineStepChaos`` sibling
    for page promotions.  Faults fire when the driver fetches a spilled
    page back out of the host-DRAM or disk tier, exercising every rung
    of the typed degrade ladder:

    - ``corrupt``: the fetched payload comes back with one byte flipped;
      the sha256 stamped at spill then fails verification
      (``TierIntegrityError`` → drop the entry, recompute from tokens);
    - ``stall``: the fetch hangs for ``stall_s`` (a slow/contended host
      path); with ``stall_s`` past the store's promotion deadline this
      is the deterministic way to trip the timeout rung;
    - ``fail``: the fetch raises ``OSError`` (a dead disk / exhausted
      host mapping) — the tier I/O rung.

    The schedule is keyed on the page's CHAIN KEY alone (crc32 ^ seed,
    never Python's salted ``hash``), so a run injects the same faults on
    the same pages regardless of eviction order or timing.
    ``max_faults`` bounds the total, i.e. chaos is transient: with the
    recompute fallback underneath, a drill loses zero prompts.
    """

    def __init__(self, rate: float = 0.2, seed: int = 0,
                 modes: tuple[str, ...] = TIER_MODES,
                 stall_s: float = 0.05, max_faults: int | None = None,
                 sleep=time.sleep):
        assert 0.0 <= rate <= 1.0, f"chaos rate must be in [0, 1], got {rate}"
        unknown = set(modes) - set(TIER_MODES)
        assert not unknown, f"unknown tier chaos modes: {sorted(unknown)}"
        self.rate = float(rate)
        self.seed = int(seed)
        self.modes = tuple(modes)
        self.stall_s = float(stall_s)
        self.max_faults = max_faults
        self.sleep = sleep
        # guarded-by: _lock (writes) — callers read the ledger after the
        # run; the driver and a rewarming boot thread may both promote
        self.injected: list[tuple[str, str]] = []   # (mode, key prefix)
        self._lock = _threading.Lock()

    def draw(self, key: str) -> str | None:
        """The fault (or None) for one promotion fetch of ``key``.
        Deterministic per (key, seed); consumes fault budget when armed.
        The stall itself happens in the tier store (OUTSIDE the lock) so
        one stalled promotion never blocks a sibling's schedule."""
        with self._lock:
            if (self.max_faults is not None
                    and len(self.injected) >= self.max_faults):
                return None
            rng = random.Random(
                (zlib.crc32(key.encode("utf-8", "replace")) << 32)
                ^ self.seed)
            if rng.random() >= self.rate:
                return None
            mode = self.modes[rng.randrange(len(self.modes))]
            self.injected.append((mode, key[:12]))
        return mode


class KernelCellChaos:
    """Targeted fault injection for the kernel-CI harness
    (``reval_tpu/kernelbench.py``) — the ``EngineStepChaos`` sibling for
    supervised benchmark cells.  Faults are keyed on the CELL NAME (not
    a seeded rate): a degradation drill wedges exactly the cell it
    names, so tier-1 can assert "this cell went stale, those survived"
    deterministically on CPU.

    Modes (``--chaos-cell MODE:CELL``):

    - ``wedge``: the cell child hangs before any device work and ignores
      SIGTERM (a dead tunnel mid-dispatch); the parent's per-cell
      StallWatchdog sees a frozen heartbeat AND failed device probes
      (:meth:`device_probe_override` simulates the dead tunnel) and
      kills it early — the watchdog kill path.
    - ``timeout``: the cell keeps heart-beating but never finishes (a
      live device running pathologically slow); only the hard per-cell
      deadline cuts it — the budget kill path.
    - ``flaky-device``: the first ``flaky_failures`` attempts die with a
      transient device-loss error, later attempts run clean — the
      RetryPolicy recovery path (cell ends ``run`` WITH retries
      recorded).
    """

    def __init__(self, rules: dict[str, str] | None = None,
                 flaky_failures: int = 1, sleep=time.sleep):
        rules = dict(rules or {})
        unknown = set(rules.values()) - set(KERNEL_CELL_MODES)
        assert not unknown, f"unknown kernel-cell chaos modes: {sorted(unknown)}"
        self.rules = rules
        self.flaky_failures = int(flaky_failures)
        self.sleep = sleep

    @classmethod
    def parse(cls, specs: list[str]) -> "KernelCellChaos":
        """From repeated ``MODE:CELL`` CLI values; raises ``ValueError``
        on a malformed spec (a typo'd mode must not silently run the
        cell clean under a chaos label)."""
        rules: dict[str, str] = {}
        for spec in specs:
            mode, sep, cell = spec.partition(":")
            if not sep or not cell or mode not in KERNEL_CELL_MODES:
                raise ValueError(
                    f"bad --chaos-cell {spec!r}: expected MODE:CELL with "
                    f"MODE in {KERNEL_CELL_MODES}")
            rules[cell] = mode
        return cls(rules)

    def to_argv(self) -> list[str]:
        """The CLI args that reproduce this schedule in a cell child."""
        out: list[str] = []
        for cell, mode in sorted(self.rules.items()):
            out += ["--chaos-cell", f"{mode}:{cell}"]
        return out

    def mode_for(self, cell_name: str) -> str | None:
        return self.rules.get(cell_name)

    def device_probe_override(self, cell_name: str):
        """A prober for the parent's per-cell StallWatchdog: a wedged
        tunnel fails its device probes, so the wedge drill exercises the
        real stall-AND-dead kill path; other modes keep the genuine
        probe (None)."""
        if self.rules.get(cell_name) == "wedge":
            return lambda: False
        return None

    def apply_in_child(self, cell_name: str, attempt: int,
                       heartbeat=None) -> None:
        """Run inside the cell child BEFORE any device work.  Returns
        normally when the cell is not targeted (or a flaky cell's retry
        attempt); hangs forever for wedge/timeout (the parent kills);
        raises ``ConnectionError`` for a flaky attempt."""
        mode = self.rules.get(cell_name)
        if mode is None:
            return
        if mode == "flaky-device":
            if attempt < self.flaky_failures:
                raise ConnectionError(
                    f"chaos: injected transient device loss "
                    f"(attempt {attempt})")
            return
        if mode == "wedge":
            try:
                import signal

                signal.signal(signal.SIGTERM, signal.SIG_IGN)
            except (ValueError, OSError):
                pass
            while True:             # frozen heartbeat: the watchdog's food
                self.sleep(3600.0)
        # timeout: keep making visible progress, just never finish
        rep = 0
        while True:
            if heartbeat is not None:
                heartbeat("chaos-timeout", rep)
            rep += 1
            self.sleep(0.2)


class EngineStepChaos:
    """Deterministic *engine-step* fault injection for the serving driver.

    ``ChaosBackend`` exercises the transport; these faults fire INSIDE the
    serve loop, between decode steps — the failure modes the lifecycle
    layer exists for:

    - ``stall``: the step hangs for ``stall_s`` (a wedged device dispatch);
      with ``stall_s`` past the session's watchdog threshold this is the
      deterministic way to make the watchdog trip in a test.
    - ``error``: the step raises mid-batch (a device fault); the driver
      must fail the in-flight submissions and keep serving — clients see a
      retryable 500, never a dead loop.

    The schedule is keyed on the step ordinal alone (seeded, no wall
    clock), so a run injects the same faults at the same steps regardless
    of timing or request interleaving.  ``max_faults`` bounds the total so
    a retrying caller always converges.
    """

    def __init__(self, rate: float = 0.2, seed: int = 0,
                 modes: tuple[str, ...] = ENGINE_STEP_MODES,
                 stall_s: float = 0.05, max_faults: int | None = None,
                 sleep=time.sleep):
        assert 0.0 <= rate <= 1.0, f"chaos rate must be in [0, 1], got {rate}"
        unknown = set(modes) - set(ENGINE_STEP_MODES)
        assert not unknown, f"unknown engine-step chaos modes: {sorted(unknown)}"
        self.rate = float(rate)
        self.seed = int(seed)
        self.modes = tuple(modes)
        self.stall_s = float(stall_s)
        self.max_faults = max_faults
        self.sleep = sleep
        self.steps = 0                  # guarded-by: _lock
        # guarded-by: _lock (writes) — callers read the ledger after joining
        self.injected: list[tuple[str, int]] = []   # (mode, step ordinal)
        # a MultiSession shares one injector across replica drivers: the
        # ordinal/ledger must not tear (the stall/raise happens OUTSIDE
        # the lock so one replica's fault never blocks the others' steps)
        self._lock = _threading.Lock()

    def tick(self) -> None:
        """Call once per engine step, BEFORE the step runs."""
        with self._lock:
            self.steps += 1
            step = self.steps
            if (self.max_faults is not None
                    and len(self.injected) >= self.max_faults):
                return
            rng = random.Random((self.seed << 32) ^ (step * 0x9E3779B1))
            if rng.random() >= self.rate:
                return
            mode = self.modes[rng.randrange(len(self.modes))]
            self.injected.append((mode, step))
        if mode == "stall":
            self.sleep(self.stall_s)
            return
        raise RuntimeError(
            f"chaos: injected engine-step fault at step {step}")


class ChaosBackend:
    """Wrap a backend; inject faults at ``rate`` per prompt, seeded."""

    def __init__(self, inner, rate: float = 0.3, seed: int = 0,
                 modes: tuple[str, ...] = CHAOS_MODES,
                 max_faults_per_prompt: int = 3, spike_s: float = 0.01,
                 sleep=time.sleep):
        assert 0.0 <= rate < 1.0, f"chaos rate must be in [0, 1), got {rate}"
        unknown = set(modes) - set(CHAOS_MODES)
        assert not unknown, f"unknown chaos modes: {sorted(unknown)}"
        self.inner = inner
        self.rate = float(rate)
        self.seed = int(seed)
        self.modes = tuple(modes)
        self.max_faults_per_prompt = int(max_faults_per_prompt)
        self.spike_s = float(spike_s)
        self.sleep = sleep
        # bookkeeping keys are crc32(prompt), not the (multi-KB) prompt
        # strings, so a thousands-of-prompts × N-repeats fleet doesn't
        # retain every prompt verbatim for the whole run
        self._pending: dict[tuple[int, int], list[str]] = {}  # (epoch, crc) → faults left
        self._epoch: dict[int, int] = {}           # crc → successful serves
        self.injected: list[tuple[str, str]] = []  # (mode, prompt[:40]) log

    # -- deterministic per-prompt schedule --------------------------------
    def _schedule(self, prompt: str, epoch: int = 0) -> list[str]:
        """Faults this prompt will inject on its ``epoch``-th serve,
        freshly seeded per (prompt, epoch) so the schedule survives
        process restarts and any batch slicing."""
        key = zlib.crc32(prompt.encode("utf-8", "replace"))
        rng = random.Random(((key << 32) ^ self.seed) + epoch * 0x9E3779B1)
        faults = []
        while (len(faults) < self.max_faults_per_prompt
               and rng.random() < self.rate):
            faults.append(rng.choice(self.modes))
        return faults

    def _raise(self, mode: str, prompt: str, batch: int):
        self.injected.append((mode, prompt[:40]))
        if mode == "latency":
            self.sleep(self.spike_s)
            return
        if mode == "timeout":
            raise TimeoutError(
                f"chaos: injected timeout ({batch} prompts in flight)")
        if mode == "http_500":
            raise urllib.error.HTTPError(
                "chaos://injected", 500, "chaos: injected internal error",
                None, None)
        # bad_json: what json.load raises on a connection cut mid-body
        raise json.JSONDecodeError("chaos: truncated response body",
                                   '{"choices": [', 13)

    # -- the infer API ----------------------------------------------------
    def infer_many(self, prompts) -> list[str]:
        prompts = list(prompts)
        for prompt in prompts:
            crc = zlib.crc32(prompt.encode("utf-8", "replace"))
            epoch = self._epoch.get(crc, 0)
            pending = self._pending.setdefault(
                (epoch, crc), self._schedule(prompt, epoch))
            while pending:
                # consume before raising: each fault fires exactly once
                mode = pending.pop(0)
                self._raise(mode, prompt, len(prompts))
        out = self.inner.infer_many(prompts)
        for prompt in prompts:
            # a successful serve re-arms the prompt's next appearance;
            # drop the drained schedule (kept until now: re-creating it
            # mid-epoch would replay the full fault list forever)
            crc = zlib.crc32(prompt.encode("utf-8", "replace"))
            epoch = self._epoch.get(crc, 0)
            self._pending.pop((epoch, crc), None)
            self._epoch[crc] = epoch + 1
        return out

    def infer_one(self, prompt: str) -> str:
        return self.infer_many([prompt])[0]

    def infer(self, prompt: str) -> str:
        return self.infer_many([prompt])[0]

    # -- identity / lifecycle delegate to the wrapped backend -------------
    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()

    def __getattr__(self, name):
        return getattr(self.inner, name)
