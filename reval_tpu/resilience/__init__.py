"""Resilience layer: retries, batch bisection, chaos injection, checkpoints.

A fleet run is hours of accelerator time spread over thousands of prompts
and an HTTP hop (``inference.client`` ↔ ``serving.server``); without this
layer one connection reset, one poisoned prompt, or one mid-run kill aborts
everything with nothing written.  The pieces compose:

- :class:`RetryPolicy` — bounded exponential backoff + jitter around any
  callable, with transport-level error classification (``retryable_error``)
  and an injectable clock/sleep/rng so tests never really wait;
- :func:`wait_for_server` — the client-side handshake loop that polls a
  server's ``/healthz`` until it comes up instead of crashing when the
  client is constructed first;
- :class:`ResilientBackend` — wraps any ``InferenceBackend``; a failing
  ``infer_many`` mega-batch is retried, then recursively bisected so a
  poisoned prompt loses only its own slot (scored as :data:`INFER_FAILED`),
  never the fleet's fused batch;
- :class:`FleetCheckpoint` — an append-only JSONL journal of completed
  (repeat, task) chunks in ``results_dir``; ``fleet --resume`` skips them;
- :class:`ChaosBackend` — deterministic, seeded fault injection (timeouts,
  HTTP 500s, truncated JSON, latency spikes) that proves the above works
  and doubles as a hardening tool for the serving stack;
- :class:`EngineStepChaos` — the server-side counterpart: deterministic
  *engine-step* faults (stalled step, mid-batch exception) injected into
  the serving session's drive loop, so the watchdog/drain/shed paths are
  testable in the fast tier without a TPU;
- :class:`KernelCellChaos` — targeted per-cell faults (wedge / timeout /
  flaky-device) for the kernel-CI harness's supervised benchmark cells,
  so every degradation path of the perf instrument is drillable on CPU;
- :class:`TierChaos` — seeded faults (corrupt / stall / fail) on KV tier
  promotions (``inference/tpu/kv_tiers.py``), proving every rung of the
  tier degrade ladder recomputes instead of serving wrong KV;
- :class:`StallWatchdog` — the no-progress + failed-device-probe trip
  wire ``bench.py`` arms per round and ``reval_tpu/kernelbench.py`` arms
  per cell.
"""

from .chaos import (CHAOS_MODES, ENGINE_STEP_MODES, KERNEL_CELL_MODES,
                    TIER_MODES, ChaosBackend, EngineStepChaos,
                    KernelCellChaos, TierChaos)
from .checkpoint import FleetCheckpoint
from .resilient import INFER_FAILED, ResilientBackend
from .retry import (RetryPolicy, retry_after_from_headers, retry_after_hint,
                    retryable_error, wait_for_server)
from .watchdog import StallWatchdog

__all__ = [
    "CHAOS_MODES",
    "ENGINE_STEP_MODES",
    "KERNEL_CELL_MODES",
    "TIER_MODES",
    "ChaosBackend",
    "EngineStepChaos",
    "KernelCellChaos",
    "TierChaos",
    "StallWatchdog",
    "FleetCheckpoint",
    "INFER_FAILED",
    "ResilientBackend",
    "RetryPolicy",
    "retry_after_from_headers",
    "retry_after_hint",
    "retryable_error",
    "wait_for_server",
]
