"""Crash-resumable fleet runs: an append-only JSONL completion journal.

One row per completed (repeat, task) chunk, written *after* that task's
log hits disk and fsync'd, so the journal never claims work whose log
could be lost.  ``fleet --resume`` reloads the journal and skips completed
chunks; because mock/greedy generation is deterministic and the per-task
JSONL contract is unchanged, a killed-then-resumed run produces logs
byte-identical to an uninterrupted one.

Rows carry the run identity (model_info, dataset, prompt_type) and are
filtered on load, so a journal left behind by a different model or prompt
style can never satisfy this run's chunks.
"""

from __future__ import annotations

import json
import os

__all__ = ["FleetCheckpoint"]


class FleetCheckpoint:
    FILENAME = "fleet_checkpoint.jsonl"

    def __init__(self, results_dir: str, identity: dict):
        self.path = os.path.join(results_dir, self.FILENAME)
        self.identity = dict(identity)
        self._done: dict[tuple[int, str], dict] = {}

    def load(self) -> int:
        """Read the journal; keep rows matching this run's identity.
        Returns the number of completed chunks found.  A torn final line
        (crash mid-append) is ignored, not fatal."""
        self._done = {}
        if not os.path.exists(self.path):
            return 0
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if any(row.get(k) != v for k, v in self.identity.items()):
                    continue
                self._done[(int(row["repeat"]), row["task"])] = row
        return len(self._done)

    def reset(self) -> None:
        """Start fresh: a non-resume run must not inherit stale chunks."""
        self._done = {}
        if os.path.exists(self.path):
            os.remove(self.path)

    def done(self, repeat: int, task: str) -> dict | None:
        return self._done.get((repeat, task))

    def record(self, repeat: int, task: str, metrics: dict) -> None:
        row = {**self.identity, "repeat": int(repeat), "task": task,
               "metrics": metrics}
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(row) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._done[(int(repeat), task)] = row
