"""Retry policy + wait-for-server handshake.

Classification first: only *transport-shaped* failures are retried —
connection refused/reset while a server boots or restarts, request
timeouts, throttling/5xx responses, and truncated or malformed JSON bodies
(a connection dropped mid-response).  Application errors (HTTP 400/404,
``ValueError`` from bad arguments, …) are bugs and propagate immediately;
retrying them would only hide the stack trace for ``max_attempts`` longer.

Everything time-shaped (clock, sleep, rng) is injectable so the backoff
schedule is unit-testable without real sleeps.
"""

from __future__ import annotations

import http.client
import json
import random
import time
import urllib.error
from typing import Callable

from ..obs.logging import log_event

__all__ = ["RetryPolicy", "retryable_error", "retry_after_hint",
           "retry_after_from_headers", "wait_for_server"]

# Status codes worth retrying: request timeout, throttling, and the 5xx
# family a restarting or overloaded server emits.
RETRYABLE_HTTP_CODES = frozenset({408, 425, 429, 500, 502, 503, 504})


def retry_after_from_headers(headers) -> float | None:
    """``Retry-After`` out of any headers-shaped object (something with
    ``.get``), in seconds; None when absent/unparseable.  THE one parse
    of this wire contract — :func:`retry_after_hint` and the fleet
    router's failover accounting both call it.  HTTP-date forms are
    ignored (the in-tree servers only send seconds)."""
    get = getattr(headers, "get", None)
    if get is None:
        return None
    try:
        return float(get("Retry-After"))
    except (TypeError, ValueError):
        return None


def retry_after_hint(exc: BaseException) -> float | None:
    """The server's ``Retry-After`` header on an HTTP error, in seconds
    (None when absent/unparseable).  The serving layer sends it with 429
    load sheds and 503 drain responses; honoring it beats blind
    exponential backoff — the server KNOWS how deep its queue is.
    """
    return retry_after_from_headers(getattr(exc, "headers", None))


def retryable_error(exc: BaseException) -> bool:
    """Is this failure transient at the transport level?"""
    if isinstance(exc, urllib.error.HTTPError):
        # Check before URLError: HTTPError subclasses it, and a 400/404 is
        # an application error that must propagate.
        return exc.code in RETRYABLE_HTTP_CODES
    return isinstance(exc, (
        urllib.error.URLError,          # refused / reset / DNS while booting
        TimeoutError,                   # socket.timeout is an alias ≥3.10
        ConnectionError,                # reset/aborted outside urllib
        http.client.HTTPException,      # IncompleteRead, BadStatusLine, …
        json.JSONDecodeError,           # truncated/malformed response body
    ))


class RetryPolicy:
    """Bounded exponential backoff with jitter around any callable.

    ``delay(attempt) = min(base * multiplier**attempt, max_delay)`` plus a
    uniform jitter of up to ``jitter * delay`` so a fleet of clients
    hammering one recovering server doesn't retry in lockstep.
    """

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.25,
                 max_delay: float = 30.0, multiplier: float = 2.0,
                 jitter: float = 0.25,
                 retryable: Callable[[BaseException], bool] = retryable_error,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: random.Random | None = None):
        assert max_attempts >= 1, "a policy needs at least one attempt"
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.retryable = retryable
        self.sleep = sleep
        self.rng = rng if rng is not None else random.Random()

    def delay_for(self, attempt: int,
                  exc: BaseException | None = None) -> float:
        """Backoff before retrying after the given 0-indexed attempt.

        When the failure carries a server ``Retry-After`` hint (a 429
        load shed or 503 drain from the serving layer), the hint wins —
        clamped to ``max_delay``, still jittered so a shedding server's
        whole fleet doesn't return in lockstep."""
        hint = retry_after_hint(exc) if exc is not None else None
        if hint is not None:
            delay = min(max(hint, 0.0), self.max_delay)
        else:
            delay = min(self.base_delay * self.multiplier ** attempt,
                        self.max_delay)
        if self.jitter:
            delay += delay * self.jitter * self.rng.random()
        return delay

    def call(self, fn: Callable[[], "object"], *, attempts: int | None = None,
             on_retry: Callable[[int, BaseException, float], None] | None = None,
             label: str | None = None):
        """Run ``fn`` under the policy; re-raise the last error when the
        attempt budget is spent or the error is not retryable.  ``attempts``
        overrides ``max_attempts`` (batch bisection retries multi-prompt
        batches less eagerly than single prompts).  ``label`` names the
        work in the retry log — the HTTP client passes its request id, so
        a client-side retry and the server-side 500 for the same request
        grep to one line."""
        budget = attempts if attempts is not None else self.max_attempts
        for attempt in range(budget):
            try:
                return fn()
            except Exception as exc:
                if not self.retryable(exc) or attempt + 1 >= budget:
                    raise
                delay = self.delay_for(attempt, exc)
                if label is not None:
                    log_event("client.retry", level="warning", label=label,
                              attempt=attempt + 1, budget=budget,
                              delay_s=round(delay, 3), exc=exc)
                if on_retry is not None:
                    on_retry(attempt, exc, delay)
                self.sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover


def wait_for_server(probe: Callable[[], "object"], *, timeout: float = 60.0,
                    interval: float = 0.5, describe: str = "server",
                    retry_statuses: frozenset = frozenset(),
                    clock: Callable[[], float] = time.monotonic,
                    sleep: Callable[[float], None] = time.sleep):
    """Poll ``probe()`` until the server answers or ``timeout`` elapses.

    Any HTTP *response* — including an error status like 404 from a server
    predating the probed route — means the server is up, so the handshake
    returns.  Transport errors (connection refused while the engine is
    still compiling, timeouts) keep polling; anything else is a real bug
    and propagates.

    ``retry_statuses``: HTTP codes that mean "up but KEEP waiting" — the
    readiness handshake passes ``{429, 503}`` so a probe against
    ``/readyz`` waits through engine load, drain, and overload instead of
    treating the 503 as arrival.
    """
    deadline = clock() + timeout
    announced = False
    while True:
        try:
            return probe()
        except Exception as exc:
            if isinstance(exc, urllib.error.HTTPError):
                if exc.code not in retry_statuses:
                    return None     # it answered: up, just no such route
            elif not retryable_error(exc):
                raise
            if clock() >= deadline:
                raise TimeoutError(
                    f"{describe} not reachable after {timeout:.0f}s "
                    f"(last error: {exc!r})") from exc
            if not announced:
                # the wait can legitimately run minutes (engine loading);
                # say so once instead of hanging silently
                log_event("client.wait", target=describe,
                          timeout_s=round(timeout, 1), exc=exc)
                announced = True
        sleep(max(0.0, min(interval, deadline - clock())))
