"""Batch bisection: one poisoned prompt must not sink the fused batch.

The fleet concatenates every task's prompts into one ``infer_many``
mega-batch (``fleet.py``) — great for chip utilisation, terrible for blast
radius: before this wrapper, a single transient OOM or poisoned prompt
aborted thousands of finished slots.  :class:`ResilientBackend` retries the
whole batch under a :class:`~reval_tpu.resilience.retry.RetryPolicy`, and
when retries don't clear it, recursively splits the batch and retries the
halves.  Failures narrow to single prompts, which get the full retry
budget and finally degrade to the :data:`INFER_FAILED` sentinel — scored
as a wrong answer, exactly one slot lost.
"""

from __future__ import annotations

from .retry import RetryPolicy

__all__ = ["INFER_FAILED", "ResilientBackend"]

# What a permanently-failing prompt "generates".  No answer parser matches
# it, so the slot scores as wrong — the log keeps its shape and the error
# is visible verbatim in the generated field.
INFER_FAILED = "[REVAL:INFER_FAILED]"


class ResilientBackend:
    """Wrap any ``InferenceBackend``: retry + bisect ``infer_many``.

    Duck-typed proxy — identity (``info``, ``prompt_type``, ``temp``, …)
    delegates to the wrapped backend so tasks and the consistency scorer
    see the same model.  ``failures`` records every prompt that exhausted
    its retry budget (the fleet surfaces the count in its summary).
    """

    def __init__(self, inner, policy: RetryPolicy | None = None,
                 sentinel: str = INFER_FAILED, batch_attempts: int = 2,
                 max_loss_fraction: float = 0.5, progress: bool = True):
        self.inner = inner
        if policy is None:
            # Only the DIRECT inner's own policy counts (instance dict, no
            # __getattr__ delegation): a ChaosBackend sitting between this
            # wrapper and an HTTP client injects faults *above* the
            # client's retry loop, so a delegated policy must not collapse
            # this layer's budget — the chaos faults would never retry.
            inner_retry = getattr(inner, "__dict__", {}).get("retry")
            if isinstance(inner_retry, RetryPolicy):
                # the wrapped backend already retries every request at the
                # transport level (HTTPClientBackend); retrying again here
                # would multiply the schedules (4×4 requests per leaf) —
                # this layer then only contributes the bisection
                policy = RetryPolicy(max_attempts=1,
                                     retryable=inner_retry.retryable)
            else:
                policy = RetryPolicy()
        self.policy = policy
        self.sentinel = sentinel
        # Multi-prompt batches get a short retry budget before bisection:
        # a batch-wide transient (server restart) usually clears in one
        # retry, while per-prompt poison never does — splitting early keeps
        # the wasted re-inference logarithmic instead of linear.
        self.batch_attempts = max(1, min(int(batch_attempts),
                                         policy.max_attempts))
        # Sentinel-degrading is for *per-prompt* poison; a batch losing
        # more than this fraction is a systemic failure (server down, bad
        # protocol) and must abort with the real error, not "complete"
        # with a log full of sentinels.
        self.max_loss_fraction = float(max_loss_fraction)
        self.progress = progress
        self.failures: list[dict] = []

    # -- the infer API ----------------------------------------------------
    def infer_many(self, prompts) -> list[str]:
        prompts = list(prompts)
        if not prompts:
            return []
        before = len(self.failures)
        out = self._attempt(prompts, depth=0)
        lost = len(self.failures) - before
        if len(prompts) > 1 and lost > len(prompts) * self.max_loss_fraction:
            raise RuntimeError(
                f"resilience: {lost}/{len(prompts)} prompts failed — "
                f"systemic backend failure, not per-prompt poison "
                f"(last error: {self.failures[-1]['error']})")
        return out

    def infer_one(self, prompt: str) -> str:
        return self.infer_many([prompt])[0]

    def infer(self, prompt: str) -> str:
        return self.infer_many([prompt])[0]

    def _attempt(self, prompts: list[str], depth: int) -> list[str]:
        attempts = (self.policy.max_attempts if len(prompts) == 1
                    else self.batch_attempts)
        try:
            out = self.policy.call(
                lambda: self.inner.infer_many(prompts), attempts=attempts)
        except Exception as exc:
            if len(prompts) == 1:
                self.failures.append({"prompt": prompts[0], "error": repr(exc)})
                if self.progress:
                    print(f"[resilience] prompt lost after "
                          f"{attempts} attempts: {exc!r}")
                return [self.sentinel]
            if self.progress and depth == 0:
                print(f"[resilience] batch of {len(prompts)} failed "
                      f"({exc!r}) → bisecting")
            mid = len(prompts) // 2
            return (self._attempt(prompts[:mid], depth + 1)
                    + self._attempt(prompts[mid:], depth + 1))
        out = list(out)
        if len(out) != len(prompts):
            # A short list is a contract bug, not a transient: bisecting
            # would "repair" it silently and mis-align task chunks.
            raise RuntimeError(
                f"backend contract violation: {type(self.inner).__name__}"
                f".infer_many returned {len(out)} responses for "
                f"{len(prompts)} prompts")
        return out

    # -- identity / lifecycle delegate to the wrapped backend -------------
    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()

    def __getattr__(self, name):
        return getattr(self.inner, name)
