"""Fleet runner: all four tasks × N repeats on one resident model.

The reference drives a fleet by spawning the four task evaluations as
concurrent OS processes, each re-connecting to a separately-launched vLLM
server, five times over (reference batch_run.py:20-32 + start_server.sh).
On TPU the right shape is the opposite: **one** resident sharded model and
one process.  Per repeat, the fleet

1. plans all four tasks up front (ground-truth sandboxes, prompt
   rendering);
2. when the tasks share one backend, concatenates every prompt into a
   single ``infer_many`` call — the engine length-buckets and batches
   across task boundaries, keeping the chips saturated where four
   processes would each trickle single prompts.  The fused batch stays
   **task-contiguous** (all of one task's prompts, then the next's): the
   four tasks use four different few-shot templates, so a global batch
   LCP is ≈ 0 — per-task grouping is what feeds the engine's radix
   prefix cache one template run at a time, and repeats 2..N then hit
   the cached template pages outright;
3. scores and writes each task's log (the per-task JSONL contract is
   unchanged), then runs the consistency scorer over the latest logs.

Mock/replay fleets (per-task backends) fall back to per-task inference.

Resilience: the backend is wrapped in a
:class:`~reval_tpu.resilience.ResilientBackend` (retry + batch bisection —
one poisoned prompt costs one sentinel slot, not the fused batch), and
every completed (repeat, task) chunk is journaled to a
:class:`~reval_tpu.resilience.FleetCheckpoint` in ``results_dir`` so a
killed run restarted with ``resume=True`` skips already-scored chunks and
reproduces identical logs.
"""

from __future__ import annotations

from .obs.logging import log_event
from .resilience import INFER_FAILED, FleetCheckpoint, ResilientBackend, RetryPolicy
from .tasks import TASKS, ConsistencyScorer

__all__ = ["FleetRunner", "FLEET_TASKS"]

FLEET_TASKS = ("coverage", "path", "state", "output")


class FleetRunner:
    def __init__(self, *, dataset: str, prompt_type: str = "direct",
                 repeats: int = 5, backend=None, mock: bool = False,
                 results_dir: str = "model_generations",
                 run_consistency: bool = True, progress: bool = True,
                 tasks: tuple[str, ...] = FLEET_TASKS,
                 multihost: str | None = None, resume: bool = False,
                 resilience: bool = True,
                 retry_policy: RetryPolicy | None = None,
                 grammar: bool = False, **task_kwargs):
        assert backend is not None or mock, "fleet needs a backend (or mock=True)"
        assert multihost in (None, "replicate", "global"), multihost
        # "global" shards one model across hosts: every infer_many is a
        # collective all hosts must enter identically, so per-host
        # retry/bisection would desynchronise the pod — don't wrap.
        # ("replicate" is per-host-local inference; wrapping is safe.)
        if (backend is not None and resilience and multihost != "global"
                and not isinstance(backend, ResilientBackend)):
            backend = ResilientBackend(backend, policy=retry_policy,
                                       progress=progress)
        self.dataset = dataset
        self.prompt_type = prompt_type
        self.repeats = repeats
        self.backend = backend
        self.mock = mock
        self.results_dir = results_dir
        self.run_consistency = run_consistency
        self.progress = progress
        self.task_names = tasks
        # multi-host: "replicate" = engine replica per host, prompts sharded
        # over DCN; "global" = one model sharded across all hosts, identical
        # prompts everywhere (70B-class); None = single host
        self.multihost = multihost
        self.resume = resume
        #: grammar-constrained decoding: each task decodes under its
        #: answer-shape automaton (decoding/grammar.py TASK_GRAMMARS;
        #: cot prompt types get the cot- wrapped variant).  Requires a
        #: backend that supports per-task grammars (set_task_grammar —
        #: the paged-engine TPU backend); rejected up front otherwise so
        #: a run can never silently score unconstrained generations as
        #: constrained ones.
        self.grammar = bool(grammar)
        if self.grammar and backend is not None and not callable(
                getattr(backend, "set_task_grammar", None)):
            raise ValueError(
                "grammar-constrained fleet runs need a backend with "
                "per-task grammar support (the paged-engine TPU backend)")
        self.task_kwargs = task_kwargs
        #: per-(repeat, task) reproducibility-receipt journal rows
        #: (obs/receipts.py), collected when the backend surfaces
        #: ``last_receipt`` (the HTTP client backend verifies + keeps
        #: the most recent one); rendered as the ``receipts`` trailer
        #: and persisted in fleet_metrics.json
        self._receipts: list[dict] = []

    def _model_info(self) -> str:
        return ("mock_model_" + self.prompt_type if self.mock
                else self.backend.info)

    def _make_tasks(self, names=None):
        return [
            TASKS[name](model=self.backend, prompt_type=self.prompt_type,
                        dataset=self.dataset, mock=self.mock,
                        results_dir=self.results_dir, progress=self.progress,
                        **self.task_kwargs)
            for name in (self.task_names if names is None else names)
        ]

    def run_repeat(self, rep: int = 0,
                   checkpoint: FleetCheckpoint | None = None) -> dict[str, dict]:
        """One pass over all tasks with fused batched inference.  Tasks the
        checkpoint already holds for this repeat are skipped (their metrics
        come from the journal) — the resume path after a crash."""
        metrics: dict[str, dict] = {}
        pending_names = []
        for name in self.task_names:
            row = checkpoint.done(rep, name) if checkpoint is not None else None
            if row is not None:
                metrics[name] = row["metrics"]
                log_event("fleet.resume_skip", repeat=rep + 1, task=name)
                if self.progress:
                    print(f"[fleet] resume: repeat {rep + 1} task {name} "
                          f"already scored — skipping")
            else:
                pending_names.append(name)
        if not pending_names:
            return metrics
        tasks = self._make_tasks(pending_names)
        planned = [(task, *task._plan()) for task in tasks]
        shared = self.backend is not None and all(
            t.backend is self.backend for t in tasks)
        if shared and self.grammar:
            # per-TASK batches instead of the cross-task fused batch:
            # each task decodes under its own answer-shape automaton,
            # and the grammar is backend state per infer_many call.  The
            # radix prefix cache persists ACROSS calls (PR 2), so the
            # per-template insert-then-hit sequence is unchanged — the
            # cost is only the per-task batch tail.
            shared = False
        if shared:
            # task-major order is load-bearing, not incidental: each task's
            # prompts share one few-shot template, and grouping them keeps
            # the radix prefix cache's insert-then-hit sequence per
            # template (tests/test_prefix_cache.py pins the sharing)
            all_jobs = [(task, job) for task, _, jobs in planned for job in jobs]
            if self.progress:
                print(f"[fleet] {len(all_jobs)} prompts across "
                      f"{len(tasks)} tasks → one batched pass")
            prompts = [job.prompt for _, job in all_jobs]
            responses = self._infer(prompts)
            self._check_aligned(len(responses), planned)
            if not self._should_write():
                return {**metrics, **{t.name: {} for t, _, _ in planned}}
            cursor = 0
            for task, records, jobs in planned:
                chunk = responses[cursor:cursor + len(jobs)]
                cursor += len(jobs)
                metrics[task.name] = task.score_and_write(records, jobs, chunk)
                self._note_task_receipt(rep, task.name)
                if checkpoint is not None:
                    checkpoint.record(rep, task.name, metrics[task.name])
        else:
            for task, records, jobs in planned:
                setter = (getattr(task.backend, "set_task_grammar", None)
                          if self.grammar else None)
                if setter is not None:
                    setter(self.task_grammar(task.name))
                try:
                    responses = task.backend.infer_many(
                        [j.prompt for j in jobs])
                finally:
                    if setter is not None:
                        setter(None)    # never leak a task's constraint
                self._check_aligned(len(responses), [(task, records, jobs)])
                metrics[task.name] = task.score_and_write(records, jobs, responses)
                self._note_task_receipt(rep, task.name)
                if checkpoint is not None and self._should_write():
                    checkpoint.record(rep, task.name, metrics[task.name])
        return metrics

    def task_grammar(self, task_name: str) -> str | None:
        """The answer-shape grammar one task decodes under when
        ``grammar=True`` (None = unconstrained — tasks outside the map,
        or the feature off).  Chain-of-thought prompt types wrap the
        shape so the free [THOUGHT] text stays unconstrained."""
        if not self.grammar:
            return None
        from .decoding import TASK_GRAMMARS

        shape = TASK_GRAMMARS.get(task_name)
        if shape is None:
            return None
        return f"cot-{shape}" if self.prompt_type == "cot" else shape

    @staticmethod
    def _check_aligned(n_responses: int, planned) -> None:
        """A backend returning a short/long list must fail loudly with the
        task attribution, never silently shift every later task's chunk."""
        counts = {task.name: len(jobs) for task, _, jobs in planned}
        total = sum(counts.values())
        if n_responses != total:
            raise RuntimeError(
                f"[fleet] backend returned {n_responses} responses for "
                f"{total} prompts (per-task prompt counts: {counts}) — "
                f"refusing to mis-align task chunks")

    def _infer(self, prompts: list[str]) -> list[str]:
        """Batched inference, sharded across hosts when configured."""
        if self.multihost == "replicate":
            from .parallel.distributed import gather_strings, shard_for_host

            local, _ = shard_for_host(prompts)
            return gather_strings(self.backend.infer_many(local))
        return self.backend.infer_many(prompts)

    def _should_write(self) -> bool:
        """In multi-host runs only the primary host scores + writes logs."""
        if self.multihost is None:
            return True
        from .parallel.distributed import is_primary_host

        return is_primary_host()

    def _make_checkpoint(self) -> FleetCheckpoint | None:
        """Single-host runs journal completions; multi-host runs don't
        (hosts would need a shared journal to skip chunks in lockstep —
        a divergent skip set would desynchronise the fused batches)."""
        if self.multihost is not None:
            if self.resume and self.progress:
                print("[fleet] resume is single-host only — ignoring")
            return None
        # identity includes every knob that changes a chunk's *shape* —
        # a journal from a different slice must never satisfy this run
        checkpoint = FleetCheckpoint(self.results_dir, {
            "model_info": self._model_info(), "dataset": self.dataset,
            "prompt_type": self.prompt_type,
            "split": self.task_kwargs.get("split"),
            "max_items": self.task_kwargs.get("max_items")})
        if self.resume:
            n = checkpoint.load()
            if self.progress and n:
                print(f"[fleet] resume: {n} completed chunks in {checkpoint.path}")
        else:
            checkpoint.reset()
        return checkpoint

    def run(self) -> dict:
        """All repeats + the consistency score (reference batch_run.py:20-32)."""
        checkpoint = self._make_checkpoint()
        all_metrics: list[dict[str, dict]] = []
        for rep in range(self.repeats):
            if self.progress:
                print(f"[fleet] repeat {rep + 1}/{self.repeats}")
            all_metrics.append(self.run_repeat(rep, checkpoint))
        result: dict = {"repeats": all_metrics}
        if isinstance(self.backend, ResilientBackend) and self.backend.failures:
            # prompts that exhausted retries and were scored as INFER_FAILED
            result["lost_prompts"] = len(self.backend.failures)
            log_event("fleet.lost_prompts", level="warning",
                      lost=len(self.backend.failures))
            if self.progress:
                print(f"[fleet] {len(self.backend.failures)} prompts lost to "
                      f"{INFER_FAILED} after retries")
        if (self.run_consistency and set(FLEET_TASKS) <= set(self.task_names)
                and self._should_write()):
            scorer = ConsistencyScorer(self._model_info(), self.dataset,
                                       results_dir=self.results_dir,
                                       progress=self.progress)
            result["consistency"] = scorer.run()
        trailer = self._prefix_cache_trailer()
        if trailer:
            result["prefix_cache"] = trailer
            if self.progress:
                print(f"[fleet] prefix cache: {trailer}")
        serving = self._serving_trailer()
        if serving:
            result["serving"] = serving
            if self.progress:
                print(f"[fleet] serving lifecycle: {serving}")
        speculative = self._spec_trailer()
        if speculative:
            result["speculative"] = speculative
            if self.progress:
                print(f"[fleet] speculative decoding: {speculative}")
        receipts = self._receipt_trailer()
        if receipts:
            result["receipts"] = receipts
            if self.progress:
                fps = receipts["fingerprints"]
                print(f"[fleet] receipts: {len(fps)} fingerprint(s) "
                      f"{fps} — "
                      f"{'converged' if receipts['converged'] else 'DIVERGENT'}")
        latency = self._latency_trailer()
        if latency:
            result["latency"] = latency
            if self.progress:
                for name, row in latency.items():
                    print(f"[fleet] latency {name}: "
                          f"p50={row['p50']}s p95={row['p95']}s "
                          f"p99={row['p99']}s (n={row['count']})")
        self._write_metrics_snapshot(result)
        return result

    def _note_task_receipt(self, rep: int, task_name: str) -> None:
        """Journal the receipt that covered one task's inference.  The
        fused-batch path rides one request, so all its tasks share one
        receipt — the journal still names each task (that is what a
        reproduction diff greps by)."""
        receipt = getattr(self.backend, "last_receipt", None)
        if not isinstance(receipt, dict):
            return
        self._receipts.append({
            "repeat": rep + 1, "task": task_name,
            "fingerprint": receipt.get("fingerprint"),
            "engine_id": receipt.get("engine_id"),
            "digest": receipt.get("digest")})

    def _receipt_trailer(self) -> dict | None:
        """The run's receipt story: every fingerprint observed (one =
        the whole run served under one config; more = the fleet failed
        over across divergent replicas mid-run) + the per-task journal."""
        if not self._receipts:
            return None
        fps = sorted({r["fingerprint"] for r in self._receipts
                      if r["fingerprint"]})
        return {"fingerprints": fps, "converged": len(fps) <= 1,
                "tasks": list(self._receipts)}

    def _prefix_cache_trailer(self) -> dict | None:
        """Engine prefix-cache counters for the run summary, when the
        backend exposes a TPU engine (ResilientBackend delegates attribute
        access to the wrapped backend).  Repeats 2..N riding repeat 1's
        cached templates show up here as hit_rate ≈ the template share."""
        engine = getattr(self.backend, "engine", None)
        stats = getattr(engine, "stats", None)
        if stats is None or not getattr(stats, "prefix_lookup_tokens", 0):
            return None
        # the SAME dict bench JSON renders (EngineStats.prefix_counters —
        # the serving_counters sibling), so the two surfaces cannot drift
        trailer = dict(stats.prefix_counters())
        gauges = getattr(engine, "prefix_cache_counters", None)
        if callable(gauges):
            trailer.update(gauges())
        return trailer

    def _serving_trailer(self) -> dict | None:
        """Serving-lifecycle counters for the run summary, when the
        backend exposes an engine whose stats saw lifecycle events
        (co-located serve + fleet, or an engine that lived through a
        drain).  All-zero counters stay out of the summary — a plain
        in-process fleet never shed, expired, or tripped anything."""
        stats = getattr(getattr(self.backend, "engine", None), "stats", None)
        counters = getattr(stats, "serving_counters", None)
        if not callable(counters):
            return None
        trailer = counters()
        return trailer if any(trailer.values()) else None

    def _spec_trailer(self) -> dict | None:
        """Speculative-decoding counters for the run summary (accept
        rate, drafted/accepted/rolled-back tokens — the SAME
        ``EngineStats.spec_counters`` dict bench JSON renders).  Absent
        when the backend exposes no instrumented engine or nothing was
        drafted/constrained this run."""
        stats = getattr(getattr(self.backend, "engine", None), "stats", None)
        counters = getattr(stats, "spec_counters", None)
        if not callable(counters):
            return None
        trailer = counters()
        return (trailer if (trailer.get("rounds")
                            or trailer.get("grammar_requests")) else None)

    def _latency_trailer(self) -> dict | None:
        """p50/p95/p99 of the engine's request-latency histograms (TTFT,
        TPOT, e2e, queue-wait) — distributions, not averages, are the
        operative serving SLOs (Comparative Analysis of vLLM and TGI,
        PAPERS.md).  None when the backend exposes no instrumented
        engine (HTTP/mock fleets) or obs was disabled."""
        stats = getattr(getattr(self.backend, "engine", None), "stats", None)
        summary = getattr(stats, "latency_summary", None)
        if not callable(summary):
            return None
        return summary() or None

    def _write_metrics_snapshot(self, result: dict) -> None:
        """Persist the engine's full metrics registry next to the fleet
        checkpoint journal (<results_dir>/fleet_metrics.json): the run's
        distributions survive for ``tools/obs_report.py`` (one snapshot
        renders; two diff — e.g. before/after a scheduler change)."""
        stats = getattr(getattr(self.backend, "engine", None), "stats", None)
        if stats is None or self.multihost is not None:
            return
        from .obs import metrics as obs_metrics

        if (not stats.registry.counter(obs_metrics.REQUESTS).value
                and not stats.prompts):
            # zero requests completed this run — e.g. a --resume where
            # every chunk was already journaled.  Writing would clobber
            # the PREVIOUS run's real distributions with an empty shell.
            if self.progress:
                print("[fleet] no requests completed — keeping the "
                      "existing metrics snapshot")
            return
        import json
        import os
        import time

        snap = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "dataset": self.dataset, "prompt_type": self.prompt_type,
                "repeats": self.repeats,
                "metrics": stats.registry.snapshot()}
        if result.get("latency"):
            snap["latency"] = result["latency"]
        if result.get("prefix_cache"):
            snap["prefix_cache"] = result["prefix_cache"]
        if result.get("serving"):
            snap["serving"] = result["serving"]
        if result.get("speculative"):
            snap["speculative"] = result["speculative"]
        if result.get("receipts"):
            snap["receipts"] = result["receipts"]
        try:
            os.makedirs(self.results_dir, exist_ok=True)
            path = os.path.join(self.results_dir, "fleet_metrics.json")
            with open(path + ".tmp", "w") as f:
                json.dump(snap, f, indent=1)
            os.replace(path + ".tmp", path)
            if self.progress:
                print(f"[fleet] metrics snapshot: {path}")
        except OSError as exc:
            # a read-only results dir must not fail the run — but the
            # lost snapshot should leave a trace
            log_event("fleet.snapshot_error", level="warning", exc=exc,
                      results_dir=self.results_dir)
