"""Fleet runner: all four tasks × N repeats on one resident model.

The reference drives a fleet by spawning the four task evaluations as
concurrent OS processes, each re-connecting to a separately-launched vLLM
server, five times over (reference batch_run.py:20-32 + start_server.sh).
On TPU the right shape is the opposite: **one** resident sharded model and
one process.  Per repeat, the fleet

1. plans all four tasks up front (ground-truth sandboxes, prompt
   rendering);
2. when the tasks share one backend, concatenates every prompt into a
   single ``infer_many`` call — the engine length-buckets and batches
   across task boundaries, keeping the chips saturated where four
   processes would each trickle single prompts;
3. scores and writes each task's log (the per-task JSONL contract is
   unchanged), then runs the consistency scorer over the latest logs.

Mock/replay fleets (per-task backends) fall back to per-task inference.
"""

from __future__ import annotations

from .tasks import TASKS, ConsistencyScorer

__all__ = ["FleetRunner", "FLEET_TASKS"]

FLEET_TASKS = ("coverage", "path", "state", "output")


class FleetRunner:
    def __init__(self, *, dataset: str, prompt_type: str = "direct",
                 repeats: int = 5, backend=None, mock: bool = False,
                 results_dir: str = "model_generations",
                 run_consistency: bool = True, progress: bool = True,
                 tasks: tuple[str, ...] = FLEET_TASKS,
                 multihost: str | None = None, **task_kwargs):
        assert backend is not None or mock, "fleet needs a backend (or mock=True)"
        assert multihost in (None, "replicate", "global"), multihost
        self.dataset = dataset
        self.prompt_type = prompt_type
        self.repeats = repeats
        self.backend = backend
        self.mock = mock
        self.results_dir = results_dir
        self.run_consistency = run_consistency
        self.progress = progress
        self.task_names = tasks
        # multi-host: "replicate" = engine replica per host, prompts sharded
        # over DCN; "global" = one model sharded across all hosts, identical
        # prompts everywhere (70B-class); None = single host
        self.multihost = multihost
        self.task_kwargs = task_kwargs

    def _make_tasks(self):
        return [
            TASKS[name](model=self.backend, prompt_type=self.prompt_type,
                        dataset=self.dataset, mock=self.mock,
                        results_dir=self.results_dir, progress=self.progress,
                        **self.task_kwargs)
            for name in self.task_names
        ]

    def run_repeat(self) -> dict[str, dict]:
        """One pass over all tasks with fused batched inference."""
        tasks = self._make_tasks()
        planned = [(task, *task._plan()) for task in tasks]
        shared = self.backend is not None and all(
            t.backend is self.backend for t in tasks)
        metrics: dict[str, dict] = {}
        if shared:
            all_jobs = [(task, job) for task, _, jobs in planned for job in jobs]
            if self.progress:
                print(f"[fleet] {len(all_jobs)} prompts across "
                      f"{len(tasks)} tasks → one batched pass")
            prompts = [job.prompt for _, job in all_jobs]
            responses = self._infer(prompts)
            if not self._should_write():
                return {t.name: {} for t, _, _ in planned}
            cursor = 0
            for task, records, jobs in planned:
                chunk = responses[cursor:cursor + len(jobs)]
                cursor += len(jobs)
                metrics[task.name] = task.score_and_write(records, jobs, chunk)
        else:
            for task, records, jobs in planned:
                responses = task.backend.infer_many([j.prompt for j in jobs])
                metrics[task.name] = task.score_and_write(records, jobs, responses)
        return metrics

    def _infer(self, prompts: list[str]) -> list[str]:
        """Batched inference, sharded across hosts when configured."""
        if self.multihost == "replicate":
            from .parallel.distributed import gather_strings, shard_for_host

            local, _ = shard_for_host(prompts)
            return gather_strings(self.backend.infer_many(local))
        return self.backend.infer_many(prompts)

    def _should_write(self) -> bool:
        """In multi-host runs only the primary host scores + writes logs."""
        if self.multihost is None:
            return True
        from .parallel.distributed import is_primary_host

        return is_primary_host()

    def run(self) -> dict:
        """All repeats + the consistency score (reference batch_run.py:20-32)."""
        all_metrics: list[dict[str, dict]] = []
        for rep in range(self.repeats):
            if self.progress:
                print(f"[fleet] repeat {rep + 1}/{self.repeats}")
            all_metrics.append(self.run_repeat())
        result: dict = {"repeats": all_metrics}
        if (self.run_consistency and set(FLEET_TASKS) <= set(self.task_names)
                and self._should_write()):
            model_info = ("mock_model_" + self.prompt_type if self.mock
                          else self.backend.info)
            scorer = ConsistencyScorer(model_info, self.dataset,
                                       results_dir=self.results_dir,
                                       progress=self.progress)
            result["consistency"] = scorer.run()
        return result
