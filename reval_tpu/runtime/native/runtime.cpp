// Paged-KV block allocator + continuous-batching scheduler.
//
// TPU-native equivalent of the native scheduling/allocation machinery the
// reference gets through vLLM (reference inference.py:90-95 constructs
// vllm.LLM, whose C++/CUDA core owns the paged KV block pool and the
// continuous-batching scheduler; SURVEY.md §2.9 catalogues that vendored
// dependency).  The accelerator side of paging lives in JAX/Pallas
// (reval_tpu/ops/pallas_attention.py); this library owns the host-side
// bookkeeping: which HBM pages belong to which sequence, which requests
// run in which batch slots, admission control, and prefix-sharing forks.
//
// Exposed as a plain C ABI consumed via ctypes (reval_tpu/runtime) — no
// pybind11 in the image, and the call rate (one advance per decode chunk)
// is far below where binding overhead matters.
//
// Concurrency: single-owner.  The engine drives one runtime from one
// thread; no locks inside.
//
// Page 0 is the trash page (see models/paged.py): never allocated, used to
// pad block tables, so a stale table slot can never alias live data.

#include <algorithm>
#include <cstdint>
#include <deque>
#include <unordered_map>
#include <vector>

namespace {

enum class SeqState { kWaiting, kRunning, kPrefix };

struct Seq {
  int64_t id = -1;
  std::vector<int32_t> pages;  // owned (or shared, see ref_counts) page ids
  int32_t len = 0;             // tokens currently materialised in the cache
  int32_t prompt_len = 0;
  int32_t max_new = 0;
  int32_t slot = -1;           // batch slot while running, -1 otherwise
  int64_t prefix_id = -1;      // shared-prefix object this request rides on
  int32_t prefix_pages = 0;    // prefix pages attached to the table (0 = none)
  SeqState state = SeqState::kWaiting;
};

struct Runtime {
  int32_t num_pages = 0;
  int32_t page_size = 0;
  int32_t max_slots = 0;
  int32_t max_pages_per_seq = 0;

  std::vector<int32_t> free_list;       // LIFO for locality
  std::vector<int32_t> ref_counts;      // per page; >1 under prefix sharing
  std::vector<int64_t> slots;           // slot -> seq id (-1 = idle)
  std::deque<int64_t> waiting;          // FCFS admission queue
  std::unordered_map<int64_t, Seq> seqs;
  int64_t next_id = 1;

  int32_t pages_needed(int32_t tokens) const {
    return (tokens + page_size - 1) / page_size;
  }
  int32_t alloc_page() {
    if (free_list.empty()) return -1;
    int32_t p = free_list.back();
    free_list.pop_back();
    ref_counts[p] = 1;
    return p;
  }
  void drop_page(int32_t p) {
    if (--ref_counts[p] == 0) free_list.push_back(p);
  }
};

Runtime* as_rt(void* h) { return static_cast<Runtime*>(h); }

}  // namespace

extern "C" {

void* reval_rt_create(int32_t num_pages, int32_t page_size, int32_t max_slots,
                      int32_t max_pages_per_seq) {
  if (num_pages < 2 || page_size < 1 || max_slots < 1 || max_pages_per_seq < 1)
    return nullptr;
  auto* rt = new Runtime();
  rt->num_pages = num_pages;
  rt->page_size = page_size;
  rt->max_slots = max_slots;
  rt->max_pages_per_seq = max_pages_per_seq;
  rt->ref_counts.assign(num_pages, 0);
  rt->slots.assign(max_slots, -1);
  rt->free_list.reserve(num_pages - 1);
  // page 0 is the trash page: permanently "allocated", never handed out
  rt->ref_counts[0] = 1;
  for (int32_t p = num_pages - 1; p >= 1; --p) rt->free_list.push_back(p);
  return rt;
}

void reval_rt_destroy(void* h) { delete as_rt(h); }

// Queue a request.  Returns the sequence id, or -1 if the request can
// never fit (more pages than max_pages_per_seq allows).
int64_t reval_rt_submit(void* h, int32_t prompt_len, int32_t max_new_tokens) {
  auto* rt = as_rt(h);
  if (prompt_len < 1 || max_new_tokens < 0) return -1;
  // must fit both the per-sequence table and the pool running solo
  // (num_pages - 1 usable: page 0 is the trash page) — otherwise the
  // request could never complete even with everything else preempted
  int32_t total = rt->pages_needed(prompt_len + max_new_tokens);
  if (total > rt->max_pages_per_seq || total > rt->num_pages - 1)
    return -1;
  Seq seq;
  seq.id = rt->next_id++;
  seq.prompt_len = prompt_len;
  seq.max_new = max_new_tokens;
  rt->seqs.emplace(seq.id, seq);
  rt->waiting.push_back(seq.id);
  return seq.id;
}

// FCFS admission: move waiting sequences into free batch slots while the
// pool can hold their prompt pages plus a one-page decode watermark.
// Fills seq_ids/slot_ids (each sized >= max_n); returns the count admitted.
// Admitted sequences have their prompt pages allocated and len = prompt_len
// — the engine prefills and commits the KV for exactly those pages
// (prefix-backed requests: only the suffix pages; their prefix pages are
// attached by refcount here).
int32_t reval_rt_admit(void* h, int64_t* seq_ids, int32_t* slot_ids,
                       int32_t max_n) {
  auto* rt = as_rt(h);
  int32_t admitted = 0;
  while (admitted < max_n && !rt->waiting.empty()) {
    int64_t id = rt->waiting.front();
    Seq& seq = rt->seqs.at(id);
    // attach the shared-prefix pages (refcounted) before counting what is
    // missing; a preempted prefix-backed request re-attaches here too
    if (seq.prefix_id >= 0 && seq.pages.empty()) {
      auto pit = rt->seqs.find(seq.prefix_id);
      if (pit != rt->seqs.end() && pit->second.state == SeqState::kPrefix) {
        for (int32_t p : pit->second.pages) {
          ++rt->ref_counts[p];
          seq.pages.push_back(p);
        }
        seq.prefix_pages = static_cast<int32_t>(pit->second.pages.size());
      } else {
        // prefix gone (released before this rider was admitted): detach
        // explicitly.  reval_rt_prefix_pages now reports 0, telling the
        // engine its prefill must cover the FULL prompt itself — the
        // freshly allocated prefix-region pages hold no KV until it does.
        seq.prefix_id = -1;
        seq.prefix_pages = 0;
      }
    }
    // a waiting sequence may already own pages (fork children / prefix
    // riders) — only the missing prompt pages need allocating
    int32_t have = static_cast<int32_t>(seq.pages.size());
    int32_t need = rt->pages_needed(std::max(seq.prompt_len, seq.len));
    int32_t missing = need > have ? need - have : 0;
    // one-page decode watermark, but only when decode will ever grow the
    // allocation — a request whose full budget fits its prompt pages may
    // take the last free page (otherwise it can deadlock the queue)
    int32_t grows = rt->pages_needed(seq.prompt_len + seq.max_new) > need;
    if (static_cast<int32_t>(rt->free_list.size()) < missing + grows) break;
    int32_t slot = -1;
    for (int32_t s = 0; s < rt->max_slots; ++s)
      if (rt->slots[s] == -1) { slot = s; break; }
    if (slot == -1) break;
    rt->waiting.pop_front();
    seq.pages.reserve(need);
    for (int32_t i = 0; i < missing; ++i) seq.pages.push_back(rt->alloc_page());
    seq.len = std::max(seq.len, seq.prompt_len);
    seq.slot = slot;
    seq.state = SeqState::kRunning;
    rt->slots[slot] = id;
    seq_ids[admitted] = id;
    slot_ids[admitted] = slot;
    ++admitted;
  }
  return admitted;
}

// Allocate a shared-prefix object: n_pages pages holding KV that many
// requests will reference (e.g. a few-shot prompt template).  The engine
// prefills into these pages once; requests submitted with
// reval_rt_submit_prefixed ride them by refcount.  Returns the prefix id
// (release with reval_rt_release when no more requests will be submitted
// against it — pages survive until the last rider finishes), or -1 OOM.
int64_t reval_rt_alloc_prefix(void* h, int32_t n_pages) {
  auto* rt = as_rt(h);
  if (n_pages < 1 || n_pages > rt->max_pages_per_seq ||
      static_cast<int32_t>(rt->free_list.size()) < n_pages)
    return -1;
  Seq prefix;
  prefix.id = rt->next_id++;
  prefix.len = n_pages * rt->page_size;
  prefix.prompt_len = prefix.len;
  prefix.state = SeqState::kPrefix;
  for (int32_t i = 0; i < n_pages; ++i)
    prefix.pages.push_back(rt->alloc_page());
  rt->seqs.emplace(prefix.id, prefix);
  return prefix.id;
}

// Extend an existing prefix object by n_pages fresh pages: the child
// prefix references every parent page by refcount and owns the new tail —
// the building block of a radix prefix tree, where a longer cached prefix
// shares all its ancestor pages with shorter ones.  Releasing the child
// drops only its own refs (the parent keeps the shared pages alive), so
// LRU eviction of a leaf frees exactly its own pages.  Returns the child
// prefix id, or -1 (unknown/dead parent, bad n_pages, table overflow, OOM).
int64_t reval_rt_alloc_prefix_extend(void* h, int64_t parent_id,
                                     int32_t n_pages) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(parent_id);
  if (it == rt->seqs.end() || it->second.state != SeqState::kPrefix)
    return -1;
  Seq& parent = it->second;
  int32_t total = static_cast<int32_t>(parent.pages.size()) + n_pages;
  if (n_pages < 1 || total > rt->max_pages_per_seq ||
      static_cast<int32_t>(rt->free_list.size()) < n_pages)
    return -1;
  Seq child;
  child.id = rt->next_id++;
  child.pages = parent.pages;
  for (int32_t p : child.pages) ++rt->ref_counts[p];
  for (int32_t i = 0; i < n_pages; ++i) child.pages.push_back(rt->alloc_page());
  child.len = total * rt->page_size;
  child.prompt_len = child.len;
  child.state = SeqState::kPrefix;
  rt->seqs.emplace(child.id, child);
  return child.id;
}

// Queue a request whose first pages are a shared prefix.  prompt_len is
// the TOTAL prompt length (prefix tokens included); admission attaches the
// prefix pages by refcount and allocates only the remainder.
int64_t reval_rt_submit_prefixed(void* h, int64_t prefix_id,
                                 int32_t prompt_len, int32_t max_new_tokens) {
  auto* rt = as_rt(h);
  auto pit = rt->seqs.find(prefix_id);
  if (pit == rt->seqs.end() || pit->second.state != SeqState::kPrefix)
    return -1;
  if (prompt_len <= pit->second.len) return -1;  // must extend past the prefix
  int64_t id = reval_rt_submit(h, prompt_len, max_new_tokens);
  if (id != -1) rt->seqs.at(id).prefix_id = prefix_id;
  return id;
}

// Copy the sequence's block table into out (length max_pages_per_seq),
// padding with the trash page.  Returns the number of live pages, -1 on
// unknown id.
int32_t reval_rt_block_table(void* h, int64_t seq_id, int32_t* out) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  if (it == rt->seqs.end()) return -1;
  const auto& pages = it->second.pages;
  for (int32_t i = 0; i < rt->max_pages_per_seq; ++i)
    out[i] = i < static_cast<int32_t>(pages.size()) ? pages[i] : 0;
  return static_cast<int32_t>(pages.size());
}

int32_t reval_rt_seq_len(void* h, int64_t seq_id) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  return it == rt->seqs.end() ? -1 : it->second.len;
}

int32_t reval_rt_slot_of(void* h, int64_t seq_id) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  return it == rt->seqs.end() ? -1 : it->second.slot;
}

// Extend a running sequence by n generated tokens, allocating pages as
// they cross page boundaries.  Returns the new length, or -1 if the pool
// is exhausted (caller should preempt; the sequence keeps the pages it
// had, and its length the tokens those pages can hold).
int32_t reval_rt_advance(void* h, int64_t seq_id, int32_t n) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  if (it == rt->seqs.end() || it->second.state != SeqState::kRunning || n < 0)
    return -1;
  Seq& seq = it->second;
  int32_t target = seq.len + n;
  int32_t need = rt->pages_needed(target);
  if (need > rt->max_pages_per_seq) return -1;
  while (static_cast<int32_t>(seq.pages.size()) < need) {
    int32_t p = rt->alloc_page();
    // OOM: leave len untouched (pages grabbed so far stay accounted to the
    // sequence; a retry after preemption needs correspondingly fewer)
    if (p == -1) return -1;
    seq.pages.push_back(p);
  }
  seq.len = target;
  return target;
}

// Shrink a RUNNING sequence's materialised length to new_len, freeing
// owned tail pages past the covering count — the speculative-decoding
// reject path: reval_rt_advance reserved pages for the whole draft
// window before the verify dispatch, and the rejected tail must not
// stay accounted to the sequence (the drift would inflate its length
// every round until it spuriously hits max_pages_per_seq).  Never
// frees shared prefix pages and never shrinks below prompt_len.
// Returns 0, or -1 (not running, or new_len outside [prompt_len, len]).
int32_t reval_rt_rollback(void* h, int64_t seq_id, int32_t new_len) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  if (it == rt->seqs.end() || it->second.state != SeqState::kRunning)
    return -1;
  Seq& seq = it->second;
  if (new_len < seq.prompt_len || new_len > seq.len) return -1;
  int32_t keep = std::max(rt->pages_needed(new_len), seq.prefix_pages);
  keep = std::max(keep, 1);  // a live sequence always keeps one page
  while (static_cast<int32_t>(seq.pages.size()) > keep) {
    rt->drop_page(seq.pages.back());
    seq.pages.pop_back();
  }
  seq.len = new_len;
  return 0;
}

// Fork for prefix sharing: the child shares every *full* page of the
// parent by refcount and gets a fresh page for the partial tail (the
// engine must copy the tail page's contents device-side).  The child is
// queued as waiting; reval_rt_admit attaches it to a slot, allocating only
// pages it does not already hold and preserving its inherited length.
// Returns the child id, or -1 on failure.  Out param fresh_page receives
// the tail page id, or the trash page if the parent's length is
// page-aligned.
int64_t reval_rt_fork(void* h, int64_t seq_id, int32_t* fresh_page) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  if (it == rt->seqs.end()) return -1;
  Seq& parent = it->second;
  int32_t full = parent.len / rt->page_size;
  bool has_tail = parent.len % rt->page_size != 0;
  int32_t tail = 0;
  if (has_tail) {
    tail = rt->alloc_page();
    if (tail == -1) return -1;
  }
  Seq child;
  child.id = rt->next_id++;
  child.prompt_len = parent.prompt_len;
  child.max_new = parent.max_new;
  child.len = parent.len;
  child.pages.assign(parent.pages.begin(), parent.pages.begin() + full);
  for (int32_t p : child.pages) ++rt->ref_counts[p];
  if (has_tail) child.pages.push_back(tail);
  *fresh_page = has_tail ? tail : 0;
  rt->seqs.emplace(child.id, child);
  rt->waiting.push_back(child.id);
  return child.id;
}

namespace {

// Shared preemption core.  Recompute is RESUME-style (vLLM recompute
// semantics): everything materialised plus the one sampled-but-unwritten
// token is folded into prompt_len, so the re-admission prefill replays
// prompt+generated and decoding continues where it left off —
// already-sampled tokens are never resampled (which would silently change
// results at temperature > 0).
void do_preempt(Runtime* rt, int64_t victim, int32_t materialized) {
  Seq& seq = rt->seqs.at(victim);
  for (int32_t p : seq.pages) rt->drop_page(p);
  seq.pages.clear();
  rt->slots[seq.slot] = -1;
  seq.slot = -1;
  int32_t resumed = materialized + 1;  // +1: the pending sampled token
  seq.max_new -= resumed - seq.prompt_len;
  seq.prompt_len = resumed;
  seq.len = 0;
  seq.prefix_pages = 0;  // re-attached (if the prefix lives) at re-admission
  seq.state = SeqState::kWaiting;
  rt->waiting.push_front(victim);
}

}  // namespace

// Preempt a specific running sequence, with the CALLER's count of tokens
// actually materialised in its pages.  The runtime's own seq.len cannot be
// trusted here: reval_rt_advance reserves pages for a decode chunk BEFORE
// it executes, so a victim picked mid-reservation carries up-to-chunk-size
// phantom tokens in len — folding those into prompt_len would permanently
// inflate its accounting (early OOMs, spurious re-preemption, possible
// deadlock of a feasible workload).  Returns 0, or -1 if the sequence is
// not running or materialized_len is outside [prompt_len-1 .. len].
int32_t reval_rt_preempt(void* h, int64_t seq_id, int32_t materialized_len) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  if (it == rt->seqs.end() || it->second.state != SeqState::kRunning)
    return -1;
  // prompt_len-1: a resumed victim preempted again before any new decode
  // (its pending token is counted by the +1 fold, not by materialized)
  if (materialized_len < it->second.prompt_len - 1 ||
      materialized_len > it->second.len)
    return -1;
  do_preempt(rt, seq_id, materialized_len);
  return 0;
}

// Preempt the most recently admitted running sequence, trusting seq.len as
// the materialised count.  ONLY sound when no advance() reservation is
// outstanding (the engine uses reval_rt_preempt with its own count
// instead).  Returns the victim id, or -1 if nothing is running.
int64_t reval_rt_preempt_last(void* h) {
  auto* rt = as_rt(h);
  int64_t victim = -1;
  for (int32_t s = 0; s < rt->max_slots; ++s)
    if (rt->slots[s] != -1 && rt->slots[s] > victim) victim = rt->slots[s];
  if (victim == -1) return -1;
  do_preempt(rt, victim, rt->seqs.at(victim).len);
  return victim;
}

// Finish a sequence: free pages (refcount-aware) and its slot, forget it.
void reval_rt_release(void* h, int64_t seq_id) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  if (it == rt->seqs.end()) return;
  Seq& seq = it->second;
  for (int32_t p : seq.pages) rt->drop_page(p);
  if (seq.slot >= 0) rt->slots[seq.slot] = -1;
  if (seq.state == SeqState::kWaiting)
    for (auto w = rt->waiting.begin(); w != rt->waiting.end(); ++w)
      if (*w == seq_id) { rt->waiting.erase(w); break; }
  rt->seqs.erase(it);
}

int32_t reval_rt_free_pages(void* h) {
  return static_cast<int32_t>(as_rt(h)->free_list.size());
}
int32_t reval_rt_num_waiting(void* h) {
  return static_cast<int32_t>(as_rt(h)->waiting.size());
}
int32_t reval_rt_num_running(void* h) {
  auto* rt = as_rt(h);
  int32_t n = 0;
  for (int64_t s : rt->slots) n += s != -1;
  return n;
}
int32_t reval_rt_page_ref(void* h, int32_t page) {
  auto* rt = as_rt(h);
  if (page < 0 || page >= rt->num_pages) return -1;
  return rt->ref_counts[page];
}

// Shared-prefix pages currently attached to this sequence's block table
// (0 when it rides no prefix, was detached because the prefix died before
// admission, or is waiting un-admitted).  The engine's prefill must cover
// prompt_len - prefix_pages*page_size tokens itself.
int32_t reval_rt_prefix_pages(void* h, int64_t seq_id) {
  auto* rt = as_rt(h);
  auto it = rt->seqs.find(seq_id);
  return it == rt->seqs.end() ? -1 : it->second.prefix_pages;
}

}  // extern "C"
