"""Native runtime bindings: paged-KV allocator + continuous-batching scheduler.

The C++ library (``native/runtime.cpp``) owns the host-side state of the
paged KV cache — the free-page pool, per-sequence block tables, batch-slot
assignment, FCFS admission with a decode watermark, recompute-style
preemption, and refcounted prefix-sharing forks.  This package compiles it
on first use (g++, no external deps) and wraps the C ABI with ctypes.

Split of responsibilities with the JAX side:
- this runtime decides *which pages* and *which slots* (integers only);
- ``models/paged.py`` + the Pallas kernel move the actual KV bytes in HBM.
The engine (inference/tpu/paged_engine.py) is the glue loop.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import tempfile

import numpy as np

__all__ = ["PagedRuntime", "load_native", "NativeBuildError"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "runtime.cpp")
_LIB = None


class NativeBuildError(RuntimeError):
    pass


def _build(so_path: str) -> None:
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise NativeBuildError("no C++ compiler found (need g++ or c++ on PATH)")
    # build to a temp name then rename: atomic against concurrent importers
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(so_path))
    os.close(fd)
    cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        os.unlink(tmp)
        raise NativeBuildError(f"native build failed: {' '.join(cmd)}\n{proc.stderr}")
    os.replace(tmp, so_path)


def load_native() -> ctypes.CDLL:
    """Compile (if stale) and load the runtime library; cached per process."""
    global _LIB
    if _LIB is not None:
        return _LIB
    so_path = os.path.join(_NATIVE_DIR, "_reval_rt.so")
    if (not os.path.exists(so_path)
            or os.path.getmtime(so_path) < os.path.getmtime(_SRC)):
        _build(so_path)
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        # stale artifact from another platform/arch: rebuild once
        _build(so_path)
        lib = ctypes.CDLL(so_path)
    i32, i64, ptr = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
    p32 = ctypes.POINTER(ctypes.c_int32)
    p64 = ctypes.POINTER(ctypes.c_int64)
    sigs = {
        "reval_rt_create": ([i32, i32, i32, i32], ptr),
        "reval_rt_destroy": ([ptr], None),
        "reval_rt_submit": ([ptr, i32, i32], i64),
        "reval_rt_alloc_prefix": ([ptr, i32], i64),
        "reval_rt_alloc_prefix_extend": ([ptr, i64, i32], i64),
        "reval_rt_submit_prefixed": ([ptr, i64, i32, i32], i64),
        "reval_rt_admit": ([ptr, p64, p32, i32], i32),
        "reval_rt_block_table": ([ptr, i64, p32], i32),
        "reval_rt_seq_len": ([ptr, i64], i32),
        "reval_rt_slot_of": ([ptr, i64], i32),
        "reval_rt_advance": ([ptr, i64, i32], i32),
        "reval_rt_rollback": ([ptr, i64, i32], i32),
        "reval_rt_fork": ([ptr, i64, p32], i64),
        "reval_rt_preempt": ([ptr, i64, i32], i32),
        "reval_rt_preempt_last": ([ptr], i64),
        "reval_rt_release": ([ptr, i64], None),
        "reval_rt_free_pages": ([ptr], i32),
        "reval_rt_num_waiting": ([ptr], i32),
        "reval_rt_num_running": ([ptr], i32),
        "reval_rt_page_ref": ([ptr, i32], i32),
        "reval_rt_prefix_pages": ([ptr, i64], i32),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    _LIB = lib
    return lib


class PagedRuntime:
    """Pythonic facade over the native scheduler/allocator.

    One instance manages one paged KV cache pool (`num_pages` pages of
    `page_size` tokens) and one decode batch of `max_slots` slots.
    """

    def __init__(self, num_pages: int, page_size: int, max_slots: int,
                 max_pages_per_seq: int):
        self._lib = load_native()
        self._h = self._lib.reval_rt_create(num_pages, page_size, max_slots,
                                            max_pages_per_seq)
        if not self._h:
            raise ValueError("invalid PagedRuntime parameters")
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_slots = max_slots
        self.max_pages_per_seq = max_pages_per_seq

    def close(self) -> None:
        if self._h:
            self._lib.reval_rt_destroy(self._h)
            self._h = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass

    # -- request lifecycle -------------------------------------------------
    def submit(self, prompt_len: int, max_new_tokens: int) -> int:
        seq_id = self._lib.reval_rt_submit(self._h, prompt_len, max_new_tokens)
        if seq_id == -1:
            raise ValueError(
                f"request (prompt={prompt_len}, new={max_new_tokens}) exceeds "
                f"max_pages_per_seq={self.max_pages_per_seq}")
        return seq_id

    def alloc_prefix(self, n_pages: int) -> int:
        """Reserve pages for a shared prompt prefix (few-shot template);
        submit riders with :meth:`submit_prefixed`, free the reservation
        with :meth:`release` (pages live on until the last rider ends)."""
        prefix_id = self._lib.reval_rt_alloc_prefix(self._h, n_pages)
        if prefix_id == -1:
            raise ValueError(f"cannot reserve {n_pages} prefix pages "
                             f"({self.free_pages} free)")
        return prefix_id

    def alloc_prefix_extend(self, parent_id: int, n_pages: int) -> int:
        """Extend a live prefix by ``n_pages`` fresh pages: the child
        prefix shares every parent page by refcount and owns the new tail
        (the radix-tree building block — see
        inference/tpu/prefix_cache.py).  Releasing the child frees only
        its own pages."""
        prefix_id = self._lib.reval_rt_alloc_prefix_extend(
            self._h, parent_id, n_pages)
        if prefix_id == -1:
            raise ValueError(
                f"cannot extend prefix {parent_id} by {n_pages} pages "
                f"(dead/unknown parent, table overflow, or only "
                f"{self.free_pages} pages free)")
        return prefix_id

    def submit_prefixed(self, prefix_id: int, prompt_len: int,
                        max_new_tokens: int) -> int:
        """Queue a request whose prompt starts with the shared prefix
        (``prompt_len`` counts the TOTAL prompt, prefix included)."""
        seq_id = self._lib.reval_rt_submit_prefixed(
            self._h, prefix_id, prompt_len, max_new_tokens)
        if seq_id == -1:
            raise ValueError(
                f"prefixed request (prefix={prefix_id}, prompt={prompt_len}, "
                f"new={max_new_tokens}) invalid: unknown/dead prefix, prompt "
                f"not longer than the prefix, or exceeds page limits")
        return seq_id

    def admit(self, max_n: int | None = None) -> list[tuple[int, int]]:
        """Admit waiting requests FCFS → [(seq_id, slot), ...]."""
        max_n = self.max_slots if max_n is None else max_n
        ids = (ctypes.c_int64 * max_n)()
        slots = (ctypes.c_int32 * max_n)()
        n = self._lib.reval_rt_admit(self._h, ids, slots, max_n)
        return [(int(ids[i]), int(slots[i])) for i in range(n)]

    def block_table(self, seq_id: int) -> np.ndarray:
        out = np.zeros(self.max_pages_per_seq, np.int32)
        n = self._lib.reval_rt_block_table(
            self._h, seq_id, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if n < 0:
            raise KeyError(seq_id)
        return out

    def seq_len(self, seq_id: int) -> int:
        n = self._lib.reval_rt_seq_len(self._h, seq_id)
        if n < 0:
            raise KeyError(seq_id)
        return n

    def slot_of(self, seq_id: int) -> int:
        return self._lib.reval_rt_slot_of(self._h, seq_id)

    def advance(self, seq_id: int, n: int) -> int | None:
        """Extend by ``n`` tokens; None signals OOM (caller preempts)."""
        res = self._lib.reval_rt_advance(self._h, seq_id, n)
        return None if res == -1 else res

    def rollback(self, seq_id: int, new_len: int) -> None:
        """Shrink a running sequence to ``new_len`` materialised tokens,
        freeing owned tail pages the shrink uncovers — the speculative
        verify's reject path (``advance`` reserved the whole draft
        window up front; rejected drafts must not stay accounted)."""
        if self._lib.reval_rt_rollback(self._h, seq_id, new_len) != 0:
            raise ValueError(
                f"cannot roll seq {seq_id} back to len {new_len}: not "
                f"running, or length outside [prompt_len, len]")

    def fork(self, seq_id: int) -> tuple[int, int]:
        """Prefix-sharing fork → (child_id, fresh_tail_page).  The caller
        must copy the parent's partial tail page into fresh_tail_page on
        device when it is non-zero."""
        fresh = ctypes.c_int32(0)
        child = self._lib.reval_rt_fork(self._h, seq_id, ctypes.byref(fresh))
        if child == -1:
            raise RuntimeError(f"fork of seq {seq_id} failed (unknown id or OOM)")
        return int(child), int(fresh.value)

    def preempt(self, seq_id: int, materialized_len: int) -> None:
        """Preempt a specific running sequence, giving the runtime the
        caller's count of tokens actually materialised in its pages —
        ``advance`` reservations for a not-yet-run chunk must NOT be
        folded into the resume prompt (they would become phantom tokens)."""
        if self._lib.reval_rt_preempt(self._h, seq_id, materialized_len) != 0:
            raise ValueError(
                f"cannot preempt seq {seq_id} at len {materialized_len}: "
                f"not running, or length outside its valid range")

    def preempt_last(self) -> int | None:
        """Preempt the youngest running sequence, trusting the runtime's
        own length (only sound with no outstanding chunk reservation)."""
        victim = self._lib.reval_rt_preempt_last(self._h)
        return None if victim == -1 else int(victim)

    def release(self, seq_id: int) -> None:
        self._lib.reval_rt_release(self._h, seq_id)

    # -- stats -------------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return self._lib.reval_rt_free_pages(self._h)

    @property
    def num_waiting(self) -> int:
        return self._lib.reval_rt_num_waiting(self._h)

    @property
    def num_running(self) -> int:
        return self._lib.reval_rt_num_running(self._h)

    def page_ref(self, page: int) -> int:
        return self._lib.reval_rt_page_ref(self._h, page)

    def prefix_pages(self, seq_id: int) -> int:
        """Shared-prefix pages attached to this sequence's block table
        (0 = the engine's prefill must cover the full prompt itself)."""
        n = self._lib.reval_rt_prefix_pages(self._h, seq_id)
        if n < 0:
            raise KeyError(seq_id)
        return n
