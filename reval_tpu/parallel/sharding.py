"""Sharding rules: params pytree leaf name → PartitionSpec.

Megatron-style tensor parallelism expressed declaratively: attention and
MLP input projections shard their *output* features over ``tp``; output
projections shard their *input* features (so each chip computes a partial
sum and XLA inserts one psum per block); vocab-dimension weights shard over
``tp`` so the logits matmul is parallel too.  Norms and small biases
replicate.  Activations shard batch over ``dp``; XLA propagates everything
else from the parameter shardings.

The KV cache shards batch over ``dp`` and KV heads over ``tp`` (when
divisible), keeping decode attention collective-free.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import ModelConfig

__all__ = ["param_specs", "shard_params", "batch_sharding", "kv_cache_spec",
           "paged_cache_spec", "resolve_moe_impl"]


def resolve_moe_impl(cfg: ModelConfig, mesh: Mesh | None) -> ModelConfig:
    """Pick the MoE formulation for a mesh: the exact ragged path cannot
    shard its data-dependent row partition over ``ep``, so ep>1 meshes
    switch to the capacity-dispatch path (see models/model.py).  Returns
    a config copy — engines call this once at construction."""
    import dataclasses
    import warnings

    if (cfg.num_experts and mesh is not None
            and dict(zip(mesh.axis_names, mesh.devices.shape)).get("ep", 1) > 1
            and cfg.moe_impl != "dispatch"):
        # With the default moe_capacity_factor=None the dispatch path is
        # EXACT (drop-free capacity, chunked — models/model.py), so the
        # switch is silent.  A float factor is a lossy opt-in the user
        # made explicitly; still say so loudly, since for an evaluation
        # framework batch-dependent logits are a correctness hazard
        # (round-4 verdict item 4 retired the warn-only default).
        if cfg.moe_capacity_factor is not None:
            warnings.warn(
                f"ep>1 mesh with explicit moe_capacity_factor="
                f"{cfg.moe_capacity_factor}: dispatch is capacity-bounded, "
                f"router skew beyond it DROPS assignments and can change "
                f"logits — unset moe_capacity_factor for exact dispatch",
                stacklevel=2)
        return dataclasses.replace(cfg, moe_impl="dispatch")
    return cfg

# leaf name → spec for stacked [L, ...] layer weights
# mesh: axes=(ep, tp)
_LAYER_RULES = {
    "q_w": P(None, None, "tp"),
    "k_w": P(None, None, "tp"),
    "v_w": P(None, None, "tp"),
    "o_w": P(None, "tp", None),
    "gate_w": P(None, None, "tp"),
    "up_w": P(None, None, "tp"),
    "down_w": P(None, "tp", None),
    "fc_w": P(None, None, "tp"),
    "proj_w": P(None, "tp", None),
    "q_b": P(None, "tp"),
    "k_b": P(None, "tp"),
    "v_b": P(None, "tp"),
    "fc_b": P(None, "tp"),
    # weight-only int8 scales [L, out] follow their weight's OUT dim:
    # output-feature-sharded weights shard the scale, input-feature-sharded
    # (o_w/down_w/proj_w — partial-sum) weights replicate it
    "q_w_scale": P(None, "tp"),
    "k_w_scale": P(None, "tp"),
    "v_w_scale": P(None, "tp"),
    "gate_w_scale": P(None, "tp"),
    "up_w_scale": P(None, "tp"),
    "fc_w_scale": P(None, "tp"),
    "o_w_scale": P(),
    "down_w_scale": P(),
    "proj_w_scale": P(),
    # int4 group scales [L, G, out]: out-feature-sharded weights shard
    # the out dim; input-feature-sharded (partial-sum) weights shard the
    # GROUP dim, which subdivides the contraction exactly like the weight
    # (groups never straddle a tp shard: g=128 divides every in-slice)
    "q_w_gscale": P(None, None, "tp"),
    "k_w_gscale": P(None, None, "tp"),
    "v_w_gscale": P(None, None, "tp"),
    "gate_w_gscale": P(None, None, "tp"),
    "up_w_gscale": P(None, None, "tp"),
    "fc_w_gscale": P(None, None, "tp"),
    "o_w_gscale": P(None, "tp", None),
    "down_w_gscale": P(None, "tp", None),
    "proj_w_gscale": P(None, "tp", None),
    # moe int4 group scales [L, E, G, out] follow their weight's ep/tp dims
    "moe_gate_w_gscale": P(None, "ep", None, "tp"),
    "moe_up_w_gscale": P(None, "ep", None, "tp"),
    "moe_down_w_gscale": P(None, "ep", "tp", None),
    # mixture-of-experts: expert dim over ``ep``, per-expert FFN dims over
    # ``tp`` (the batched-einsum formulation in models/model.py keeps the
    # expert dim leading, so ep shards experts whole — the dispatch
    # all-to-all is XLA-inserted from the scatter/gather shardings)
    "moe_gate_w": P(None, "ep", None, "tp"),
    "moe_up_w": P(None, "ep", None, "tp"),
    "moe_down_w": P(None, "ep", "tp", None),
    "moe_gate_w_scale": P(None, "ep", "tp"),
    "moe_up_w_scale": P(None, "ep", "tp"),
    "moe_down_w_scale": P(None, "ep", None),
    "router_w": P(),     # [L, D, E] — tiny; replicate so routing is local
    # replicated small leaves
    "o_b": P(),
    "proj_b": P(),
    "attn_norm_w": P(),
    "attn_norm_b": P(),
    "mlp_norm_w": P(),
    "mlp_norm_b": P(),
}

# mesh: axes=(tp)
_TOP_RULES = {
    "embed": P("tp", None),       # vocab-sharded; also the tied lm head
    "lm_head": P(None, "tp"),
    "lm_head_scale": P("tp"),     # int8 scale follows lm_head's vocab dim
    "lm_head_gscale": P(None, "tp"),   # int4 [G, V]: vocab dim sharded
    "final_norm_w": P(),
    "final_norm_b": P(),
}


def _divisible(cfg: ModelConfig, mesh: Mesh) -> dict[str, bool]:
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    tp = sizes.get("tp", 1)
    ep = sizes.get("ep", 1)
    return {
        "heads": cfg.num_heads % tp == 0,
        "kv_heads": cfg.num_kv_heads % tp == 0,
        "ffn": cfg.intermediate_size % tp == 0,
        "vocab": cfg.vocab_size % tp == 0,
        "experts": cfg.num_experts % ep == 0 if cfg.num_experts else True,
    }


# mesh: axes=(ep, tp)
def param_specs(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    """PartitionSpec tree mirroring ``params``.

    Falls back to replication for any dimension the mesh doesn't divide
    (correctness first; the loader warns so mis-sized meshes are visible).
    """
    div = _divisible(cfg, mesh)

    def top_spec(name):
        # gzero leaves (AWQ asymmetric int4) shard exactly like gscales
        spec = _TOP_RULES.get(name.replace("_gzero", "_gscale"), P())
        base = (name.removesuffix("_gzero").removesuffix("_gscale")
                .removesuffix("_scale"))
        if base in ("embed", "lm_head") and not div["vocab"]:
            return P()
        return spec

    def layer_spec(name):
        spec = _LAYER_RULES.get(name.replace("_gzero", "_gscale"), P())
        base = (name.removesuffix("_gzero").removesuffix("_gscale")
                .removesuffix("_scale"))  # scales follow their weight
        if base in ("k_w", "v_w", "k_b", "v_b") and not div["kv_heads"]:
            return P()
        if base in ("q_w", "o_w", "q_b") and not div["heads"]:
            return P()
        if base in ("gate_w", "up_w", "down_w", "fc_w", "proj_w", "fc_b") and not div["ffn"]:
            return P()
        if base in ("moe_gate_w", "moe_up_w", "moe_down_w"):
            # drop per-axis on non-divisible dims, keep the rest
            spec = P(*(None if (a == "ep" and not div["experts"])
                       or (a == "tp" and not div["ffn"]) else a
                       for a in spec))
        return spec

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def fit(name: str, spec: P, leaf) -> P:
        """Shape safety net: drop any spec axis that does not divide its
        dim (e.g. an int4 gscale with fewer groups than tp shards on a
        toy config) — NamedSharding would reject it outright, and GSPMD
        keeps the math correct with the dim replicated.  Downgrades are
        WARNED (once per leaf/axis): a silently replicated 34B leaf is
        gigabytes of duplicated HBM per chip and would otherwise surface
        only as an unexplained OOM.  ``leaf`` is an array or a bare shape
        tuple (the sharded loader passes the checkpoint template)."""
        import warnings

        shape = getattr(leaf, "shape", leaf)
        if len(spec) > len(shape):
            warnings.warn(
                f"sharding: rule for {name!r} has rank {len(spec)} but the "
                f"leaf is rank {len(shape)} — trailing axes dropped "
                f"(template/rule mismatch?)", stacklevel=3)
        out = []
        for d, a in enumerate(spec[:len(shape)]):
            if a is not None and shape[d] % sizes.get(a, 1) != 0:
                warnings.warn(
                    f"sharding: replicating dim {d} of {name!r} "
                    f"(size {shape[d]} not divisible by {a}={sizes.get(a)})",
                    stacklevel=3)
                a = None
            out.append(a)
        return P(*out)

    specs: dict = {}
    for name, value in params.items():
        if name == "layers":
            specs["layers"] = {k: fit(k, layer_spec(k), v)
                               for k, v in value.items()}
        else:
            specs[name] = fit(name, top_spec(name), value)
    return specs


# mesh: axes=()
def shard_params(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    """Place a params pytree onto the mesh per the rules above."""
    specs = param_specs(params, cfg, mesh)
    # multihost global mode: device_put cannot move a committed
    # single-device array onto a mesh spanning other processes — feed it
    # the host value instead (each process then places just its own
    # addressable shards; all hosts hold identical values by construction)
    cross = any(d.process_index != jax.process_index()
                for d in mesh.devices.flat)

    def place(leaf, spec):
        # already-global leaves (shard-direct loads) are not addressable
        # here and must go straight through; device_put re-place is a no-op
        if cross and isinstance(leaf, jax.Array) and leaf.is_fully_addressable:
            leaf = np.asarray(leaf)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(
        place, params, specs,
        is_leaf=lambda x: not isinstance(x, dict),
    )


# mesh: axes=(dp)
def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [B, ...] host arrays (tokens, pad lengths)."""
    return NamedSharding(mesh, P("dp"))


# mesh: axes=(dp, tp)
def kv_cache_spec(cfg: ModelConfig, mesh: Mesh) -> P:
    """[L, B, S, H_kv, D] — batch over dp, kv heads over tp if divisible."""
    div = _divisible(cfg, mesh)
    return P(None, "dp", None, "tp" if div["kv_heads"] else None, None)


# mesh: axes=(tp)
def paged_cache_spec(cfg: ModelConfig, mesh: Mesh) -> P:
    """Per-layer flat pool arrays ``[N_pages * P, H_kv, D]`` — kv heads
    over tp if divisible.  The page pool is shared across the whole decode
    batch, so there is no dp axis; data parallelism for the paged engine
    is one engine replica per dp group (fleet replicate mode)."""
    div = _divisible(cfg, mesh)
    return P(None, "tp" if div["kv_heads"] else None, None)
