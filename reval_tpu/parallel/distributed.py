"""Multi-host runtime helpers: topology, prompt sharding, result gathering.

Two multi-host shapes exist (SURVEY §5.8's TPU-native answer to the
reference's NCCL-inside-vLLM + subprocess fleet):

- **replicated engines** — each host owns a full model replica on its
  local chips; the fleet shards the prompt list across hosts
  (:func:`shard_for_host`), every host decodes its shard, and
  :func:`gather_strings` reassembles the full response list everywhere.
  This is data parallelism over DCN with zero inter-host traffic during
  decode.
- **one global sharded model** (70B-class) — every host executes the same
  ``infer_many`` on the same prompts; XLA shards the computation over the
  global mesh (params over ICI/DCN per parallel/sharding.py) and each
  host sees identical results.  Only the primary host should write logs
  (:func:`is_primary_host`).

All helpers degrade to no-ops in a single-process run, so the same fleet
code runs unchanged on one chip, one host, or a pod.
"""

from __future__ import annotations

import json

import numpy as np

__all__ = [
    "ensure_initialized",
    "process_topology",
    "is_primary_host",
    "shard_for_host",
    "gather_strings",
]

_initialized = False


def ensure_initialized(coordinator_address: str | None = None,
                       num_processes: int | None = None,
                       process_id: int | None = None,
                       strict: bool = False) -> None:
    """Idempotent :func:`jax.distributed.initialize` (auto-detects TPU
    runtime metadata when no arguments are given).  Call before any other
    JAX API in multi-host launches; harmless in single-process runs.

    ``strict=True`` makes initialisation failure fatal — pass it whenever
    the caller *explicitly* asked for multi-host execution (otherwise every
    host silently degrades to an independent single-process run, and a pod
    writes N duplicate result logs).

    Topology resolution order: explicit arguments, then the
    ``REVAL_TPU_COORDINATOR`` / ``REVAL_TPU_NUM_PROCESSES`` /
    ``REVAL_TPU_PROCESS_ID`` environment rig (manual launches outside
    SLURM/TPU-metadata — e.g. `launchers/tpu_vm_fleet.sh` over plain SSH,
    or CPU test rigs), then JAX's own cluster auto-detection.  If the
    embedding process already initialised ``jax.distributed`` itself,
    that is honoured as-is."""
    global _initialized
    if _initialized:
        return
    import jax

    from ..env import env_int, env_str

    if jax.distributed.is_initialized():
        # the embedding process brought up jax.distributed before calling
        # us — a second initialize() would raise; their topology stands
        _initialized = True
        return
    # each field resolves independently: explicit argument, then the
    # declared env rig (reval_tpu/env.py)
    if coordinator_address is None:
        coordinator_address = env_str("REVAL_TPU_COORDINATOR")
    if num_processes is None:
        num_processes = env_int("REVAL_TPU_NUM_PROCESSES")
    if process_id is None:
        process_id = env_int("REVAL_TPU_PROCESS_ID")
    if num_processes == 1:
        _initialized = True
        return
    try:
        # must run before anything touches a JAX backend (so no
        # jax.process_count() probing here); on a plain single-process
        # machine the no-arg call has no coordinator to find and raises —
        # that is the signal to proceed single-process
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except (ValueError, RuntimeError):
        if strict or coordinator_address is not None or num_processes is not None:
            raise  # requested multi-host could not come up: a real error
    _initialized = True


def process_topology() -> tuple[int, int]:
    """(process_index, process_count) — (0, 1) when JAX is single-process."""
    import jax

    return jax.process_index(), jax.process_count()


def is_primary_host() -> bool:
    return process_topology()[0] == 0


def shard_for_host(items: list, index: int | None = None,
                   count: int | None = None) -> tuple[list, int]:
    """Contiguous shard of ``items`` for this host plus its start offset.

    Contiguous (not round-robin) so concatenating the per-host results in
    process order restores the original order exactly.
    """
    if index is None or count is None:
        index, count = process_topology()
    base, extra = divmod(len(items), count)
    start = index * base + min(index, extra)
    size = base + (1 if index < extra else 0)
    return items[start:start + size], start


def gather_strings(local: list[str]) -> list[str]:
    """All-gather variable-length strings across hosts, concatenated in
    process order.  Identity in single-process runs."""
    index, count = process_topology()
    if count == 1:
        return list(local)
    from jax.experimental import multihost_utils

    payload = json.dumps(local).encode()
    # equal shapes are required: gather lengths first, then padded bytes
    lengths = multihost_utils.process_allgather(np.array([len(payload)], np.int64))
    max_len = int(np.max(lengths))
    buf = np.zeros(max_len, np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    out: list[str] = []
    for i in range(count):
        raw = bytes(gathered[i][: int(lengths.reshape(-1)[i])])
        out.extend(json.loads(raw.decode()))
    return out
