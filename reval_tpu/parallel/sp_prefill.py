"""Sequence-parallel prefill: one long prompt spread over the ``sp`` axis.

Long-context is first-class here (the reference has none — SURVEY §5.7):
when a prompt's KV or attention working set outgrows one chip, the
*sequence* dimension shards over the mesh.  Everything except attention
is position-local (norms, projections, MLPs — XLA keeps them sharded over
T from the activation constraint); attention is the one cross-position op
and runs as ring attention (`parallel/ring_attention.py`): KV blocks
rotate around the ``sp`` ring, each hop overlapped with the block compute.

The produced KV cache keeps the sequence dim ``sp``-sharded.  Decode then
works unchanged: `decode_attention`'s score einsum contracts the sharded
S dim, so XLA turns each step into shard-local partial attention + one
psum — distributed decode attention for free, no code fork (the
engine-side sharding constraint is the only sp-specific line).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import ModelConfig
from ..models.model import KVCache, prefill
from .mesh import mesh_axis_sizes
from .ring_attention import ring_attention_sharded
from .sharding import _divisible, kv_cache_spec

__all__ = ["sequence_parallel_prefill", "sp_kv_cache_spec"]


# mesh: axes=(dp, sp, tp)
def sp_kv_cache_spec(cfg: ModelConfig, mesh: Mesh) -> P:
    """[L, B, S, H_kv, D]: the contiguous cache rules (batch over dp, kv
    heads over tp when divisible — ONE policy, defined in
    parallel/sharding.py) with the sequence dim additionally over sp."""
    base = kv_cache_spec(cfg, mesh)
    return P(base[0], base[1], "sp", base[3], base[4])


def sequence_parallel_prefill(params, cfg: ModelConfig, tokens: jnp.ndarray,
                              pad_len: jnp.ndarray, cache: KVCache,
                              mesh: Mesh) -> tuple[jnp.ndarray, KVCache]:
    """Prefill a left-padded [B, T] block with T sharded over ``sp``.

    Same contract as ``models.model.prefill(..., logits_mode="last")``:
    the shared prefill scaffold runs with ring attention injected as the
    ``attend_fn`` and an sp sharding constraint on the activations.
    T must be divisible by the sp axis size.
    """
    sp = mesh_axis_sizes(mesh).get("sp", 1)
    b, t = tokens.shape
    if t % sp:
        raise ValueError(f"prefill length {t} must be divisible by sp={sp}")
    # shard heads over tp inside the ring too (when divisible): without
    # this every tp device would all-gather full-head q/k/v and compute
    # redundant attention, doubling the working set sp exists to shrink
    div = _divisible(cfg, mesh)
    head_axis = ("tp" if mesh_axis_sizes(mesh).get("tp", 1) > 1
                 and div["heads"] and div["kv_heads"] else None)
    # batch stays dp-sharded end to end (replication would run dp-fold
    # redundant prefill)
    # mesh: axes=(dp, sp)
    seq_sharding = NamedSharding(mesh, P("dp", "sp", None))

    def constrain(h):
        # reshard: pin prefill activations (dp, sp)-sharded — without the
        # constraint XLA all-gathers the full T dim at the first norm,
        # exactly the working set sp exists to shrink
        return jax.lax.with_sharding_constraint(h, seq_sharding)

    def attend_fn(q, k, v, win):
        # win: the layer's traced window (sentinel-big = full causal) —
        # uniform-window (mistral) and alternating (gemma-2) models ride
        # the same mask; softcap composes with the ring's online softmax
        return ring_attention_sharded(q, k, v, mesh, pad_len, win,
                                      head_axis=head_axis,
                                      scale=cfg.attn_scale,
                                      softcap=cfg.attn_softcap)

    return prefill(params, cfg, tokens, pad_len, cache, logits_mode="last",
                   attend_fn=attend_fn, constrain=constrain)
