"""Ring attention: sequence-parallel causal attention over an ``sp`` mesh axis.

Long-context prefill support (the reference has no long-context machinery —
SURVEY.md §5.7 — but this framework treats it as first-class): when one
sequence's KV does not fit a single chip's HBM, shard the *sequence*
dimension over the mesh and pass KV blocks around the ring, overlapping
each hop with the attention compute for the block already in hand.

Design (blockwise/ring formulation, written for XLA collectives):
- runs inside :func:`jax.shard_map` over the ``sp`` axis; every device
  holds ``[B, T/sp, H, D]`` of q, k, v;
- ``sp`` static steps: compute online-softmax partial attention of the
  local q block against the currently-held KV block, then rotate the KV
  block to the next device with ``lax.ppermute`` (XLA schedules the
  permute on ICI concurrently with the next block's compute);
- causality is enforced with *global* positions (block index × block
  length + local offset), so each step is one uniform masked matmul — no
  per-device control flow, fully MXU-shaped.

The same kernel body also runs un-sharded (``axis_name=None``) which is
what the parity tests compare against ``prefill_attention``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import compat_shard_map

__all__ = ["ring_self_attention", "ring_attention_sharded"]

_NEG_INF = -1e30


def _block_update(q, k, v, q_pos, k_pos, m, l, acc, scale, pad_len=None,
                  window=None, softcap=None):
    """One online-softmax accumulation of q against a KV block.

    q: [B, Tq, H_kv, G, D]; k/v: [B, Tk, H_kv, D]; positions: [Tq]/[Tk];
    m/l: [B, H_kv, G, Tq, 1]; acc: [B, Tq, H_kv, G, D].

    ``pad_len`` [B]: left-pad counts.  Padding shifts query and key
    positions equally, so the causal comparison is pad-invariant in
    buffer coordinates — only pad KEYS need masking out.  The same
    shift-invariance makes the sliding ``window`` mask (a position
    DIFFERENCE bound, traced per layer) exact across ring blocks, and
    ``softcap`` is pointwise on scores so it composes with the online
    softmax unchanged — ordering matches ops/attention.prefill_attention:
    scale → softcap → mask.
    """
    scores = jnp.einsum("bqngd,bknd->bngqk", q, k) * scale
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None]
    if window is not None:
        in_window = (q_pos[:, None] - k_pos[None, :]) < window
        mask = mask & in_window[None, None, None]
    if pad_len is not None:
        valid_key = k_pos[None, :] >= pad_len[:, None]     # [B, Tk]
        mask = mask & valid_key[:, None, None, None, :]
    scores = jnp.where(mask, scores, _NEG_INF)
    m_cur = scores.max(axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    # m stays -inf for fully-masked rows (no valid key yet): keep exp at 0
    alpha = jnp.exp(jnp.where(m == _NEG_INF, _NEG_INF, m - m_new))
    probs = jnp.exp(scores - m_new)
    l_new = alpha * l + probs.sum(axis=-1, keepdims=True)
    upd = jnp.einsum("bngqk,bknd->bqngd", probs, v)
    acc_new = acc * alpha.transpose(0, 3, 1, 2, 4) + upd
    return m_new, l_new, acc_new


# mesh: axes=(sp) via=(axis_name)
def _ring_body(q, k, v, pad_len, window=None, *, axis_name: str | None,
               axis_size: int, scale, softcap=None):
    """Local ring-attention body.  q: [B, Tl, H, D]; k/v: [B, Tl, H_kv, D];
    pad_len: [B] or None; window: traced scalar (sentinel-big = full
    causal) or None."""
    b, t_loc, h, d = q.shape
    n_kv = k.shape[2]
    g = h // n_kv
    idx = jax.lax.axis_index(axis_name) if axis_name else 0

    qg = q.reshape(b, t_loc, n_kv, g, d).astype(jnp.float32)
    offs = jnp.arange(t_loc)
    q_pos = idx * t_loc + offs

    m = jnp.full((b, n_kv, g, t_loc, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, n_kv, g, t_loc, 1), jnp.float32)
    acc = jnp.zeros((b, t_loc, n_kv, g, d), jnp.float32)

    for step in range(axis_size):
        # after `step` rotations we hold the block that started on idx-step
        block = (idx - step) % axis_size
        k_pos = block * t_loc + offs
        # cast per block at compute time: KV rotates in its source dtype so
        # bf16 caches move half the bytes per ICI hop
        m, l, acc = _block_update(qg, k.astype(jnp.float32),
                                  v.astype(jnp.float32), q_pos, k_pos,
                                  m, l, acc, scale, pad_len=pad_len,
                                  window=window, softcap=softcap)
        if axis_name is not None and step + 1 < axis_size:
            perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    # rows with no valid key (impossible for causal q_pos>=0) guard anyway
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2, 4)
    return out.reshape(b, t_loc, h, d).astype(q.dtype)


def ring_self_attention(q, k, v, pad_len=None, window=None, *,
                        axis_name: str | None = None,
                        axis_size: int = 1, scale: float | None = None,
                        softcap: float | None = None):
    """Causal self-attention with ring-rotated KV blocks.

    Call inside ``shard_map`` with ``axis_name`` set (q/k/v are the local
    sequence shards), or stand-alone with ``axis_name=None`` for the
    single-device reference semantics.  Shard layout is contiguous
    (device i holds positions [i·Tl, (i+1)·Tl)); ``pad_len`` [B] marks
    left-padding (pad keys masked; causality is pad-invariant).

    ``window``: sliding-window size (traced scalar ok — gemma-2
    alternates per layer, sentinel-big = full causal); ``softcap``:
    gemma-2 attention-score softcapping.  Semantics match
    ``ops.attention.prefill_attention`` exactly.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    return _ring_body(q, k, v, pad_len, window, axis_name=axis_name,
                      axis_size=axis_size, scale=scale, softcap=softcap)


# mesh: axes=(dp, sp, tp) via=(sp_axis, head_axis, batch_axis)
def ring_attention_sharded(q, k, v, mesh: Mesh, pad_len=None, window=None, *,
                           sp_axis: str = "sp", head_axis: str | None = None,
                           batch_axis: str | None = "dp",
                           scale: float | None = None,
                           softcap: float | None = None):
    """Shard ``q, k, v`` ([B, T, H, D], T divisible by the ``sp`` axis
    size) over the sequence dimension and run ring attention.

    The returned array is sequence-sharded on the same axis; callers
    under ``jit`` can keep computing on it shard-local (norms/MLPs are
    elementwise over T) so the full sequence never materialises on one
    device.  ``head_axis`` additionally shards the head dim (attention is
    head-local, so this is free parallelism — pass "tp" when it divides
    both H and H_kv; GQA group blocks stay contiguous per shard), and
    ``batch_axis`` keeps the batch dim data-parallel (attention is
    batch-local too — replicating it would run dp-fold redundant rings).
    """
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))[sp_axis]
    t = q.shape[1]
    if t % axis_size:
        raise ValueError(f"sequence length {t} not divisible by sp={axis_size}")
    if batch_axis is not None and batch_axis not in mesh.axis_names:
        batch_axis = None
    body = partial(ring_self_attention, axis_name=sp_axis,
                   axis_size=axis_size, scale=scale, softcap=softcap)
    spec = P(batch_axis, sp_axis, head_axis, None)
    args, specs = [q, k, v], [spec, spec, spec]
    if pad_len is not None or window is not None:
        # pad_len rides along whenever window does (positional order);
        # zeros = "no padding", the masks it produces are no-ops
        args.append(pad_len if pad_len is not None
                    else jnp.zeros(q.shape[0], jnp.int32))
        specs.append(P(batch_axis))
    if window is not None:
        # traced per-layer scalar (gemma-2 alternates): replicated operand,
        # not a closure — shard_map wants traced values as explicit args
        args.append(jnp.asarray(window))
        specs.append(P())
    # jit-entry: ring.attn_shard bucketed=(rows, tokens)
    # mesh: axes=(dp, sp, tp) in=(dynamic) out=(dynamic)
    return compat_shard_map(
        body, mesh=mesh, in_specs=tuple(specs),
        out_specs=spec, check_vma=False)(*args)
