"""Device mesh construction + multi-host initialisation.

Axes: ``dp`` (data parallel — prompt batches), ``tp`` (tensor parallel —
heads/ffn), optional ``sp`` (sequence parallel — ring attention).  On a
TPU slice the mesh should be built so ``tp`` rides the fastest ICI links;
``jax.devices()`` order already follows the physical torus for v4/v5 — we
keep device order and reshape, which maps tp to adjacent chips.

Multi-host (pods / multi-slice): call :func:`init_distributed` once per
process before any other JAX call; ``jax.devices()`` then spans the whole
pod and the same mesh construction works unchanged — DCN-crossing axes
should be the outermost (dp) axis.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "init_distributed", "mesh_axis_sizes"]


def make_mesh(tp: int = 1, dp: int = 1, sp: int = 1, pp: int = 1, ep: int = 1,
              devices=None) -> Mesh:
    """Build a ``(dp, pp, sp, ep, tp)`` mesh (singleton axes are kept —
    named axes must exist for the sharding rules to reference them).

    Axis order puts the heaviest-traffic axes innermost (fastest ICI
    links): ``tp`` exchanges activations every layer, ``ep`` all-to-alls
    tokens every MoE block, ``sp`` ring-passes KV blocks, while ``pp``
    moves one activation per microbatch tick and ``dp`` only syncs at
    boundaries — those two can ride slower links (or DCN multi-host)."""
    devices = list(devices if devices is not None else jax.devices())
    need = tp * dp * sp * pp * ep
    if len(devices) < need:
        raise ValueError(f"mesh needs {need} devices (tp={tp} dp={dp} sp={sp} "
                         f"pp={pp} ep={ep}), have {len(devices)}")
    arr = np.array(devices[:need]).reshape(dp, pp, sp, ep, tp)
    return Mesh(arr, ("dp", "pp", "sp", "ep", "tp"))


def mesh_axis_sizes(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Initialise multi-host JAX (pods, multi-slice over DCN).

    With TPU metadata available all arguments are auto-detected; explicit
    values support manual rigs.  Safe to call more than once per process —
    this is the same idempotent entry point as
    :func:`reval_tpu.parallel.distributed.ensure_initialized`, in strict
    mode: calling it is an explicit request for multi-host, so failure to
    bring up the coordinator raises instead of silently degrading.
    """
    from .distributed import ensure_initialized

    ensure_initialized(coordinator_address=coordinator_address,
                       num_processes=num_processes,
                       process_id=process_id, strict=True)
