"""Device mesh construction + multi-host initialisation.

Axes: ``dp`` (data parallel — prompt batches), ``tp`` (tensor parallel —
heads/ffn), optional ``sp`` (sequence parallel — ring attention).  On a
TPU slice the mesh should be built so ``tp`` rides the fastest ICI links;
``jax.devices()`` order already follows the physical torus for v4/v5 — we
keep device order and reshape, which maps tp to adjacent chips.

Multi-host (pods / multi-slice): call :func:`init_distributed` once per
process before any other JAX call; ``jax.devices()`` then spans the whole
pod and the same mesh construction works unchanged — DCN-crossing axes
should be the outermost (dp) axis.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["AXES", "make_mesh", "init_distributed", "mesh_axis_sizes",
           "compat_shard_map"]

#: The canonical mesh-axis namespace: name -> one-line meaning, in mesh
#: axis order (outermost/slowest links first).  This dict is THE
#: registry the ``mesh`` lint pass (analysis/meshreg.py) resolves every
#: ``# mesh: axes=(...)`` contract against — an axis name used in a
#: ``PartitionSpec``/collective that is not declared here is a lint
#: violation, not a runtime XLA "unbound axis name" error.  Keep it a
#: PURE LITERAL: the pass reads it from the AST (lint stays jax-free).
AXES: dict[str, str] = {
    "dp": "data parallel — prompt batches; syncs only at boundaries, "
          "may ride DCN multi-host",
    "pp": "pipeline parallel — contiguous layer stages; one activation "
          "per microbatch tick",
    "sp": "sequence parallel — ring attention over sequence blocks",
    "ep": "expert parallel — MoE expert shards (all-to-all per block)",
    "tp": "tensor parallel — heads/ffn/vocab; heaviest traffic, "
          "innermost (fastest ICI)",
}


def make_mesh(tp: int = 1, dp: int = 1, sp: int = 1, pp: int = 1, ep: int = 1,
              devices=None) -> Mesh:
    """Build a ``(dp, pp, sp, ep, tp)`` mesh (singleton axes are kept —
    named axes must exist for the sharding rules to reference them).

    Axis order puts the heaviest-traffic axes innermost (fastest ICI
    links): ``tp`` exchanges activations every layer, ``ep`` all-to-alls
    tokens every MoE block, ``sp`` ring-passes KV blocks, while ``pp``
    moves one activation per microbatch tick and ``dp`` only syncs at
    boundaries — those two can ride slower links (or DCN multi-host)."""
    devices = list(devices if devices is not None else jax.devices())
    need = tp * dp * sp * pp * ep
    if len(devices) < need:
        raise ValueError(f"mesh needs {need} devices (tp={tp} dp={dp} sp={sp} "
                         f"pp={pp} ep={ep}), have {len(devices)}")
    arr = np.array(devices[:need]).reshape(dp, pp, sp, ep, tp)
    # mesh: axes=(dp, pp, sp, ep, tp)
    return Mesh(arr, tuple(AXES))


def compat_shard_map(f, *, mesh: Mesh, in_specs, out_specs,
                     axis_names=None, check_vma: bool = True):
    """``jax.shard_map`` across jax generations — the ONE compat shim.

    jax >= 0.6 spells the API ``jax.shard_map(..., axis_names=...,
    check_vma=...)``; jax 0.4.x spells it
    ``jax.experimental.shard_map.shard_map`` where the replication
    checker is ``check_rep`` and partial-manual regions are expressed as
    ``auto`` (the COMPLEMENT of ``axis_names``).  Every shard_map in the
    tree routes through here (models/paged.py carried a private copy of
    this branch since PR 2 while the pp/sp ring paths called
    ``jax.shard_map`` directly and were env-broken on 0.4.x hosts).

    ``axis_names``: the axes the region is manual over (None = all mesh
    axes, the jax default).  0.4.x raises ``NotImplementedError`` on
    real partial-manual (``auto``) programs, so there a partial request
    degrades to manual over ALL axes: the specs still place only the
    named axes, every other axis is replicated at region entry —
    correct, at worst redundant compute on multi-axis meshes — and the
    replication checker goes off (it would demand the ``lax.pcast``
    varying-marking the 0.4 API lacks)."""
    new_sm = getattr(jax, "shard_map", None)
    if new_sm is not None:
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return new_sm(f, **kwargs)
    # jax 0.4.x: check_rep is the same replication checker check_vma
    # renamed
    from jax.experimental.shard_map import shard_map as _sm04

    return _sm04(f, mesh, in_specs=in_specs, out_specs=out_specs,
                 check_rep=False if axis_names is not None else check_vma)


def mesh_axis_sizes(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Initialise multi-host JAX (pods, multi-slice over DCN).

    With TPU metadata available all arguments are auto-detected; explicit
    values support manual rigs.  Safe to call more than once per process —
    this is the same idempotent entry point as
    :func:`reval_tpu.parallel.distributed.ensure_initialized`, in strict
    mode: calling it is an explicit request for multi-host, so failure to
    bring up the coordinator raises instead of silently degrading.
    """
    from .distributed import ensure_initialized

    ensure_initialized(coordinator_address=coordinator_address,
                       num_processes=num_processes,
                       process_id=process_id, strict=True)
