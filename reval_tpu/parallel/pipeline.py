"""Pipeline parallelism (``pp`` mesh axis): GPipe microbatching over layers.

The reference has no pipeline parallelism to port (its largest configs ride
vLLM tensor parallelism, reference inference.py:92); this is the TPU-native
answer to the same scaling problem for models whose layer stack does not fit
one chip's HBM even sharded tp-wide (BASELINE.json configs[4]: CodeLlama-70B
on v5p-16, where tp=16 would waste ICI on 70B's modest head count — pp=2/4
over DCN-adjacent hosts keeps tp inside each host).

Design (TPU-first):
- The params pytree already stacks every per-layer weight as ``[L, ...]``
  (models/model.py), so a pipeline stage is nothing more than sharding the
  leading layer dim over the ``pp`` mesh axis: stage ``s`` holds layers
  ``[s*L/P, (s+1)*L/P)``.  No parameter surgery, no per-stage module types.
- The schedule runs inside one ``jax.shard_map`` over ``pp`` (other mesh
  axes stay automatic, so tp sharding composes): every tick, each stage
  scans its local layers over its current microbatch and ``ppermute``s the
  activation to the next stage.  Data-dependent stage behaviour (pipeline
  fill/drain) is expressed with clamped indices + scratch slots, not Python
  control flow — everything jits to one XLA while loop.
- **Prefill** is GPipe: ``M >= P`` microbatches, bubble fraction
  ``(P-1)/(M+P-1)``.  KV writes land in the stage-local shard of the cache
  (the cache's layer dim is ``pp``-sharded too, so cache traffic never
  crosses stages).
- **Decode** is a token ring: exactly ``M = P`` microbatches in flight, one
  per stage; the last stage samples the next token, embeds it, and the ring
  ``ppermute`` returns it to stage 0 — after the ``P``-tick fill, every
  stage is busy every tick (no steady-state bubble), and a chunk of
  ``steps`` tokens costs ``steps*P + P - 1`` ticks of ``1/P`` of the model
  each.

Scratch-slot convention: inactive (fill/drain) ticks write to dedicated
scratch rows — batch row ``B`` (the cache carries ``B + mb`` rows) and
output slot ``M`` — so no ``where``-select over whole cache buffers is
needed and XLA keeps the real writes in place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import ModelConfig
from ..models.model import KVCache, _block, _embed, _norm, _unembed
from ..ops import decode_attention, prefill_attention, rope_angles
from .mesh import compat_shard_map, mesh_axis_sizes
from .sharding import param_specs

__all__ = ["pp_param_specs", "shard_params_pp", "pipeline_prefill",
           "pipeline_decode_chunk", "pp_size"]


def pp_size(mesh: Mesh) -> int:
    return mesh_axis_sizes(mesh).get("pp", 1)


# mesh: axes=(pp)
def pp_param_specs(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    """The tp/replication rules of ``parallel.sharding`` with the stacked
    layer dim additionally sharded over ``pp`` (stage = contiguous block of
    layers).  Top-level leaves (embed/lm_head/final norm) replicate across
    stages: the first stage needs the embedding, the last stage needs the
    head, and both are small next to the layer stack."""
    specs = param_specs(params, cfg, mesh)
    pp = pp_size(mesh)
    if pp == 1:
        return specs
    if cfg.num_layers % pp:
        raise ValueError(f"pp={pp} must evenly divide num_layers={cfg.num_layers}")
    specs["layers"] = {
        name: P("pp", *spec[1:]) for name, spec in specs["layers"].items()
    }
    return specs


# mesh: axes=()
def shard_params_pp(params: dict, cfg: ModelConfig, mesh: Mesh) -> dict:
    specs = pp_param_specs(params, cfg, mesh)
    if jax.default_backend() == "cpu":
        # XLA:CPU check-fails ("Invalid binary instruction opcode copy",
        # hlo_instruction.cc) compiling bf16 dots inside this module's
        # nested while loops (scan-over-layers inside the GPipe fori_loop
        # inside shard_map) — the same dots compile fine under plain jit
        # (the static/paged engines run bf16 on CPU), so this is
        # pp-program-specific; reduced toys hit either this fatal or
        # "UNIMPLEMENTED: unsupported operand type BF16 in op dot".  On
        # the CPU backend (virtual-mesh validation only) run the pp
        # engine in f32: upcast bf16 leaves, which makes the activations
        # (and KV cache dtype, derived from embed) f32 too.  s4 weight
        # stacks are unaffected and stay s4.
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x,
            params)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, specs,
        is_leaf=lambda x: not isinstance(x, dict),
    )


def _ring(n: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % n) for i in range(n)]


# mesh: axes=(pp) via=(axis)
def _varying(x, axis: str = "pp"):
    """Mark a replicated value as device-varying over ``axis`` so it can
    seed a loop carry whose body output is varying (shard_map VMA rule).
    jax 0.4.x has no ``lax.pcast`` — there the compat shard_map runs
    with the replication checker off (partial-manual forces it), so the
    marking is unnecessary and the value passes through unchanged."""
    if not hasattr(lax, "pcast"):
        return x
    return lax.pcast(x, (axis,), to="varying")


def _run_local_layers_prefill(h, layers, wins, pad, cfg, kv_dtype):
    """Scan this stage's layers over one left-padded microbatch block;
    returns the block output and the stage-local KV ([Lp, mb, T, H_kv, D]).
    ``wins``: [Lp] per-layer window sizes (sentinel-big = global) — the
    stage's slice of the model-wide array, so gemma-2 window alternation
    follows global layer indices across stages."""
    t = h.shape[1]
    positions = jnp.maximum(jnp.arange(t)[None, :] - pad[:, None], 0)
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

    def layer_step(hc, xs):
        layer, win = xs
        kv = {}

        def attend(q, k, v):
            kv["k"], kv["v"] = k.astype(kv_dtype), v.astype(kv_dtype)
            return prefill_attention(q, k, v, pad, scale=cfg.attn_scale,
                                     window=win, softcap=cfg.attn_softcap)

        hc = _block(hc, layer, cfg, cos, sin, attend)
        return hc, (kv["k"], kv["v"])

    return lax.scan(layer_step, h, (layers, wins))


# mesh: axes=(pp)
def pipeline_prefill(params, cfg: ModelConfig, tokens: jnp.ndarray,
                     pad_len: jnp.ndarray, cache: KVCache, mesh: Mesh,
                     n_micro: int) -> tuple[jnp.ndarray, KVCache]:
    """GPipe prefill of a left-padded [B, T] block over the ``pp`` axis.

    ``cache`` must carry ``B + B//n_micro`` batch rows (the tail rows are
    the fill/drain scratch — see module docstring); rows ``[0, B)`` come
    back filled at positions ``[0, T)``.  Returns last-position logits
    ``[B, 1, V]`` (the only logits generation needs) and the cache.
    """
    pp = pp_size(mesh)
    b, t = tokens.shape
    m_count = n_micro
    if b % m_count:
        raise ValueError(f"batch {b} must divide into n_micro={m_count}")
    mb = b // m_count
    if m_count < pp:
        raise ValueError(f"n_micro={m_count} must be >= pp={pp}")

    h = _embed(params, cfg, tokens)
    hm = h.reshape(m_count, mb, t, h.shape[-1])
    padm = pad_len.reshape(m_count, mb)
    layers = params["layers"]
    top = {k: v for k, v in params.items() if k != "layers"}
    wins = cfg.layer_windows_array()

    def staged(layers, wins, hm, padm, ck, cv):
        stage = lax.axis_index("pp")

        def tick(ti, state):
            h_cur, ck, cv, outbuf = state
            m = ti - stage
            active = (m >= 0) & (m < m_count)
            mc = jnp.clip(m, 0, m_count - 1)
            h_in = jnp.where(stage == 0,
                             lax.dynamic_index_in_dim(hm, mc, 0, keepdims=False),
                             h_cur)
            pad = lax.dynamic_index_in_dim(padm, mc, 0, keepdims=False)
            h_out, (ks, vs) = _run_local_layers_prefill(
                h_in, layers, wins, pad, cfg, ck.dtype)
            row = jnp.where(active, mc * mb, b)
            ck = lax.dynamic_update_slice(ck, ks, (0, row, 0, 0, 0))
            cv = lax.dynamic_update_slice(cv, vs, (0, row, 0, 0, 0))
            # left-padding puts every row's final prompt token last
            h_last = h_out[:, -1, :]
            is_out = active & (stage == pp - 1)
            val = jnp.where(stage == pp - 1, h_last, jnp.zeros_like(h_last))
            outbuf = lax.dynamic_update_slice(
                outbuf, val[None], (jnp.where(is_out, mc, m_count), 0, 0))
            h_next = lax.ppermute(h_out, "pp", _ring(pp))
            return (h_next, ck, cv, outbuf)

        h0 = _varying(jnp.zeros_like(hm[0]))
        outbuf = _varying(jnp.zeros((m_count + 1, mb, hm.shape[-1]), hm.dtype))
        _, ck, cv, outbuf = lax.fori_loop(
            0, m_count + pp - 1, tick, (h0, ck, cv, outbuf))
        return lax.psum(outbuf[:m_count], "pp"), ck, cv

    # jit-entry: pp.prefill_stage bucketed=(rows, tokens)
    # mesh: axes=(pp) in=(P(pp), P(pp), P(), P(), P(pp), P(pp)) out=(P(), P(pp), P(pp))
    outbuf, ck, cv = compat_shard_map(
        staged, mesh=mesh, axis_names=("pp",),
        in_specs=(P("pp"), P("pp"), P(), P(), P("pp"), P("pp")),
        out_specs=(P(), P("pp"), P("pp")),
    )(layers, wins, hm, padm, cache.k, cache.v)

    h_final = _norm(outbuf.reshape(b, -1), top["final_norm_w"],
                    top.get("final_norm_b"), cfg)
    logits = _unembed(top, cfg, h_final)
    return logits[:, None, :], KVCache(ck, cv)


# mesh: axes=(pp)
def pipeline_decode_chunk(params, cfg: ModelConfig, first_token: jnp.ndarray,
                          pad_len: jnp.ndarray, cache: KVCache,
                          start_pos: jnp.ndarray, temperature, key,
                          mesh: Mesh, *, steps: int,
                          top_k: jnp.ndarray | None = None,
                          top_p: jnp.ndarray | None = None,
                          filtered: bool = False):
    """Token-ring decode: ``steps`` tokens for every row of [B, 1]
    ``first_token`` (engine-chunk contract: returns ``(toks [B, steps],
    cache, last [B, 1])``).

    ``M = P`` microbatches circulate; the last stage samples microbatch
    ``m``'s next token, embeds it, and the ring permute hands it straight
    back to stage 0 one tick later — zero steady-state bubble.

    ``filtered`` (static) compiles the top-k/nucleus logits filter into
    the last stage's sampling; ``top_k``/``top_p`` are per-row [B]
    arrays (ignored when ``filtered`` is False, so default chunks carry
    no [mb, V] sort).
    """
    # function-local so ``reval_tpu.parallel`` (a models-layer dependency)
    # never imports the inference package at module load
    from ..inference.tpu.sampling import filter_logits, sample_token

    pp = pp_size(mesh)
    b = first_token.shape[0]
    if b % pp:
        raise ValueError(f"batch {b} must divide into pp={pp} ring microbatches")
    mb = b // pp
    n_total = steps * pp

    emb_first = _embed(params, cfg, first_token)       # [B, 1, D]
    hm = emb_first.reshape(pp, mb, 1, emb_first.shape[-1])
    padm = pad_len.reshape(pp, mb)
    if top_k is None:
        top_k = jnp.zeros((b,), jnp.int32)
    if top_p is None:
        top_p = jnp.ones((b,), jnp.float32)
    kfm = jnp.asarray(top_k, jnp.int32).reshape(pp, mb)
    pfm = jnp.asarray(top_p, jnp.float32).reshape(pp, mb)
    layers = params["layers"]
    top = {k: v for k, v in params.items() if k != "layers"}
    wins = cfg.layer_windows_array()

    def staged(layers, wins, top, hm, padm, kfm, pfm, ck, cv):
        stage = lax.axis_index("pp")
        lp = jax.tree_util.tree_leaves(layers)[0].shape[0]
        s_max = ck.shape[2]

        def tick(ti, state):
            h_cur, ck, cv, tokbuf = state
            n = ti - stage
            active = (n >= 0) & (n < n_total)
            nc = jnp.clip(n, 0, n_total - 1)
            m = nc % pp
            j = nc // pp
            h_in = jnp.where(
                (stage == 0) & (j == 0),
                lax.dynamic_index_in_dim(hm, m, 0, keepdims=False), h_cur)
            pad = lax.dynamic_index_in_dim(padm, m, 0, keepdims=False)
            row = jnp.where(active, m * mb, b)
            pos = start_pos + j
            positions = jnp.maximum(pos - pad, 0)[:, None]
            cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

            # unrolled over the stage's layers with STATIC layer indices —
            # the same choice as decode_step (models/model.py): scanning
            # with the cache in carry defeats in-place updates
            h_out = h_in
            for li in range(lp):
                layer = jax.tree_util.tree_map(lambda x: x[li], layers)

                def attend(q, k, v, li=li):
                    nonlocal ck, cv
                    ck = lax.dynamic_update_slice(
                        ck, k[None].astype(ck.dtype), (li, row, pos, 0, 0))
                    cv = lax.dynamic_update_slice(
                        cv, v[None].astype(cv.dtype), (li, row, pos, 0, 0))
                    kc = lax.dynamic_slice(
                        ck, (li, row, 0, 0, 0),
                        (1, mb, s_max, ck.shape[3], ck.shape[4]))[0]
                    vc = lax.dynamic_slice(
                        cv, (li, row, 0, 0, 0),
                        (1, mb, s_max, cv.shape[3], cv.shape[4]))[0]
                    return decode_attention(q, kc, vc, pad, pos,
                                            scale=cfg.attn_scale,
                                            window=wins[li],
                                            softcap=cfg.attn_softcap)

                h_out = _block(h_out, layer, cfg, cos, sin, attend)

            def sample_and_embed(h_out):
                hf = _norm(h_out[:, 0, :], top["final_norm_w"],
                           top.get("final_norm_b"), cfg)
                logits = _unembed(top, cfg, hf)
                if filtered:   # static: default chunks carry no [mb, V] sort
                    kfj = lax.dynamic_index_in_dim(kfm, m, 0, keepdims=False)
                    pfj = lax.dynamic_index_in_dim(pfm, m, 0, keepdims=False)
                    logits = filter_logits(logits, kfj, pfj, temperature)
                tok = sample_token(logits, temperature,
                                   jax.random.fold_in(key, nc))
                return tok.astype(jnp.int32), _embed(
                    top, cfg, tok[:, None]).astype(h_out.dtype)

            def passthrough(h_out):
                return (_varying(jnp.zeros((mb,), jnp.int32)), h_out)

            tok, h_ring = lax.cond(stage == pp - 1, sample_and_embed,
                                   passthrough, h_out)
            is_out = active & (stage == pp - 1)
            tokbuf = lax.dynamic_update_slice(
                tokbuf, tok[None], (jnp.where(is_out, nc, n_total), 0))
            h_next = lax.ppermute(h_ring, "pp", _ring(pp))
            return (h_next, ck, cv, tokbuf)

        h0 = _varying(jnp.zeros_like(hm[0]))
        tokbuf = _varying(jnp.zeros((n_total + 1, mb), jnp.int32))
        _, ck, cv, tokbuf = lax.fori_loop(
            0, n_total + pp - 1, tick, (h0, ck, cv, tokbuf))
        return lax.psum(tokbuf[:n_total], "pp"), ck, cv

    # jit-entry: pp.decode_stage bucketed=(rows, steps)
    # mesh: axes=(pp) in=(P(pp), P(pp), P(), P(), P(), P(), P(), P(pp), P(pp)) out=(P(), P(pp), P(pp))
    tokbuf, ck, cv = compat_shard_map(
        staged, mesh=mesh, axis_names=("pp",),
        in_specs=(P("pp"), P("pp"), P(), P(), P(), P(), P(), P("pp"),
                  P("pp")),
        out_specs=(P(), P("pp"), P("pp")),
    )(layers, wins, top, hm, padm, kfm, pfm, cache.k, cache.v)

    # tokbuf flat index n = j*P + m holds step j of microbatch m
    toks = tokbuf.reshape(steps, pp, mb).transpose(1, 2, 0).reshape(b, steps)
    return toks, KVCache(ck, cv), toks[:, -1:]
