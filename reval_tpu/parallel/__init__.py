"""Parallelism: meshes, sharding rules, multi-host init, and the four
model-sharding strategies beyond plain dp.

The TPU analogue of the reference's delegated tensor parallelism
(``tensor_parallel_size`` handed to vLLM/NCCL, SURVEY §2.8): here sharding
is first-class — a ``Mesh`` with named axes ``('dp', 'pp', 'sp', 'ep',
'tp')``, ``NamedSharding`` rules per weight, and XLA-inserted collectives.
``tp``: Megatron-style rules (sharding.py).  ``pp``: GPipe prefill +
token-ring decode over the stacked layer dim (pipeline.py).  ``sp``:
ring-attention prefill with a sequence-sharded KV cache (ring_attention.py,
sp_prefill.py).  ``ep``: MoE expert sharding (sharding.py + the dispatch
formulation in models/model.py).  No NCCL analogue exists to manage: the
compiler inserts the communication.
"""

from .mesh import make_mesh, init_distributed, mesh_axis_sizes
from .sharding import param_specs, shard_params, batch_sharding, paged_cache_spec
from .ring_attention import ring_self_attention, ring_attention_sharded
from .pipeline import (
    pipeline_decode_chunk,
    pipeline_prefill,
    pp_param_specs,
    shard_params_pp,
)

__all__ = [
    "batch_sharding",
    "init_distributed",
    "make_mesh",
    "mesh_axis_sizes",
    "paged_cache_spec",
    "param_specs",
    "pipeline_decode_chunk",
    "pipeline_prefill",
    "pp_param_specs",
    "ring_attention_sharded",
    "ring_self_attention",
    "shard_params",
    "shard_params_pp",
]
