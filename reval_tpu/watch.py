"""`reval_tpu watch`: a live one-screen console over a serving endpoint.

The Python sibling of ``tools/tpu_watch.sh`` (which babysits the *chip*
through a flaky tunnel): this one babysits the *server*.  It polls
``GET /statusz`` (merged metrics + readiness) and ``GET /debugz`` (the
live postmortem bundle: flight-record tail, in-flight request table,
recent structured-log events) and renders one refreshing screen:

    throughput (req/s, tok/s from counter deltas) · queue depth ·
    page pool (free/cached/pinned from the newest flight record) ·
    latency percentiles (ttft/e2e/queue-wait, THE shared estimator) ·
    lifecycle counters · last faults (error/warning log events)

Read-only: two GETs per refresh, no state server-side.  A refresh
against a down/unready server renders a waiting banner and keeps
polling — the console is exactly for watching a server come up, drain,
or die.

Pointing it at a **fleet router** (``reval_tpu router``) works too: the
router's ``/statusz`` carries ``"router": true``, and the console
switches to the federated fleet view — per-replica health
(healthy/ejected/half-open, ready, in-flight forwards, strikes, last
error), fleet request rate and routing counters from the router's own
registry, and the hash-ring/affinity placement.  The router serves no
``/debugz`` (it owns no engine), so that fetch is skipped.

The router view is also the **fleet-load console**: a goodput row
(arrival rate, deadline-met vs SLO-miss counters, e2e attainment
against ``--slo-e2e`` via the shared CDF estimator), one row per tenant
(requests, shed rate per interval, e2e p95 from the router's labeled
histograms), and the tail of the router's admin action log — which is
where a live autoscaler's add/drain/remove story shows up, each entry
carrying the reason the autoscaler sent.

Usage::

    python -m reval_tpu watch [--host H] [--port P] [--interval S]
                              [--iterations N] [--no-clear]
                              [--slo-e2e S]

``--iterations`` bounds the refresh count (smoke tests; default:
forever, Ctrl-C exits cleanly).
"""

from __future__ import annotations

import argparse
import json
import re
import time
import urllib.error
import urllib.request

from .obs import metrics as obs_metrics
from .obs.metrics import snapshot_fraction_le, snapshot_percentile

_TENANT_LABEL_RE = re.compile(r'\{tenant="([^"]+)"\}')

__all__ = ["run_watch", "render_screen", "render_router_screen"]

CLEAR = "\x1b[H\x1b[2J"

#: (label, histogram metric) rows of the latency block
_LATENCY_ROWS = (("queue_wait", obs_metrics.QUEUE_WAIT),
                 ("ttft", obs_metrics.TTFT),
                 ("tpot", obs_metrics.TPOT),
                 ("e2e", obs_metrics.E2E))

#: counters whose per-interval RATE headlines the screen
_RATE_ROWS = (("req/s", obs_metrics.REQUESTS),
              ("gen tok/s", "reval_engine_generated_tokens_total"),
              ("prefill tok/s", "reval_engine_prefill_tokens_total"))

_SERVING_COUNTERS = ("reval_serving_sheds_total",
                     "reval_serving_deadline_expired_total",
                     "reval_serving_watchdog_trips_total",
                     "reval_http_requests_total")


def _fetch_json(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _fmt_s(v: float) -> str:
    return f"{v:.3f}s" if v >= 1.0 else f"{v * 1e3:.1f}ms"


def _rates(counters: dict, prev: dict | None, dt: float) -> list[str]:
    out = []
    for label, name in _RATE_ROWS:
        cur = counters.get(name, 0)
        if prev is None or dt <= 0:
            out.append(f"{label} —")
        else:
            out.append(f"{label} {max(0.0, (cur - prev.get(name, 0)) / dt):.1f}")
    return out


def render_screen(status: dict, debug: dict, prev_counters: dict | None,
                  dt: float, target: str) -> str:
    """One screenful from a /statusz body + a /debugz bundle."""
    metrics = status.get("metrics", {})
    counters = metrics.get("counters", {})
    gauges = metrics.get("gauges", {})
    hists = metrics.get("histograms", {})
    readiness = status.get("readiness", {}) or {}
    lines = []
    state = ("DRAINING" if status.get("draining")
             else "READY" if readiness.get("ready")
             else "WARMING" if readiness.get("warming") else "UNREADY")
    lines.append(f"== reval_tpu watch · {target} · "
                 f"{status.get('model', '?')} · {state} · "
                 f"{time.strftime('%H:%M:%S')} ==")
    lines.append("throughput   " + "  ".join(_rates(counters, prev_counters,
                                                    dt)))
    lines.append(f"totals       requests {counters.get(obs_metrics.REQUESTS, 0)}"
                 f"  prompts {counters.get('reval_engine_prompts_total', 0)}"
                 f"  gen tokens "
                 f"{counters.get('reval_engine_generated_tokens_total', 0)}")

    # queue / pool: the session gauge plus the newest flight record's view
    flight = debug.get("flight") or []
    for replica in debug.get("replicas", ()):   # dp: first replica's tail
        flight = replica.get("flight") or flight
        break
    last = flight[-1] if flight else {}
    lines.append(
        f"queue        queued_tokens {int(gauges.get(obs_metrics.QUEUED_TOKENS, 0))}"
        f"  inflight {len(debug.get('inflight') or [])}"
        f"  running {last.get('running', '—')}"
        f"  waiting {last.get('queued', '—')}")
    lines.append(
        f"page pool    free {int(gauges.get(obs_metrics.FREE_PAGES, 0))}"
        f"  cached {last.get('cached_pages', '—')}"
        f"  pinned {last.get('pinned_pages', '—')}"
        f"  step {last.get('step', '—')}"
        + (f"  step_ms {last.get('step_ms'):.2f}"
           if isinstance(last.get("step_ms"), (int, float)) else ""))

    rows = []
    for label, name in _LATENCY_ROWS:
        h = hists.get(name)
        if h and h.get("count"):
            rows.append(f"{label} p50 {_fmt_s(snapshot_percentile(h, .50))}"
                        f"/p95 {_fmt_s(snapshot_percentile(h, .95))}"
                        f"/p99 {_fmt_s(snapshot_percentile(h, .99))}")
    lines.append("latency      " + ("  ".join(rows) if rows
                                    else "(no requests observed)"))
    lifecycle = "  ".join(
        f"{name.split('_', 2)[-1].rsplit('_total', 1)[0]} "
        f"{counters.get(name, 0)}" for name in _SERVING_COUNTERS)
    hb = readiness.get("heartbeat_age_s")
    lines.append("lifecycle    " + lifecycle
                 + (f"  hb_age {hb}s" if hb is not None else ""))

    # warm-restart row: only when the AOT cache / snapshot restore has
    # anything to say (a cold-configured server keeps the screen short)
    aot_hits = counters.get(obs_metrics.AOT_HITS, 0)
    aot_miss = counters.get(obs_metrics.AOT_MISSES, 0)
    warm = counters.get(obs_metrics.RESTART_WARM_PREFIXES, 0)
    if aot_hits or aot_miss or warm:
        saved = counters.get(obs_metrics.AOT_SAVED_SECONDS, 0.0)
        lines.append(
            f"warm restart aot hits {int(aot_hits)}"
            f"  misses {int(aot_miss)}"
            f"  compile_s_saved {saved:.1f}"
            f"  warm_prefixes {int(warm)}"
            f"  cache_entries {int(gauges.get(obs_metrics.AOT_ENTRIES, 0))}")

    # KV-tier row: only once the tier store has seen traffic (tiering
    # off or idle keeps the screen short)
    tier = _kvtier_row(counters, gauges)
    if tier:
        lines.append(tier)

    receipt = _receipt_row(status)
    if receipt:
        lines.append(receipt)

    faults = [e for e in (debug.get("recent_logs") or ())
              if e.get("level") in ("error", "warning")][-4:]
    lines.append("last faults" + ("  (none)" if not faults else ""))
    for e in faults:
        extra = e.get("error") or ""
        lines.append(f"  {e.get('ts', '')} [{e.get('level')}] "
                     f"{e.get('event')} {extra}"[:100])
    return "\n".join(lines) + "\n"


def _kvtier_row(counters: dict, gauges: dict) -> str | None:
    """The hierarchical-KV-tier line (inference/tpu/kv_tiers.py), or
    None while the store has no story to tell.  Works off whatever
    registry the screen's /statusz carried — the engine's own for a
    single server, the replica-merged one for a dp set."""
    spills = counters.get(obs_metrics.KVTIER_SPILLS, 0)
    promos = counters.get(obs_metrics.KVTIER_PROMOTIONS, 0)
    recomputes = counters.get(obs_metrics.KVTIER_RECOMPUTES, 0)
    integrity = counters.get(obs_metrics.KVTIER_INTEGRITY_FAILURES, 0)
    host = gauges.get(obs_metrics.KVTIER_HOST_PAGES, 0)
    disk = gauges.get(obs_metrics.KVTIER_DISK_PAGES, 0)
    queue = gauges.get(obs_metrics.KVTIER_QUEUE_DEPTH, 0)
    if not (spills or promos or recomputes or host or disk):
        return None
    return (f"kv tiers     host {int(host)}p  disk {int(disk)}p"
            f"  queue {int(queue)}  spills {int(spills)}"
            f"  promotions {int(promos)}  recomputes {int(recomputes)}"
            f"  integrity_fail {int(integrity)}")


def _receipt_row(status: dict) -> str | None:
    """The reproducibility-receipt line (obs/receipts.py), or None when
    the endpoint carries no provenance yet.  A router's /statusz brings
    the fleet fingerprint map (fingerprint -> ready replica ids): one
    fingerprint renders as converged, more than one names the replicas
    off the plurality fingerprint — the ones a pinned tenant would be
    withheld from.  A single server's readiness carries its own
    fingerprint + engine id."""
    fps = status.get("fingerprints")
    if isinstance(fps, dict) and fps:
        if len(fps) == 1:
            fp, ids = next(iter(fps.items()))
            return (f"receipts     fingerprint {str(fp)[:16]}  converged "
                    f"({len(ids)} replica(s))")
        groups = sorted(fps.items(), key=lambda kv: (-len(kv[1]), kv[0]))
        divergent = [str(rid) for _, ids in groups[1:] for rid in ids]
        return (f"receipts     SKEW: {len(fps)} fleet fingerprints  "
                f"divergent: {', '.join(divergent) or '?'}")
    readiness = status.get("readiness") or {}
    fp = readiness.get("fingerprint")
    if not fp:
        return None
    eng = readiness.get("engine_id")
    return (f"receipts     fingerprint {str(fp)[:16]}"
            + (f"  engine {eng}" if eng else ""))


#: router counters whose running totals headline the fleet view
_ROUTER_COUNTERS = (("routed", obs_metrics.ROUTER_ROUTED),
                    ("failovers", obs_metrics.ROUTER_FAILOVERS),
                    ("ejections", obs_metrics.ROUTER_EJECTIONS),
                    ("recoveries", obs_metrics.ROUTER_RECOVERIES),
                    ("sheds", obs_metrics.ROUTER_SHEDS))


def _tenant_names(counters: dict) -> list[str]:
    names = set()
    for key in counters:
        if key.startswith(obs_metrics.TENANT_REQUESTS + "{"):
            m = _TENANT_LABEL_RE.search(key)
            if m:
                names.add(m.group(1))
    return sorted(names)


def _merged_tenant_e2e(hists: dict) -> dict | None:
    """All tenants' router-side e2e histograms folded into one snapshot
    (same bounds by construction) — the fleet attainment/percentile
    source."""
    merged: dict | None = None
    for key, h in hists.items():
        if not key.startswith(obs_metrics.TENANT_E2E + "{") or not h:
            continue
        if merged is None:
            merged = {"buckets": [[b, c] for b, c in h["buckets"]],
                      "inf": h.get("inf", 0), "sum": h.get("sum", 0.0),
                      "count": h.get("count", 0)}
        else:
            for row, (_, c) in zip(merged["buckets"], h["buckets"]):
                row[1] += c
            merged["inf"] += h.get("inf", 0)
            merged["sum"] += h.get("sum", 0.0)
            merged["count"] += h.get("count", 0)
    return merged


def render_router_screen(status: dict, prev_counters: dict | None,
                         dt: float, target: str,
                         slo_e2e_s: float | None = None) -> str:
    """The federated fleet view from a router's /statusz body: the
    router's own counters headline, fleet-load + per-tenant + admin
    (autoscaler) rows, one row per replica underneath."""
    metrics = status.get("metrics", {})
    counters = metrics.get("counters", {})
    gauges = metrics.get("gauges", {})
    hists = metrics.get("histograms", {})
    replicas = status.get("replicas") or []
    ready_n = sum(1 for r in replicas
                  if r.get("ready") and r.get("state") == "healthy")
    lines = [f"== reval_tpu watch · {target} · ROUTER · "
             f"{status.get('model', '?')} · {ready_n}/{len(replicas)} "
             f"replicas ready · {time.strftime('%H:%M:%S')} =="]

    name = obs_metrics.ROUTER_REQUESTS
    cur = counters.get(name, 0)
    if prev_counters is None or dt <= 0:
        rate = "req/s —"
    else:
        rate = f"req/s {max(0.0, (cur - prev_counters.get(name, 0)) / dt):.1f}"
    lines.append(f"fleet        {rate}  requests {int(cur)}  "
                 + "  ".join(f"{label} {int(counters.get(m, 0))}"
                             for label, m in _ROUTER_COUNTERS))
    ring = status.get("ring") or {}
    affinity = status.get("affinity") or {}
    lines.append(f"ring         {len(ring.get('members') or ())} members × "
                 f"{ring.get('vnodes', '?')} vnodes"
                 f"  affinity_window {status.get('window_chars', '?')} chars"
                 + (f"  pinned_templates {len(affinity.get('placement') or ())}"
                    if affinity else ""))

    # fleet load: goodput counters + e2e attainment from the router's
    # labeled tenant histograms (THE shared CDF estimator)
    goodput = int(counters.get(obs_metrics.ROUTER_GOODPUT, 0))
    miss = int(counters.get(obs_metrics.ROUTER_SLO_MISS, 0))
    served = goodput + miss
    load = (f"load         goodput {goodput}  slo_miss {miss}  "
            f"ratio {goodput / served:.3f}" if served
            else "load         goodput 0  slo_miss 0  ratio —")
    merged = _merged_tenant_e2e(hists)
    if merged and merged["count"]:
        load += (f"  e2e p95 {_fmt_s(snapshot_percentile(merged, .95))}"
                 f"/p99 {_fmt_s(snapshot_percentile(merged, .99))}")
        if slo_e2e_s:
            load += (f"  attainment(e2e≤{slo_e2e_s:g}s) "
                     f"{snapshot_fraction_le(merged, slo_e2e_s) * 100:.1f}%")
    lines.append(load)

    # per-tenant QoS: requests, shed rate over the refresh interval,
    # router-side e2e p95
    tenants = _tenant_names(counters)
    for tenant in tenants:
        req_key = f'{obs_metrics.TENANT_REQUESTS}{{tenant="{tenant}"}}'
        shed_key = f'{obs_metrics.TENANT_SHEDS}{{tenant="{tenant}"}}'
        e2e_key = f'{obs_metrics.TENANT_E2E}{{tenant="{tenant}"}}'
        reqs = int(counters.get(req_key, 0))
        sheds = int(counters.get(shed_key, 0))
        if prev_counters is not None and dt > 0:
            shed_rate = max(0.0, (counters.get(shed_key, 0)
                                  - prev_counters.get(shed_key, 0)) / dt)
            shed_txt = f"{shed_rate:.1f}/s"
        else:
            shed_txt = "—"
        h = hists.get(e2e_key)
        p95 = (_fmt_s(snapshot_percentile(h, .95))
               if h and h.get("count") else "—")
        lines.append(f"tenant       {tenant:<16} requests {reqs:>6}  "
                     f"sheds {sheds:>5} ({shed_txt})  e2e p95 {p95}")
    if not tenants:
        lines.append("tenant       (no tenant traffic observed)")

    # fleet-wide KV tiers (counters arrive pre-merged when the statusz
    # body federates replica registries)
    tier = _kvtier_row(counters, gauges)
    if tier:
        lines.append(tier)

    receipt = _receipt_row(status)
    if receipt:
        lines.append(receipt)

    # the admin action log tail: drains/rejoins/resizes with the
    # caller's reason — a live autoscaler's story reads right here
    admin_log = status.get("admin_log") or []
    lines.append("autoscaler " + ("  (no admin actions)"
                                  if not admin_log else ""))
    for entry in admin_log[-4:]:
        ts = time.strftime("%H:%M:%S", time.localtime(entry.get("ts", 0)))
        reason = entry.get("reason") or ""
        lines.append(f"  {ts} {entry.get('action', '?'):<16} "
                     f"{entry.get('replica', '?'):<18} {reason}"[:100])

    lines.append(f"replicas     {'id':<18} {'state':<10} {'ready':<6} "
                 f"{'inflight':>8} {'strikes':>8}  last_error")
    for rep in replicas:
        err = (rep.get("last_error") or "")[:40]
        lines.append(f"             {str(rep.get('id', '?')):<18} "
                     f"{str(rep.get('state', '?')):<10} "
                     f"{('yes' if rep.get('ready') else 'NO'):<6} "
                     f"{rep.get('inflight', 0):>8} "
                     f"{rep.get('fails', 0):>8}  {err}")
    if not replicas:
        lines.append("             (no replicas registered)")
    return "\n".join(lines) + "\n"


def run_watch(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="reval_tpu watch",
        description="Live console over a serving endpoint "
                    "(/statusz + /debugz)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=3000)
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period, seconds (default 2)")
    parser.add_argument("--iterations", type=int, default=None,
                        help="stop after N refreshes (default: forever)")
    parser.add_argument("--no-clear", action="store_true",
                        help="append screens instead of clearing (pipes, "
                             "logs, tests)")
    parser.add_argument("--slo-e2e", type=float, default=None,
                        help="router view: e2e SLO target seconds — the "
                             "fleet-load row reports attainment against it")
    args = parser.parse_args(argv)
    base = f"http://{args.host}:{args.port}"
    target = f"{args.host}:{args.port}"
    prev_counters: dict | None = None
    prev_t = time.monotonic()
    n = 0
    try:
        while args.iterations is None or n < args.iterations:
            if n:
                time.sleep(args.interval)
            n += 1
            try:
                status = _fetch_json(f"{base}/statusz")
                # a fleet router has no engine, hence no /debugz — its
                # /statusz self-identifies and gets the federated view
                debug = ({} if status.get("router")
                         else _fetch_json(f"{base}/debugz"))
            except (urllib.error.URLError, TimeoutError, ConnectionError,
                    json.JSONDecodeError, OSError) as exc:
                if not args.no_clear:
                    print(CLEAR, end="")
                print(f"== reval_tpu watch · {target} · UNREACHABLE · "
                      f"{time.strftime('%H:%M:%S')} ==\n  {exc!r}\n"
                      f"  (retrying every {args.interval:g}s)")
                continue
            now = time.monotonic()
            if status.get("router"):
                screen = render_router_screen(status, prev_counters,
                                              now - prev_t, target,
                                              slo_e2e_s=args.slo_e2e)
            else:
                screen = render_screen(status, debug, prev_counters,
                                       now - prev_t, target)
            prev_counters = dict(
                status.get("metrics", {}).get("counters", {}))
            prev_t = now
            if not args.no_clear:
                print(CLEAR, end="")
            print(screen, end="", flush=True)
    except KeyboardInterrupt:
        pass
    return 0
