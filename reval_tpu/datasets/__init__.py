"""DREval benchmark datasets: constants, loaders, ClassEval hooks."""

from .dreval import (
    ClassEvalHooks,
    DREvalDataset,
    Families,
    MAX_INPUTS,
    SPLIT_FILES,
    data_dir,
    family_of,
    resolve_split,
)

__all__ = [
    "ClassEvalHooks",
    "DREvalDataset",
    "Families",
    "MAX_INPUTS",
    "SPLIT_FILES",
    "data_dir",
    "family_of",
    "resolve_split",
]
