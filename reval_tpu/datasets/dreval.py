"""DREval dataset constants, loaders and ClassEval test-class hooks.

Capability parity with the reference dataset layer (dataset.py:1-56) plus
the fixes SURVEY §2.10 calls for: split selection is explicit configuration
(no hard-coded data paths) and lookups are indexed dictionaries instead of
linear scans (evaluation.py:90-94).
"""

from __future__ import annotations

import ast
import json
import unittest
from dataclasses import dataclass, field
from pathlib import Path

__all__ = [
    "Families",
    "MAX_INPUTS",
    "SPLIT_FILES",
    "ClassEvalHooks",
    "DREvalDataset",
    "data_dir",
    "family_of",
    "resolve_split",
]


class Families:
    """Benchmark-index ranges per source dataset (reference dataset.py:45-52)."""

    HUMANEVAL_START = 0
    HUMANEVAL_END = 84
    CLASSEVAL_START = 85
    CLASSEVAL_END = 153
    MBPP_START = 154
    MBPP_END = 654
    MATHQA_START = 655
    MATHQA_END = 2583

    # MBPP's upstream `test` split starts at task_id 11; MathQA is 0-based.
    MBPP_TASK_ID_OFFSET = 11


# Cap on inputs evaluated per benchmark item (compute budget;
# reference dataset.py:54-56).
MAX_INPUTS = 5

VALID_FAMILIES = ("humaneval", "classeval", "mbpp", "mathqa")


def family_of(idx: int) -> str:
    """Which source dataset a DREval index belongs to."""
    if Families.HUMANEVAL_START <= idx <= Families.HUMANEVAL_END:
        return "humaneval"
    if Families.CLASSEVAL_START <= idx <= Families.CLASSEVAL_END:
        return "classeval"
    if Families.MBPP_START <= idx <= Families.MBPP_END:
        return "mbpp"
    if Families.MATHQA_START <= idx <= Families.MATHQA_END:
        return "mathqa"
    raise ValueError(f"invalid DREval index: {idx}")


def data_dir() -> Path:
    return Path(__file__).resolve().parent.parent / "data"


# split name -> (data file, tasks file).  Explicit, overridable per run —
# the reference hard-coded these (evaluation.py:60-65).
SPLIT_FILES: dict[str, tuple[str, str]] = {
    "main": ("DREval_data.jsonl", "DREval_tasks.jsonl"),
    "humaneval_classeval": (
        "DREval_data_humaneval_classeval.jsonl",
        "DREval_tasks_humaneval_classeval.jsonl",
    ),
    "mbpp": ("DREval_data_mbpp.black.jsonl", "DREval_tasks_mbpp.black.jsonl"),
    "mbpp_raw": ("DREval_data_mbpp.jsonl", "DREval_tasks_mbpp.jsonl"),
    "mathqa": ("DREval_data_mathqa.black.jsonl", "DREval_tasks_mathqa.black.jsonl"),
}

# Which split file a dataset family lives in by default.
_DEFAULT_SPLIT_FOR_FAMILY = {
    "humaneval": "main",
    "classeval": "main",
    "mbpp": "mbpp",
    "mathqa": "mathqa",
}


def resolve_split(dataset: str, split: str | None = None) -> tuple[Path, Path]:
    """Map (dataset family, optional explicit split) to concrete file paths."""
    assert dataset in VALID_FAMILIES, f"dataset must be one of {VALID_FAMILIES}"
    split = split or _DEFAULT_SPLIT_FOR_FAMILY[dataset]
    data_file, tasks_file = SPLIT_FILES[split]
    base = data_dir()
    return base / data_file, base / tasks_file


def _read_jsonl(path: Path) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@dataclass
class DREvalDataset:
    """Indexed view over one (data, tasks) split pair."""

    data_path: Path
    tasks_path: Path
    by_idx: dict[int, dict] = field(default_factory=dict)
    task_rows: list[dict] = field(default_factory=list)

    @classmethod
    def load(cls, dataset: str, split: str | None = None, data_path=None, tasks_path=None) -> "DREvalDataset":
        if data_path is None or tasks_path is None:
            data_path, tasks_path = resolve_split(dataset, split)
        ds = cls(Path(data_path), Path(tasks_path))
        for row in _read_jsonl(ds.data_path):
            idx = int(str(row["task_id"]).rsplit("/", 1)[-1])
            ds.by_idx[idx] = row
        ds.task_rows = _read_jsonl(ds.tasks_path)
        return ds

    # -- per-item accessors ------------------------------------------------
    def row(self, idx: int) -> dict:
        return self.by_idx[idx]

    def code(self, idx: int) -> str:
        return self.row(idx)["code"]

    def entry_point(self, idx: int) -> str:
        return self.row(idx)["entry_point"]

    def inputs(self, idx: int) -> list[str]:
        return self.row(idx)["inputs"]

    def invocations(self, idx: int) -> list[str] | None:
        row = self.row(idx)
        # upstream data files spell it 'innvocations' (sic, SURVEY §2.23)
        return row.get("innvocations", row.get("invocations"))

    def test_code(self, idx: int) -> str | None:
        return self.row(idx).get("test")

    def iter_tasks(self, dataset: str):
        """Yield task rows whose index belongs to ``dataset``'s family."""
        for row in self.task_rows:
            if family_of(int(row["idx"])) == dataset:
                yield row


class ClassEvalHooks:
    """Hooks shaping ClassEval unittest classes for tracing.

    Equivalent of the reference hooks (dataset.py:5-42), reimplemented on
    AST source extraction so no temp files or ``inspect`` machinery are
    needed: :func:`postprocess` receives the raw test source alongside the
    class (see ``CodeSpace.load_test_classes``).
    """

    @staticmethod
    def name_pattern(test_cls_name: str, cls_name: str) -> bool:
        return test_cls_name.startswith(f"{cls_name}Test")

    @staticmethod
    def validation(cls: type) -> bool:
        return isinstance(cls, type) and issubclass(cls, unittest.TestCase)

    @staticmethod
    def postprocess(cls: type, test_code: str) -> type:
        """Keep only the first ``test*`` method, renamed ``dreval_test``.

        Also stows, for prompt construction:
        - ``fn.__source__``: the method's source segment,
        - ``fn.__input__``: its body with ``self.assert`` → ``assert``,
        - ``cls.__setup__``: source of ``setUp`` iff the class defines one
          itself (an inherited unittest stub must not leak into prompts).
        """
        test_methods = [k for k in cls.__dict__ if k.startswith("test")]
        assert test_methods, f"no test methods found in {cls.__name__}"
        first = test_methods[0]
        fn = getattr(cls, first)

        tree = ast.parse(test_code)
        method_src = None
        setup_src = None
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and node.name == cls.__name__:
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        if item.name == first:
                            method_src = ast.get_source_segment(test_code, item)
                        elif item.name == "setUp":
                            setup_src = ast.get_source_segment(test_code, item)
        assert method_src, f"source for {cls.__name__}.{first} not found"

        body_lines = method_src.split("\n")[1:]
        fn.__doc__ = cls.__doc__
        fn.__source__ = method_src
        fn.__input__ = "\n".join(l.replace("self.assert", "assert").lstrip() for l in body_lines)
        if setup_src is not None:
            cls.__setup__ = setup_src
        cls.dreval_test = fn
        for k in test_methods:
            delattr(cls, k)
        return cls
