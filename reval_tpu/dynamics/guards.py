"""Execution guards for running untrusted benchmark code.

Wall-clock timeout via ``SIGALRM`` and stdio capture with a write-only
buffer, in the spirit of the classic HumanEval harness (capability parity
with the reference guards at execution.py:1-49).  There is intentionally no
filesystem or network isolation here: ground truth requires executing the
benchmark programs in-process so the tracer can observe them.  Callers that
need stronger isolation should run the whole sandbox in a subprocess (see
``reval_tpu.tasks``).
"""

from __future__ import annotations

import contextlib
import io
import signal

__all__ = ["ExecTimeout", "time_limit", "swallow_io"]


class ExecTimeout(Exception):
    """Raised inside the guarded region when the time budget is exhausted."""


@contextlib.contextmanager
def time_limit(seconds: float):
    """Raise :class:`ExecTimeout` in the calling thread after ``seconds``.

    Uses ``signal.setitimer`` so fractional budgets work.  Main-thread only
    (a CPython ``signal`` restriction) — which is fine: ground-truth tracing
    must run on the main thread anyway for ``sys.settrace``.

    The timer is *periodic*, not one-shot: the exception raised by the
    handler can land in a context that swallows it — observed in practice
    with JAX's gc callback (``_xla_gc_callback``), where CPython treats the
    exception as unraisable and drops it.  A periodic timer retries until
    one raise lands in interruptible code; the finally-clause disarms it.
    """

    def _on_alarm(signum, frame):
        raise ExecTimeout(f"execution exceeded {seconds}s")

    previous = signal.signal(signal.SIGALRM, _on_alarm)
    retry = min(seconds, 1.0)
    signal.setitimer(signal.ITIMER_REAL, seconds, retry)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, previous)


class _WriteOnlyBuffer(io.StringIO):
    """A StringIO that refuses to be read while attached as stdin.

    Benchmark programs occasionally call ``input()``; letting that block or
    read captured output would corrupt the trace, so reads fail fast.
    """

    def read(self, *args, **kwargs):
        raise IOError("stdin is closed inside the sandbox")

    def readline(self, *args, **kwargs):
        raise IOError("stdin is closed inside the sandbox")

    def readlines(self, *args, **kwargs):
        raise IOError("stdin is closed inside the sandbox")

    def readable(self) -> bool:
        return False


class _redirect_stdin(contextlib._RedirectStream):
    _stream = "stdin"


@contextlib.contextmanager
def swallow_io():
    """Silence stdout/stderr and disconnect stdin for the guarded region."""
    sink = _WriteOnlyBuffer()
    with contextlib.redirect_stdout(sink), contextlib.redirect_stderr(sink), _redirect_stdin(sink):
        yield sink
