"""The sandbox: traced execution of one callable under guards.

Installs a ``sys.settrace`` hook that records, for every frame compiled with
the :data:`~reval_tpu.dynamics.factory.TRACE_FILENAME` sentinel, a snapshot
of the frame's locals on each ``line`` event plus ``return``/``exception``
events, into an :class:`~reval_tpu.dynamics.states.ExecutionTrace`.
Capability parity with the reference sandbox/tracer (dynamics.py:94-135,
406-446), instance-based instead of module-global so sandboxes are
re-entrant-safe and unit-testable in isolation.
"""

from __future__ import annotations

import sys
from copy import deepcopy
from time import monotonic
from types import BuiltinFunctionType, FrameType, FunctionType, ModuleType
from typing import Callable, Iterator

from .factory import TRACE_FILENAME
from .guards import ExecTimeout, swallow_io, time_limit
from .nil import Nil
from .states import ExecutionTrace

__all__ = ["Sandbox", "snapshot_locals"]

# Local values of these kinds are not snapshotted: they are either
# unserialisable or meaningless to compare (reference filter,
# dynamics.py:107-118).
_SKIPPED_TYPES = (ModuleType, FunctionType, BuiltinFunctionType)


def snapshot_locals(frame_locals: dict) -> dict:
    """Deep-copy the serialisable subset of a frame's locals."""
    snap = {}
    for name, value in frame_locals.items():
        if isinstance(value, _SKIPPED_TYPES) or isinstance(value, Iterator):
            continue
        try:
            snap[name] = deepcopy(value)
        except ExecTimeout:
            # The SIGALRM timeout may land while we are inside deepcopy;
            # it must propagate or the one-shot itimer never fires again
            # and the sandbox hangs forever.
            raise
        except Exception:
            # Un-deep-copyable values (open files, locks, …) are skipped
            # rather than crashing the trace.
            continue
    return snap


class Sandbox:
    """Runs one callable under tracing + io/time guards.

    ``fn.__doc__`` must hold the source of the code under test (the
    factories guarantee this); trace linenos are 0-indexed into it.

    After :meth:`run`, ``status`` is ``'ok'``, ``'timed out'`` or
    ``'exception: <msg>'`` and ``states`` holds the recorded trace.
    """

    def __init__(self, fn: Callable, timeout: float = 120.0):
        self.fn = fn
        self.timeout = timeout
        self.result = Nil
        self.status = ""
        self.states = ExecutionTrace()
        self._codelines = (fn.__doc__ or "").split("\n")
        self._deadline = float("inf")

    # -- trace hooks -------------------------------------------------------
    def _global_hook(self, frame: FrameType, event: str, arg):
        if event == "call" and frame.f_code.co_filename == TRACE_FILENAME:
            return self._local_hook
        return None

    def _local_hook(self, frame: FrameType, event: str, arg):
        lineno = frame.f_lineno - 1  # 0-indexed trace linenos
        if event == "line":
            # Second timeout layer: the SIGALRM raise can be swallowed if it
            # lands in an unraisable context (gc callbacks); the hook runs on
            # every traced line, which is a context the raise always escapes.
            if monotonic() > self._deadline:
                raise ExecTimeout(f"execution exceeded {self.timeout}s")
            self._record(lineno, "locals", snapshot_locals(frame.f_locals))
        elif event == "return":
            self._record(lineno, "return", arg)
        elif event == "exception":
            self._record(lineno, "exception", arg[0])
        return self._local_hook

    def _record(self, lineno: int, event: str, value):
        codeline = self._codelines[lineno] if 0 <= lineno < len(self._codelines) else ""
        self.states.record(lineno, event, value, codeline)

    # -- execution ---------------------------------------------------------
    def run(self, *args, **kwargs):
        """Execute ``fn(*args, **kwargs)`` traced; return (result, states)."""
        self.result = Nil
        self.status = ""
        self.states = ExecutionTrace()
        self._deadline = monotonic() + self.timeout

        try:
            with swallow_io():
                with time_limit(self.timeout):
                    sys.settrace(self._global_hook)
                    try:
                        self.result = self.fn(*args, **kwargs)
                    finally:
                        sys.settrace(None)
            self.status = "ok"
        except ExecTimeout:
            self.status = "timed out"
        except BaseException as exc:  # noqa: BLE001 — benchmark code may raise anything
            self.status = f"exception: {exc}"
        return self.result, self.states
