"""Ground-truth runtime dynamics: tracing real CPython execution.

This package is deliberately host-CPU pure Python — tracing the interpreter
is not accelerator work.  The TPU engine lives in ``reval_tpu.inference``.

Reference-API compatibility: users of the reference harness can keep writing
``FunctionFactory.create`` / ``ClassFactory.create`` / ``Sandbox`` /
``States`` / ``Nil``; they are thin wrappers over :class:`CodeSpace` and
:class:`ExecutionTrace`.
"""

from .factory import TRACE_FILENAME, CodeSpace
from .guards import ExecTimeout, swallow_io, time_limit
from .nil import Nil, NilType, is_nil
from .sandbox import Sandbox, snapshot_locals
from .states import ExecutionTrace, LineState, VarInterpreter

# Reference-familiar alias.
States = ExecutionTrace

__all__ = [
    "CodeSpace",
    "ExecutionTrace",
    "ExecTimeout",
    "FunctionFactory",
    "ClassFactory",
    "LineState",
    "Nil",
    "NilType",
    "Sandbox",
    "States",
    "TRACE_FILENAME",
    "VarInterpreter",
    "is_nil",
    "snapshot_locals",
    "swallow_io",
    "time_limit",
]


class FunctionFactory:
    """Reference-compatible facade over :class:`CodeSpace` for functions.

    Each call uses a fresh namespace; helper functions defined in the same
    ``code`` blob resolve through the function's ``__globals__``.
    """

    @staticmethod
    def create(fn_name: str, code: str):
        return CodeSpace().load_function(fn_name, code)

    @staticmethod
    def create_from_answer(generated: str, test_cls):
        # The predictor must compile in the namespace that holds the code
        # under test or its name references cannot resolve.
        space = getattr(test_cls, "__reval_space__", None) or CodeSpace()
        return space.attach_output_predictor(generated, test_cls)


class ClassFactory:
    """Reference-compatible facade over :class:`CodeSpace` for classes.

    Note: unlike :class:`FunctionFactory`, ClassEval flows need the class
    under test visible to its test code — use one :class:`CodeSpace` for
    both (`create` returns the class; pass the same space to
    ``load_test_classes``), or use these statics which share one space per
    call chain via the returned class's ``__reval_space__`` attribute.
    """

    @staticmethod
    def create(cls_name: str, code: str):
        space = CodeSpace()
        cls = space.load_class(cls_name, code)
        cls.__reval_space__ = space
        return cls

    @staticmethod
    def create_test_classes(cls_name, code, test_code, name_pattern, validation, postprocess=None):
        space = CodeSpace()
        space.load_class(cls_name, code)
        return space.load_test_classes(cls_name, code, test_code, name_pattern, validation, postprocess)
