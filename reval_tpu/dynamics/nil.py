"""The ``Nil`` sentinel.

``Nil`` means "no value was observed" — a line never executed, a variable
undefined at a probe point — and is distinct from ``None``, which programs
under test may legitimately produce.  (Capability parity with the reference
sentinel at dynamics.py:137-162.)

The singleton survives ``copy``, ``deepcopy`` and ``pickle`` round-trips:
all of them return the same object, so ``is Nil`` checks stay valid across
the deep-copied locals snapshots taken by the tracer.
"""

__all__ = ["Nil", "NilType", "is_nil"]


class NilType:
    """Singleton class for :data:`Nil`.  Do not instantiate elsewhere."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    # Keep the singleton a singleton under every duplication protocol.
    def __reduce__(self):
        return (NilType, ())

    def __copy__(self):
        return self

    def __deepcopy__(self, memo):
        return self

    def __repr__(self):
        return "Nil"

    def __str__(self):
        return "Nil"

    def __bool__(self):
        return False


Nil = NilType()


def is_nil(value) -> bool:
    return value is Nil
