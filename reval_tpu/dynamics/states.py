"""Recorded execution states and queries over them.

A :class:`LineState` is the snapshot taken when the tracer reached one source
line (0-indexed); an :class:`ExecutionTrace` is the ordered sequence of those
snapshots for one sandboxed call, with the query API the task layer scores
against.  Capability parity with the reference state model (dynamics.py:225-404)
including its *after* semantics: ``sys.settrace`` fires **before** a line runs,
so the values produced *by* line L are read from the trace entry that follows
each occurrence of L.
"""

from __future__ import annotations

import ast
import itertools
from typing import Any

from .nil import Nil

__all__ = ["LineState", "ExecutionTrace", "VarInterpreter"]


class LineState:
    """Snapshot of one visit to one (0-indexed) source line."""

    __slots__ = ("lineno", "code", "locals", "return_value", "exception")

    def __init__(self, lineno: int, code: str):
        self.lineno = lineno
        self.code = code
        self.locals: dict[str, Any] = {}
        self.return_value = Nil
        self.exception = Nil

    def merge_event(self, event: str, value) -> None:
        """Fold a tracer event ('locals' | 'return' | 'exception') in."""
        if event == "locals":
            self.locals = value
        elif event == "return":
            self.return_value = value
        elif event == "exception":
            self.exception = value
        else:
            raise ValueError(f"unknown trace event {event!r}")

    def get_local(self, var: str):
        return self.locals.get(var, Nil)

    def get_attr(self, var: str, attr: str):
        obj = self.locals.get(var, Nil)
        if obj is Nil or not hasattr(obj, attr):
            return Nil
        return getattr(obj, attr)

    def get_subscript(self, var: str, key_expr: str):
        obj = self.locals.get(var, Nil)
        if obj is Nil:
            return Nil
        try:
            return obj[ast.literal_eval(key_expr)]
        except (TypeError, KeyError, IndexError, ValueError, SyntaxError):
            return Nil

    def to_json(self) -> dict:
        doc: dict[str, Any] = {"lineno": self.lineno, "locals": {}}
        for name, value in self.locals.items():
            doc["locals"][name] = list(value) if isinstance(value, set) else value
        if self.return_value is not Nil:
            doc["return"] = self.return_value
        if self.exception is not Nil:
            exc = self.exception
            doc["exception"] = exc.__name__ if isinstance(exc, type) else exc.__class__.__name__
        return doc

    def __repr__(self):
        return (
            f"LineState(lineno={self.lineno}, locals={self.locals!r}, "
            f"return={self.return_value!r}, exception={self.exception!r})"
        )


class ExecutionTrace:
    """Ordered line-state sequence for one sandboxed call, plus queries.

    Also exported as ``States`` for users coming from the reference API.
    """

    def __init__(self):
        self._states: list[LineState] = []
        # lineno -> positions in self._states, kept in order.  The reference
        # linear-scans per query (dynamics.py:325,343); an index keeps query
        # cost O(visits) instead of O(trace length).
        self._by_line: dict[int, list[int]] = {}

    # -- construction -----------------------------------------------------
    def record(self, lineno: int, event: str, value, codeline: str) -> None:
        """Append an event, merging consecutive events on the same line.

        The tracer emits 'locals' then possibly 'return'/'exception' for the
        same visit; those belong to one :class:`LineState`.
        """
        if self._states and self._states[-1].lineno == lineno:
            self._states[-1].merge_event(event, value)
            return
        state = LineState(lineno, codeline)
        state.merge_event(event, value)
        self._by_line.setdefault(lineno, []).append(len(self._states))
        self._states.append(state)

    # -- container protocol ----------------------------------------------
    def __len__(self):
        return len(self._states)

    def __getitem__(self, i: int) -> LineState:
        return self._states[i]

    def __iter__(self):
        return iter(self._states)

    def __repr__(self):
        return f"ExecutionTrace({self._states!r})"

    # -- queries (linenos are 0-indexed throughout) -----------------------
    @property
    def trace(self) -> list[int]:
        """The executed line sequence."""
        return [s.lineno for s in self._states]

    def get_coverage(self, lineno: int) -> bool:
        return lineno in self._by_line

    def get_next_line(self, lineno: int) -> set[int]:
        """All observed successor lines of ``lineno``; -1 marks trace end.

        Returns ``{-1}`` when the line was never executed (reference
        convention, dynamics.py:322-323).
        """
        positions = self._by_line.get(lineno)
        if not positions:
            return {-1}
        succ: set[int] = set()
        for i in positions:
            succ.add(self._states[i + 1].lineno if i + 1 < len(self._states) else -1)
        return succ

    def states_before(self, lineno: int) -> list[LineState]:
        """Snapshots taken on arrival at ``lineno`` (pre-execution values)."""
        return [self._states[i] for i in self._by_line.get(lineno, [])]

    def states_after(self, lineno: int) -> list[LineState]:
        """Snapshots reflecting the world *after* each visit to ``lineno``.

        Because the tracer fires before a line executes, that is the next
        trace entry — except when the visit is the final entry (a return or
        exception), whose own snapshot already holds the post-line values.
        """
        out = []
        for i in self._by_line.get(lineno, []):
            out.append(self._states[i + 1] if i + 1 < len(self._states) else self._states[i])
        return out

    def _collect_after(self, lineno: int, getter) -> list | type(Nil):
        found = []
        for state in self.states_after(lineno):
            value = getter(state)
            if value is not Nil:
                found.append(value)
        return found if found else Nil

    def get_local(self, lineno: int, var: str):
        """Values of ``var`` after each visit to ``lineno`` (a list across
        loop iterations), or ``Nil`` if never executed / never defined."""
        return self._collect_after(lineno, lambda s: s.get_local(var))

    def get_attr(self, lineno: int, var: str, attr: str):
        return self._collect_after(lineno, lambda s: s.get_attr(var, attr))

    def get_subscript(self, lineno: int, var: str, key_expr: str):
        return self._collect_after(lineno, lambda s: s.get_subscript(var, key_expr))

    def interpret_var(self, lineno: int, expr: str):
        """Evaluate a probe expression (``x``, ``self.a``, ``arr[0]``, …)
        against the recorded states.  See :class:`VarInterpreter`."""
        return VarInterpreter(lineno, expr, self).get()

    def get_return(self, lineno: int):
        values = [
            s.return_value
            for s in (self._states[i] for i in self._by_line.get(lineno, []))
            if s.return_value is not Nil
        ]
        assert len(values) <= 1, f"multiple return values recorded for line {lineno}: {values}"
        return values[0] if values else Nil

    def get_exception(self, lineno: int):
        values = [
            s.exception
            for s in (self._states[i] for i in self._by_line.get(lineno, []))
            if s.exception is not Nil
        ]
        assert len(values) <= 1, f"multiple exceptions recorded for line {lineno}: {values}"
        return values[0] if values else Nil

    def to_json(self) -> list[dict]:
        return [s.to_json() for s in self._states]


class VarInterpreter:
    """Evaluates a restricted expression grammar against a trace.

    Supported AST shapes: constants, names, attribute access, subscripts and
    tuples (reference grammar, dynamics.py:170-207).  Because a line may be
    visited many times, every sub-expression evaluates to a *list* of
    candidate values; subscripts/tuples take cartesian products across their
    operands' candidates.  ``Nil`` propagates, and any internal error
    collapses to ``Nil``.
    """

    def __init__(self, lineno: int, expr: str, trace: ExecutionTrace):
        self.lineno = lineno
        self.expr = expr
        self.trace = trace

    def get(self):
        try:
            return self._analyze()
        except Exception:
            return Nil

    def _analyze(self):
        if not self.trace.get_coverage(self.lineno):
            return Nil
        tree = ast.parse(self.expr, mode="eval")
        return self._eval(tree.body)

    def _eval(self, node: ast.expr):
        if isinstance(node, ast.Constant):
            return [node.value]
        if isinstance(node, ast.Name):
            return self.trace.get_local(self.lineno, node.id)
        if isinstance(node, ast.Attribute):
            candidates = self._eval(node.value)
            if candidates is Nil:
                return Nil
            found = [getattr(obj, node.attr) for obj in candidates if hasattr(obj, node.attr)]
            return found if found else Nil
        if isinstance(node, ast.Subscript):
            containers = self._eval(node.value)
            keys = self._eval(node.slice)
            if containers is Nil or keys is Nil:
                return Nil
            found = []
            for container, key in itertools.product(containers, keys):
                try:
                    found.append(container[key])
                except (TypeError, KeyError, IndexError):
                    pass
            return found if found else Nil
        if isinstance(node, ast.Tuple):
            parts = [self._eval(elt) for elt in node.elts]
            if any(p is Nil for p in parts):
                return Nil
            return list(itertools.product(*parts))
        raise ValueError(f"unsupported probe expression node: {ast.dump(node)}")
