"""Materialising benchmark code into traceable Python objects.

A :class:`CodeSpace` is an isolated namespace for one benchmark item: the
function or class under test, its helpers, and (for ClassEval) the unittest
test classes that drive it.  Code under test is compiled with the sentinel
:data:`TRACE_FILENAME` so the sandbox tracer knows which frames to record;
test-driver code is compiled under a distinct filename so only the code
under test is traced (capability parity with the reference factories at
dynamics.py:15-92, without its module-global namespace pollution).
"""

from __future__ import annotations

from typing import Callable, Type

__all__ = ["TRACE_FILENAME", "CodeSpace"]

# co_filename sentinel marking frames the tracer records.
TRACE_FILENAME = "<reval:sandbox>"
# co_filename for driver/test code that must NOT be traced.
DRIVER_FILENAME = "<reval:driver>"


class CodeSpace:
    """One namespace holding a benchmark item's executable objects."""

    def __init__(self):
        self.ns: dict = {"__name__": "__reval_sandbox__", "__builtins__": __builtins__}

    # -- loading code under test (traced) ---------------------------------
    def _exec_traced(self, code: str) -> None:
        exec(compile(code, TRACE_FILENAME, "exec"), self.ns)

    def load_function(self, fn_name: str, code: str) -> Callable:
        """Compile ``code`` and return the named function.

        The full source is attached as ``__doc__`` (and ``__source__``) —
        the sandbox maps trace linenos back to source lines through it.
        """
        self._exec_traced(code)
        fn = self.ns[fn_name]
        assert callable(fn), f"{fn_name!r} is not callable"
        fn.__doc__ = code
        fn.__source__ = code
        return fn

    def load_class(self, cls_name: str, code: str) -> Type:
        """Compile ``code`` and return the named class (no instantiation)."""
        self._exec_traced(code)
        cls = self.ns[cls_name]
        assert isinstance(cls, type), f"{cls_name!r} is not a class"
        cls.__doc__ = code
        return cls

    # -- loading test-driver code (not traced) -----------------------------
    def load_test_classes(
        self,
        cls_name: str,
        code: str,
        test_code: str,
        name_pattern: Callable[[str, str], bool],
        validation: Callable[[Type], bool],
        postprocess: Callable[[Type, str], Type] | None = None,
    ) -> list[Type]:
        """Compile unittest driver code and return its matching test classes.

        ``name_pattern(test_cls_name, cls_name)`` selects classes by name,
        ``validation(cls)`` filters (e.g. unittest.TestCase subclasses), and
        ``postprocess(cls, test_code)`` may rewrite each class — it receives
        the raw test source so method sources can be extracted via AST
        without tempfile/inspect machinery.  Matching classes get the code
        under test as ``__doc__`` so sandboxes can index its source lines.
        """
        before = set(self.ns)
        exec(compile(test_code, DRIVER_FILENAME, "exec"), self.ns)
        found = []
        # Iterate in definition order; include pre-existing names too in case
        # the driver re-binds them (mirrors the reference's global scan).
        for name, obj in list(self.ns.items()):
            if name.startswith("__") and name not in before:
                continue
            if not isinstance(obj, type):
                continue
            if not name_pattern(name, cls_name) or not validation(obj):
                continue
            obj.__doc__ = code
            # Remember which namespace holds the code under test so later
            # phases (e.g. output-prediction scoring) can compile model
            # answers where the tested names resolve.
            obj.__reval_space__ = self
            if postprocess is not None:
                postprocess(obj, test_code)
            found.append(obj)
        return found

    def attach_output_predictor(self, generated: str, test_cls: Type) -> Callable:
        """Wrap a model-completed assertion block as a bound test method.

        The generated snippet uses bare ``assertEqual(...)`` style (per the
        output-task prompt); it is indented into a ``dreval_output_pred``
        method body (triple-quoted blocks keep their indentation) and the
        ``assert`` prefix is rewritten to ``self.assert`` so unittest
        helpers resolve.  The method is attached to ``test_cls`` and
        returned; calling it raises iff the model's assertions fail.
        """
        lines = ["def dreval_output_pred(self):"]
        in_string_block = False
        for line in generated.split("\n"):
            lines.append(line if in_string_block else "\t" + line)
            if "'''" in line or '"""' in line:
                in_string_block = not in_string_block
        method_src = "\n".join(lines).replace("assert", "self.assert")
        fn = self.load_function("dreval_output_pred", method_src)
        fn.__doc__ = test_cls.__doc__
        setattr(test_cls, "dreval_output_pred", fn)
        return fn

    # -- helpers -----------------------------------------------------------
    def eval_invocation(self, expr: str):
        """Evaluate an input/invocation expression inside this namespace."""
        return eval(compile(expr, DRIVER_FILENAME, "eval"), self.ns)

    def exec_driver(self, code: str) -> None:
        """Execute arbitrary driver code (e.g. a completed assert block)."""
        exec(compile(code, DRIVER_FILENAME, "exec"), self.ns)


