"""PipelinedTPUEngine: the static engine over a pipeline-parallel mesh.

Same generation contract as :class:`TPUEngine` (bucketed batches, chunked
decode, post-detokenisation stop strings) with the model step swapped for
the ``pp``-sharded GPipe/token-ring schedules in
``reval_tpu.parallel.pipeline``.  Use when the layer stack does not fit one
chip even tp-sharded (BASELINE.json configs[4]: CodeLlama-70B on v5p-16;
the reference reached such models only through vLLM tensor parallelism,
reference inference.py:92).

The mesh may carry both ``pp`` and ``tp`` axes: the pipeline shard_map is
manual over ``pp`` only, so tp sharding composes automatically (GSPMD
partitions each stage's layer compute tp-wide).
"""

from __future__ import annotations

from functools import partial

import jax

from ...analysis.jitcheck import tracked_jit
from ...models import ModelConfig
from ...parallel.mesh import mesh_axis_sizes
from ...parallel.pipeline import (
    pipeline_decode_chunk,
    pipeline_prefill,
    shard_params_pp,
)
from .engine import TPUEngine

__all__ = ["PipelinedTPUEngine"]


class PipelinedTPUEngine(TPUEngine):
    # mesh: axes=(pp)
    def __init__(self, params, cfg: ModelConfig, tokenizer, *,
                 batch_size: int = 8, max_seq_len: int = 8192, mesh,
                 n_micro: int | None = None, seed: int = 0):
        pp = mesh_axis_sizes(mesh).get("pp", 1)
        if pp < 2:
            raise ValueError("PipelinedTPUEngine needs a mesh with pp >= 2")
        # prefill microbatch count: more microbatches shrink the GPipe
        # bubble ((P-1)/(M+P-1)); 2*pp halves it vs M=pp while keeping
        # microbatches MXU-sized.  Decode always rings with exactly pp.
        self.n_micro = n_micro if n_micro is not None else min(2 * pp, batch_size)
        if batch_size % self.n_micro or batch_size % pp:
            raise ValueError(
                f"batch_size {batch_size} must divide by n_micro="
                f"{self.n_micro} and pp={pp}")
        if cfg.num_layers % pp:
            raise ValueError(
                f"pp={pp} must evenly divide num_layers={cfg.num_layers}")
        from ...parallel.sharding import resolve_moe_impl

        cfg = resolve_moe_impl(cfg, mesh)
        super().__init__(params, cfg, tokenizer, batch_size=batch_size,
                         max_seq_len=max_seq_len, mesh=None, seed=seed)
        self.mesh = mesh
        self._pp = pp
        self.params = shard_params_pp(params, cfg, mesh)
        # born-sharded buffers (advisor round-2): the KV cache's layer dim
        # is pp-sharded (matching pipeline_prefill's in_specs), so no
        # full-size [L, B+mb, S, H_kv, D] transient ever lands on one
        # stage's chip; tokens/pad replicate (the shard_map takes them P())
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._input_sharding = NamedSharding(mesh, P())
        self._cache_sharding = NamedSharding(mesh, P("pp"))
        # rebind the entries the base ctor tracked — keep _jit_trackers
        # pointing at the LIVE wrappers, or the pp path's compiles would
        # vanish from jit_counters()/reval_jit_* while the API still
        # reports the discarded base-engine trackers
        # out_shardings pins the returned cache to its declared pp
        # placement — XLA propagation is otherwise free to pick another
        # layout (the mechanism the shardcheck guard caught on the
        # paged/static engines), and a respec here lands the full
        # [L, B+mb, S, H_kv, D] cache on one stage's chip
        # jit-entry: pp.prefill bucketed=(rows, tokens) warmup=16
        self._jit_prefill = tracked_jit(
            "pp.prefill",
            jax.jit(partial(
                pipeline_prefill, cfg=cfg, mesh=mesh, n_micro=self.n_micro),
                out_shardings=(None, self._cache_sharding)),
            registry=lambda: self.stats.registry, warmup=16)
        # jit-entry: pp.decode_chunk static=(steps, filtered) bucketed=(tokens) warmup=48
        self._jit_decode_chunk = tracked_jit(
            "pp.decode_chunk",
            jax.jit(
                partial(self._pp_decode_chunk, cfg=cfg, mesh=mesh),
                static_argnames=("steps", "filtered"),
                donate_argnames=("cache",),
                out_shardings=(None, self._cache_sharding, None)),
            registry=lambda: self.stats.registry, warmup=48)
        # runtime mesh discipline (analysis/shardcheck.py): the base
        # ctor saw mesh=None, so guard the rebound pp entries here — the
        # KV cache's layer dim must stay pp-sharded through every chunk
        # (a respec would land a full [L, B+mb, S, H_kv, D] buffer on
        # one stage's chip, the exact transient pipelining exists to
        # avoid)
        from ...analysis.shardcheck import ShardGuard

        self._jit_prefill = ShardGuard(
            "pp.prefill", self._jit_prefill,
            registry=lambda: self.stats.registry,
            in_checks={"cache": self._cache_sharding},
            out_checks={1: self._cache_sharding})
        self._jit_decode_chunk = ShardGuard(
            "pp.decode_chunk", self._jit_decode_chunk,
            registry=lambda: self.stats.registry,
            in_checks={3: self._cache_sharding},
            out_checks={1: self._cache_sharding})
        self._jit_trackers = (self._jit_prefill, self._jit_decode_chunk)

    @classmethod
    def from_pretrained(cls, model_path: str, *, dtype: str = "bfloat16",
                        pp_size: int = 2, tp_size: int = 1,
                        batch_size: int = 8, max_seq_len: int = 8192,
                        tokenizer=None, seed: int = 0,
                        local_devices_only: bool = False,
                        n_micro: int | None = None) -> "PipelinedTPUEngine":
        from ...models import load_checkpoint
        from ...parallel import make_mesh
        from ...parallel.pipeline import pp_param_specs

        devices = jax.local_devices() if local_devices_only else None
        mesh = make_mesh(pp=pp_size, tp=tp_size, devices=devices)
        if dtype != "int8":
            # shard-direct: each host reads only its stages'/tp-slices' bytes
            from ...models import load_checkpoint_sharded

            params, cfg = load_checkpoint_sharded(model_path, mesh,
                                                  dtype=dtype,
                                                  specs_fn=pp_param_specs)
        else:
            params, cfg = load_checkpoint(model_path, dtype=dtype)
        if tokenizer is None:
            from .tokenizer import HFTokenizer

            tokenizer = HFTokenizer(model_path)
        return cls(params, cfg, tokenizer, batch_size=batch_size,
                   max_seq_len=max_seq_len, mesh=mesh, n_micro=n_micro,
                   seed=seed)

    def _cache_rows(self, b: int) -> int:
        # fill/drain scratch: one microbatch of rows past the real batch
        # (decode microbatches b/pp are the widest users of the slot)
        return b + b // self._pp

    @staticmethod
    def _pp_decode_chunk(params, first_token, pad_len, cache, start_pos,
                         temperature, key, top_k=None, top_p=None, *,
                         cfg, mesh, steps: int, filtered: bool = False):
        return pipeline_decode_chunk(
            params, cfg, first_token, pad_len, cache, start_pos,
            temperature, key, mesh, steps=steps,
            top_k=top_k, top_p=top_p, filtered=filtered)
